"""Fleet-wide observability plane: the router-side aggregation layer.

PRs 3–5 built a three-rung observability tower that is strictly
per-replica; PR 9's router made the fleet one *system* without making it
one *view*.  This module is the missing aggregation layer (the
Pipeline-Collector shape of cross-node performance accounting,
arXiv:1807.05733), four pillars, all read-only on the math:

- **Metrics federation** (:class:`ScrapeCache`,
  :func:`federated_exposition`): the router's poll loop scrapes each
  replica's ``/metrics`` (parsed strictly by
  ``obs.metrics.parse_exposition``); ``GET /fleet/metrics`` serves every
  per-replica series re-labeled ``{replica=...}`` plus *merged* families
  under an ``ict_fleet_``-prefixed rename — counters summed, the fixed
  log2-bucket latency histograms merged bucket-wise (identical bounds by
  construction, so the merge is exact), gauges max/sum by the
  :func:`gauge_merge_policy` table — built from ONE cache snapshot so the
  merged totals always equal the per-replica sums they sit next to.
  Scrape-staleness gauges (``ict_fleet_scrape_ok`` /
  ``ict_fleet_scrape_age_seconds``) make a wedged replica visible instead
  of silently stale: a dead replica's last good scrape keeps serving, its
  age keeps growing.
- **Cross-hop trace assembly** (:class:`TraceStore`): a bounded span
  store indexes the router's own placement/failover/terminal events under
  the adopted ``X-ICT-Trace`` id; ``GET /fleet/trace/<trace_id>``
  stitches one timeline — submit → placement → the serving replica's
  persisted per-job forensics (``GET /jobs/<id>/trace``, fetched lazily)
  → (failover → second replica) → done.  A dead hop's spans come from the
  best-effort pre-death **flight-ring cache** the poll loop keeps, so a
  failed-over job's partial telemetry survives the replica that produced
  it (the gap ROADMAP item 1 left open).
- **Incident bundles** (:func:`write_incident_bundle`): on death
  eviction, failover, or an observed audit-divergence/demotion the router
  snapshots its placement table, the registry, the replica's last good
  ``/metrics`` scrape, its cached flight ring, and (for job-scoped
  incidents) the stitched trace into ``<spool>/fleet-incidents/`` — same
  ``.part``-rename + bounded-retention discipline as
  ``obs.audit.write_repro_bundle``.
- **SLO & straggler detection** (:class:`StragglerDetector`): windowed
  per-replica p50 estimates off the scraped latency histograms; a replica
  whose p50 sits ``straggler_factor`` above the fleet median for
  ``straggler_polls`` consecutive polls is flagged
  (``ict_fleet_stragglers`` gauge, a flight/event record, and a placement
  de-prioritization penalty in the router's ranked-candidate scoring)
  and cleared once it recovers.  Per-tenant SLO burn counters
  (``ict_fleet_slo_burn_total{tenant}``) ride the WFQ admission path.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import threading
import time
import uuid

from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.obs.metrics import MetricFamily

#: Incident bundles kept per directory (oldest swept) — the
#: flight.MAX_DUMPS_KEPT rationale: a flapping replica must not fill the
#: router spool with one bundle per death/failover.
MAX_INCIDENTS_KEPT = 20

#: Bounds on the router-side span store: traces evicted LRU beyond
#: ``MAX_TRACES``; spans per trace capped (a pathological retry loop must
#: not grow one trace without bound).
MAX_TRACES = 1024
MAX_SPANS_PER_TRACE = 128

#: Substrings that flag a gauge family as a high-water/point-in-time fact
#: where summing across replicas would lie — merged with max instead.
#: Everything else (RSS, HBM in use, queue depths) merges additively.
GAUGE_MAX_HINTS = ("max", "peak", "last", "limit")


def merged_name(name: str) -> str:
    """The merged-family rename: ``ict_service_jobs_done`` ->
    ``ict_fleet_service_jobs_done``.  Renamed, not re-labeled: the same
    family cannot carry both ``{replica=...}`` per-replica series and an
    unlabeled fleet total without colliding in the exposition."""
    if name.startswith("ict_"):
        return "ict_fleet_" + name[len("ict_"):]
    return "fleet_" + name


def gauge_merge_policy(family_name: str) -> str:
    """``"max"`` or ``"sum"`` for one gauge family (the merge-policy
    table in docs/OBSERVABILITY.md "Fleet observability")."""
    lowered = family_name.lower()
    if any(hint in lowered for hint in GAUGE_MAX_HINTS):
        return "max"
    return "sum"


def merge_families(scrapes: dict[str, list[MetricFamily]],
                   ) -> list[MetricFamily]:
    """Merge per-replica family lists into fleet totals.

    Counters and histograms sum sample-wise (histogram buckets share the
    fixed log2 bounds by construction, so the bucket-wise sum is the
    exact fleet histogram); gauges follow :func:`gauge_merge_policy`.
    Sample identity is (suffixed sample name, label pairs); families and
    samples keep first-seen order over the sorted replica ids so the
    exposition is deterministic."""
    merged: dict[str, MetricFamily] = {}
    order: list[str] = []
    # (family, sample_name, labels) -> accumulated float
    acc: dict[tuple, float] = {}
    sample_order: dict[str, list[tuple]] = {}
    for rid in sorted(scrapes):
        for fam in scrapes[rid]:
            out_name = merged_name(fam.name)
            out = merged.get(out_name)
            if out is None:
                out = MetricFamily(name=out_name, kind=fam.kind,
                                   help=fam.help)
                merged[out_name] = out
                order.append(out_name)
                sample_order[out_name] = []
            policy = ("max" if fam.kind == "gauge"
                      and gauge_merge_policy(fam.name) == "max" else "sum")
            for name, labels, raw in fam.samples:
                out_sample = merged_name(name) if name.startswith(
                    fam.name) else name
                key = (out_name, out_sample, labels)
                value = obs_metrics.sample_value(raw)
                if key not in acc:
                    acc[key] = value
                    sample_order[out_name].append((out_sample, labels))
                elif policy == "max":
                    acc[key] = max(acc[key], value)
                else:
                    acc[key] += value
    for out_name in order:
        fam = merged[out_name]
        fam.samples = [
            (sample_name, labels,
             obs_metrics._fmt(acc[(out_name, sample_name, labels)]))
            for sample_name, labels in sample_order[out_name]]
    return [merged[name] for name in order]


def relabeled_families(scrapes: dict[str, list[MetricFamily]],
                       ) -> list[MetricFamily]:
    """Per-replica series under their original family names with a
    ``replica`` label appended — the raw federated view next to the
    merged one."""
    out: dict[str, MetricFamily] = {}
    order: list[str] = []
    for rid in sorted(scrapes):
        for fam in scrapes[rid]:
            dst = out.get(fam.name)
            if dst is None:
                dst = MetricFamily(name=fam.name, kind=fam.kind,
                                   help=fam.help)
                out[fam.name] = dst
                order.append(fam.name)
            for name, labels, raw in fam.samples:
                dst.samples.append(
                    (name, labels + (("replica", rid),), raw))
    return [out[name] for name in order]


def federated_exposition(scrapes: dict[str, list[MetricFamily]]) -> str:
    """The replica half of ``GET /fleet/metrics``: every per-replica
    series re-labeled, then every merged family.  Built from one scrapes
    snapshot, so the merged totals equal the per-replica sums by
    construction."""
    if not scrapes:
        return ""
    return (obs_metrics.render_exposition(relabeled_families(scrapes))
            + obs_metrics.render_exposition(merge_families(scrapes)))


def phase_hist_cum(families: list[MetricFamily], phase: str,
                   ) -> dict[float, float]:
    """Cumulative latency-bucket counts (``le`` bound -> count) for one
    phase out of a parsed scrape's ``ict_phase_duration_seconds`` family;
    empty when the replica has not observed the phase yet.  Thin wrapper
    over the shared :func:`obs.metrics.bucket_cum` (foreign ``le`` bounds
    are skipped, never raised out of the poll thread)."""
    return obs_metrics.bucket_cum(families, "ict_phase_duration_seconds",
                                  {"phase": phase})


def histogram_quantile(cum: dict[float, float], q: float) -> float | None:
    """Back-compat alias for the ONE shared upper-bound-bucket estimator,
    :func:`obs.metrics.quantile_from_cum` — the straggler detector, the
    capacity model, and the alert engine's quantile predicates must never
    disagree about the same scrape."""
    return obs_metrics.quantile_from_cum(cum, q)


class ScrapeCache:
    """Per-replica last-good ``/metrics`` scrape + flight-ring cache,
    written by the router's poll thread and read by its HTTP handler
    threads.  A failed scrape never evicts the last good one — staleness
    is *reported* (the age gauges), not silently absorbed."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scrapes: dict[str, dict] = {}  # ict: guarded-by(self._lock)

    def update(self, replica_id: str, text: str,
               families: list[MetricFamily],
               flight_events: list[dict] | None) -> None:
        rec = {
            "text": text,
            "families": families,
            "flight": list(flight_events or ()),
            "ts_mono": time.monotonic(),
            "ts": round(time.time(), 3),
            "ok": True,
        }
        with self._lock:
            # Keep the previous flight cache when this scrape could not
            # fetch the ring — a partially-degraded replica's last good
            # pre-death ring is exactly what the post-mortem needs.
            if flight_events is None and replica_id in self._scrapes:
                rec["flight"] = self._scrapes[replica_id]["flight"]
            self._scrapes[replica_id] = rec

    def note_failure(self, replica_id: str) -> None:
        with self._lock:
            rec = self._scrapes.get(replica_id)
            if rec is not None:
                rec["ok"] = False

    def snapshot(self) -> dict[str, dict]:
        """Shallow copies: family lists are replaced whole on update,
        never mutated in place, so readers can render from them lock-free."""
        with self._lock:
            return {rid: dict(rec) for rid, rec in self._scrapes.items()}

    def ages(self) -> dict[str, float]:
        """Seconds since each replica's last GOOD scrape."""
        now = time.monotonic()
        with self._lock:
            return {rid: round(now - rec["ts_mono"], 3)
                    for rid, rec in self._scrapes.items()}

    def flight_events(self, replica_id: str) -> list[dict]:
        with self._lock:
            rec = self._scrapes.get(replica_id)
            return list(rec["flight"]) if rec is not None else []

    def forget(self, replica_id: str) -> None:
        """Drop one replica's cached scrape — the autoscaler's scale-down
        path (a replica that LEFT the fleet must fall off the staleness
        gauges instead of aging forever; a dead-but-configured replica
        keeps its last good scrape, as before)."""
        with self._lock:
            self._scrapes.pop(replica_id, None)


class TraceStore:
    """Bounded router-side span store, indexed by trace id.  LRU over
    traces (``MAX_TRACES``), capped per trace (``MAX_SPANS_PER_TRACE``);
    a span is one small dict, so the store's memory is bounded by
    construction."""

    def __init__(self, max_traces: int = MAX_TRACES,
                 max_spans: int = MAX_SPANS_PER_TRACE) -> None:
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans)
        self._lock = threading.Lock()
        # trace_id -> {"spans": [...], "job_id": str}
        self._traces: collections.OrderedDict = collections.OrderedDict()  # ict: guarded-by(self._lock)

    def record(self, trace_id: str, event: str, job_id: str = "",
               **fields) -> None:
        if not trace_id:
            return
        span = {"ts": round(time.time(), 6), "source": "router",
                "event": event}
        span.update(fields)
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                rec = {"spans": [], "job_id": ""}
                self._traces[trace_id] = rec
            self._traces.move_to_end(trace_id)
            if len(rec["spans"]) < self.max_spans:
                rec["spans"].append(span)
            if job_id:
                rec["job_id"] = job_id
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    def spans(self, trace_id: str) -> list[dict]:
        with self._lock:
            rec = self._traces.get(trace_id)
            return [dict(s) for s in rec["spans"]] if rec else []

    def job_for(self, trace_id: str) -> str:
        with self._lock:
            rec = self._traces.get(trace_id)
            return rec["job_id"] if rec else ""


def span_hops(spans: list[dict]) -> dict:
    """Per-hop latency off one assembled trace: consecutive span deltas
    in timestamp order, each hop labeled ``source:event`` ->
    ``source:event``.  The canary prober stamps this on every journey
    verdict (ISSUE 18), re-using the trace assembly instead of growing a
    second timing path; unstamped spans are skipped, < 2 stamped spans
    yield no hops."""
    stamped = []
    for s in spans:
        try:
            ts = float(s.get("ts", 0.0))
        except (TypeError, ValueError):
            continue
        if ts <= 0.0:
            continue
        source = str(s.get("source", "") or "")
        event = str(s.get("event", "") or "")
        label = f"{source}:{event}" if source else event
        stamped.append((ts, label))
    stamped.sort(key=lambda pair: pair[0])
    hops = []
    for (t0, l0), (t1, l1) in zip(stamped, stamped[1:]):
        hops.append({"from": l0, "to": l1, "dt_s": round(t1 - t0, 6)})
    total = (round(stamped[-1][0] - stamped[0][0], 6)
             if len(stamped) >= 2 else 0.0)
    return {"hops": hops, "total_s": total}


class StragglerDetector:
    """Windowed per-replica latency p50 vs the fleet median.

    Each poll hands :meth:`update` every scraped replica's *cumulative*
    bucket counts for the watched phase; the detector differences them
    against the previous poll (new observations only), keeps a sliding
    window of the last ``window`` polls' deltas, and estimates each
    replica's p50 over the window.  A replica whose p50 exceeds
    ``factor`` times the fleet median of those p50s for ``polls``
    consecutive updates is flagged; one in-bounds update clears it.
    Replicas with fewer than ``min_count`` windowed observations (idle,
    freshly started, or dead) get no verdict and are never flagged.  A
    replica MISSING from an update (failed scrape, death) keeps its flag
    and its countdown frozen — a degrading replica whose scrape just
    timed out must not silently shed its placement penalty; only an
    explicit in-bounds verdict clears."""

    def __init__(self, factor: float = 3.0, polls: int = 3,
                 window: int = 8, min_count: int = 3) -> None:
        self.factor = float(factor)
        self.polls = int(polls)
        self.window = int(window)
        self.min_count = int(min_count)
        self._lock = threading.Lock()
        self._last_cum: dict[str, dict[float, float]] = {}  # ict: guarded-by(self._lock)
        self._windows: dict[str, collections.deque] = {}  # ict: guarded-by(self._lock)
        self._consec: dict[str, int] = {}  # ict: guarded-by(self._lock)
        self._flagged: set[str] = set()  # ict: guarded-by(self._lock)

    def update(self, cum_by_replica: dict[str, dict[float, float]]) -> dict:
        """One poll's verdict: ``{"p50": {...}, "median": float|None,
        "stragglers": set, "fired": [...], "cleared": [...]}``."""
        with self._lock:
            p50: dict[str, float] = {}
            for rid, cum in cum_by_replica.items():
                prev = self._last_cum.get(rid, {})
                delta = {le: max(n - prev.get(le, 0.0), 0.0)
                         for le, n in cum.items()}
                self._last_cum[rid] = dict(cum)
                win = self._windows.get(rid)
                if win is None:
                    win = self._windows[rid] = collections.deque(
                        maxlen=self.window)
                win.append(delta)
                summed: dict[float, float] = {}
                for d in win:
                    for le, n in d.items():
                        summed[le] = summed.get(le, 0.0) + n
                total = max(summed.values()) if summed else 0.0
                if total >= self.min_count:
                    q = obs_metrics.quantile_from_cum(summed, 0.5)
                    if q is not None:
                        p50[rid] = q
            median = None
            if len(p50) >= 2:
                ordered = sorted(p50.values())
                mid = len(ordered) // 2
                median = (ordered[mid] if len(ordered) % 2
                          else 0.5 * (ordered[mid - 1] + ordered[mid]))
            fired, cleared = [], []
            for rid in cum_by_replica:
                slow = (median is not None and median > 0
                        and rid in p50
                        and p50[rid] > self.factor * median)
                if slow:
                    self._consec[rid] = self._consec.get(rid, 0) + 1
                    if (self._consec[rid] >= self.polls
                            and rid not in self._flagged):
                        self._flagged.add(rid)
                        fired.append(rid)
                else:
                    self._consec[rid] = 0
                    if rid in self._flagged:
                        self._flagged.discard(rid)
                        cleared.append(rid)
            return {"p50": p50, "median": median,
                    "stragglers": set(self._flagged),
                    "fired": fired, "cleared": cleared}

    def stragglers(self) -> set[str]:
        with self._lock:
            return set(self._flagged)

    def forget(self, replica_id: str) -> None:
        """Drop one replica's windows/flag — scale-down removal (its id
        may be reused by a future spawn and must start clean)."""
        with self._lock:
            self._last_cum.pop(replica_id, None)
            self._windows.pop(replica_id, None)
            self._consec.pop(replica_id, None)
            self._flagged.discard(replica_id)


# --- incident bundles ---


def write_incident_bundle(directory: str, *, reason: str,
                          replica_id: str = "", job_id: str = "",
                          trace_id: str = "",
                          placements: list[dict] | None = None,
                          replicas: list[dict] | None = None,
                          metrics_text: str = "",
                          flight_events: list[dict] | None = None,
                          trace: dict | None = None) -> str | None:
    """One self-contained fleet incident under ``directory``.

    Layout: ``incident-<unixms>-<hex6>/`` holding ``manifest.json``
    (reason, placement-table and registry snapshots, trace context),
    ``metrics.prom`` (the replica's last good scrape), ``flight.json``
    (its cached flight ring), and ``trace.json`` (the stitched trace,
    for job-scoped incidents).  Built under a ``.part`` name and renamed;
    oldest bundles beyond :data:`MAX_INCIDENTS_KEPT` swept; returns the
    path or None — forensics must never become a second failure (the
    ``write_repro_bundle`` contract)."""
    try:
        os.makedirs(directory, exist_ok=True)
        name = (f"incident-{int(time.time() * 1000):013d}-"
                f"{uuid.uuid4().hex[:6]}")
        final = os.path.join(directory, name)
        tmp = f"{final}.part"
        os.makedirs(tmp)
        manifest = {
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "replica_id": replica_id,
            "job_id": job_id,
            "trace_id": trace_id,
            "placements": placements or [],
            "replicas": replicas or [],
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as fh:
            json.dump(manifest, fh, indent=1, default=str)
            fh.write("\n")
        if metrics_text:
            with open(os.path.join(tmp, "metrics.prom"), "w") as fh:
                fh.write(metrics_text)
        with open(os.path.join(tmp, "flight.json"), "w") as fh:
            json.dump({"events": flight_events or []}, fh, indent=1,
                      default=str)
            fh.write("\n")
        if trace is not None:
            with open(os.path.join(tmp, "trace.json"), "w") as fh:
                json.dump(trace, fh, indent=1, default=str)
                fh.write("\n")
        os.replace(tmp, final)
        bundles = sorted(n for n in os.listdir(directory)
                         if n.startswith("incident-")
                         and not n.endswith(".part"))
        for old in bundles[:-MAX_INCIDENTS_KEPT]:
            try:
                shutil.rmtree(os.path.join(directory, old))
            except OSError:
                pass
        return final
    except Exception:  # noqa: BLE001 — best-effort by contract
        return None


def list_incidents(directory: str) -> list[dict]:
    """Bundle inventory for ``GET /fleet/incidents`` (name / reason / ts
    / replica / job / trace)."""
    out: list[dict] = []
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("incident-")
                       and not n.endswith(".part"))
    except OSError:
        return out
    for name in names:
        entry = {"name": name, "path": os.path.join(directory, name)}
        try:
            with open(os.path.join(directory, name, "manifest.json")) as fh:
                m = json.load(fh)
            entry.update(reason=m.get("reason"), ts=m.get("ts"),
                         replica_id=m.get("replica_id"),
                         job_id=m.get("job_id"),
                         trace_id=m.get("trace_id"))
        except (OSError, ValueError):
            entry["reason"] = "unreadable manifest"
        out.append(entry)
    return out
