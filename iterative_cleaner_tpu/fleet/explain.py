"""The per-job explain plane: one causal report from seven sources.

The fleet already emits every piece of per-job evidence — the stitched
cross-hop trace (fleet/obs.py), the CostRecord with its roofline
attainment (obs/costs.py), per-diagnostic zap attribution
(obs/forensics.py timeline records), the shadow-audit verdict with its
repro bundle (obs/audit.py), the RFI quality summary (obs/quality.py),
the cache/coalesce disposition (fleet/cache.py + the coalescer's
batch_k), and the SLO journeys (fleet/slo.py) — but across six
endpoints with no causal view.  ``GET /fleet/explain/<job_id>`` (and
``ict-clean explain`` on the CLI) stitches them into ONE JSON report,
answering "why was this job slow / why was this channel zapped / did
the cache serve it" without six manual scrapes.

Every plane is stamped with its provenance (the PR-10 flight-cache
discipline, generalized):

- ``live`` — fetched from the serving replica (or computed from the
  router's own in-memory state) on this request;
- ``spool`` — served from what the router durably remembers: the
  fleet-cache result record, the placement table's terminal summary,
  or the pre-death flight-ring cache;
- ``unavailable`` — the evidence would live on a replica that is dead
  (or was never recorded); the report says so instead of guessing.

This module deliberately does NOT import the router (it would be a
cycle); it drives the router object through its public read surface.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request

from iterative_cleaner_tpu.fleet.client import ReplicaRefused, ReplicaUnreachable
from iterative_cleaner_tpu.service.scheduler import bucket_label

#: The report's plane names, in causal order: what happened (trace),
#: what it cost, what the cleaner did (zaps/quality), was it right
#: (audit), was it reused (cache), and what it did to the objectives
#: (slo).  tests/test_recorder_explain.py pins this exact set.
PLANES = ("trace", "cost", "zaps", "audit", "quality", "cache", "slo")


def _plane(source: str, **body) -> dict:
    return {"source": source, **body}


def _fetch_timeline(router, p: dict) -> tuple[str, list]:
    """The per-iteration forensics timeline for the CURRENT hop —
    served only by the replica's ``GET /jobs/<id>/trace`` (manifests
    stay lean), so a dead replica means honestly unavailable."""
    if not p.get("base_url"):
        return "unavailable", []
    rep = router.registry.get(p["base_url"])
    if rep is None or not rep.alive:
        return "unavailable", []
    try:
        tr = router.client.job_trace(p["base_url"], p["replica_job_id"])
    except (ReplicaUnreachable, ReplicaRefused):
        return "unavailable", []
    timeline = tr.get("timeline") or []
    return "live", timeline if isinstance(timeline, list) else []


def _zap_attribution(timeline: list) -> dict:
    """Fold the per-iteration ``zaps_by_diagnostic`` votes into one
    per-diagnostic total (the quality summary's attribution source,
    summed across the whole convergence run)."""
    totals: dict[str, int] = {}
    for rec in timeline:
        votes = rec.get("zaps_by_diagnostic") if isinstance(rec, dict) \
            else None
        if not isinstance(votes, dict):
            continue
        for diag, n in votes.items():
            try:
                totals[str(diag)] = totals.get(str(diag), 0) + int(n)
            except (TypeError, ValueError):
                continue
    return totals


def explain_job(router, job_id: str) -> tuple[int, dict]:
    """Build the seven-plane report; (404, ...) for a job the placement
    table no longer remembers."""
    p = router.placement_snapshot(job_id)
    if p is None:
        return 404, {"error": f"no job {job_id!r} in the placement table"}
    code, manifest = router.job_manifest(job_id)
    if code != 200 or not isinstance(manifest, dict):
        manifest = {}
    # Manifest provenance: a fleet-cache placement serves its recorded
    # result summary (spool); a full manifest (it always carries "path")
    # came off the live replica; anything else is the placement table's
    # lean terminal/pending summary (spool).
    if p["cached"] is not None:
        manifest_src = "spool"
    elif "path" in manifest:
        manifest_src = "live"
    else:
        manifest_src = "spool"

    # 1. The cross-hop trace, with its per-hop sources folded into the
    # plane's own provenance: all-live hops read live, any hop recovered
    # from the pre-death flight cache demotes the plane to spool.
    t_code, trace = (router.fleet_trace(p["trace_id"])
                     if p["trace_id"] else (404, {}))
    if t_code != 200:
        trace_plane = _plane("unavailable")
    else:
        hop_sources = trace.get("sources", {})
        if any(s == "flight-cache" for s in hop_sources.values()):
            src = "spool"
        elif any(s == "unavailable" for s in hop_sources.values()):
            src = "spool"   # router spans still tell the story; the
            # missing hop is visible in hop_sources
        else:
            src = "live"
        trace_plane = _plane(src, trace_id=p["trace_id"],
                             state=trace.get("state"),
                             hops=trace.get("hops", []),
                             hop_sources=hop_sources,
                             spans=trace.get("spans", []))

    # 2. Cost + roofline: the manifest's CostRecord, joined with the
    # poll-tick cost fold's per-bucket attainment for context.
    cost = manifest.get("cost") or {}
    shape = manifest.get("shape") or list(p.get("shape") or [])
    bucket = ""
    if isinstance(shape, (list, tuple)) and len(shape) == 3:
        bucket = bucket_label(shape)
    bucket_attainment = None
    try:
        fold = router.fleet_costs()
        bucket_attainment = (fold.get("buckets", {})
                             .get(bucket, {}).get("attainment"))
    except Exception:  # noqa: BLE001 — context, never a report-killer
        pass
    if cost:
        cost_plane = _plane(
            manifest_src, record=cost,
            device_s=cost.get("device_s"),
            compile_s=cost.get("compile_s"),
            phases=cost.get("phases") or {},
            attainment=cost.get("attainment"),
            bucket=bucket, bucket_attainment=bucket_attainment)
    else:
        cost_plane = _plane("unavailable", bucket=bucket,
                            bucket_attainment=bucket_attainment)

    # 3. Per-diagnostic zap attribution: timeline-only evidence — live
    # replica or nothing (manifests exclude the timeline by design).
    tl_src, timeline = _fetch_timeline(router, p)
    if tl_src == "live":
        zaps_plane = _plane("live",
                            by_diagnostic=_zap_attribution(timeline),
                            iterations=len(timeline))
    else:
        zaps_plane = _plane("unavailable")

    # 4. The audit verdict (+ the repro-bundle pointer a divergence
    # writes — obs/audit.py stamps it on the record as "bundle").
    audit = manifest.get("audit_result") or {}
    if audit:
        audit_plane = _plane(
            manifest_src,
            mask_identical=audit.get("mask_identical"),
            n_mask_diffs=audit.get("n_mask_diffs"),
            repro_bundle=audit.get("bundle") or None,
            record=audit)
    else:
        audit_plane = _plane("unavailable",
                             note="no shadow audit ran for this job")

    # 5. The RFI quality summary.
    quality = manifest.get("quality") or {}
    quality_plane = (_plane(manifest_src, **quality) if quality
                     else _plane("unavailable"))

    # 6. Cache/coalesce disposition: who served it (fleet cache /
    # replica cache / a coalesced batch) and what that avoided.
    served_by = str(manifest.get("served_by", "") or "")
    if p["cached"] is not None:
        served_by = served_by or "fleet-cache"
    cache_plane = _plane(
        manifest_src if (manifest or p["cached"] is not None)
        else "unavailable",
        served_by=served_by,
        fleet_cache_hit=p["cached"] is not None,
        cache_hit=bool(cost.get("cache_hit")),
        avoided_device_s=cost.get("avoided_device_s"),
        coalesced_batch_k=cost.get("batch_k"),
        route=cost.get("route"))

    # 7. SLO journeys: classify which journeys this job's path walked
    # (a cache-served job is the cache journey; every real placement
    # walks admission) and report those journeys' SLI rows — computed
    # from the router's own in-memory plane, so always live.
    journeys = ["cache" if (p["cached"] is not None
                            or served_by == "fleet-cache") else "fresh"]
    if not p["synthetic"]:
        journeys.append("admission")
    latency_s = None
    try:
        fin = float(manifest.get("finished_s", 0.0) or 0.0)
        sub = float(manifest.get("submitted_s", 0.0)
                    or p.get("submitted_s", 0.0) or 0.0)
        if fin > 0 and sub > 0:
            latency_s = round(fin - sub, 6)
    except (TypeError, ValueError):
        pass
    slo_report = router.slo.report()
    slo_plane = _plane(
        "live", journeys=journeys, latency_s=latency_s,
        failing_journeys=[j for j in slo_report.get("failing_journeys", [])
                          if j in journeys],
        rows={j: slo_report.get("journeys", {}).get(j) for j in journeys})

    report = {
        "job_id": p["job_id"],
        "state": manifest.get("state", p["state"]),
        "tenant": p["tenant"],
        "trace_id": p["trace_id"],
        "replica_id": p["replica_id"],
        "attempts": p["attempts"],
        "synthetic": p["synthetic"],
        "planes": {
            "trace": trace_plane,
            "cost": cost_plane,
            "zaps": zaps_plane,
            "audit": audit_plane,
            "quality": quality_plane,
            "cache": cache_plane,
            "slo": slo_plane,
        },
    }
    return 200, report


# --- the ``ict-clean explain`` CLI (and fleet_top's one-shot reuse) ---

def fetch_explain(router_url: str, job_id: str,
                  timeout_s: float = 10.0) -> tuple[int, dict]:
    """GET /fleet/explain/<job_id> from a live router; (0, {...}) on a
    transport failure (the CLI and fleet_top share this)."""
    url = f"{router_url.rstrip('/')}/fleet/explain/{job_id}"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as exc:
        try:
            body = json.load(exc)
        except ValueError:
            body = {"error": str(exc)}
        return exc.code, body
    except (urllib.error.URLError, OSError, ValueError) as exc:
        return 0, {"error": f"router unreachable: {exc}"}


def render_explain(report: dict) -> str:
    """The human rendering: one header line plus one line per plane,
    provenance first — scannable in a terminal, no JSON spelunking."""
    lines = [
        f"job {report.get('job_id')}  state={report.get('state')}  "
        f"tenant={report.get('tenant')}  replica={report.get('replica_id')}  "
        f"attempts={report.get('attempts')}"]
    planes = report.get("planes", {})
    for name in PLANES:
        plane = planes.get(name) or {}
        src = plane.get("source", "unavailable")
        detail = ""
        if name == "trace":
            detail = (f"{len(plane.get('spans') or [])} spans, "
                      f"{len(plane.get('hops') or [])} hop(s)")
        elif name == "cost" and src != "unavailable":
            detail = (f"device_s={plane.get('device_s')} "
                      f"compile_s={plane.get('compile_s')} "
                      f"attainment={plane.get('attainment')}")
        elif name == "zaps" and src != "unavailable":
            by = plane.get("by_diagnostic") or {}
            detail = " ".join(f"{k}={v}" for k, v in sorted(by.items())) \
                or "no zaps attributed"
        elif name == "audit" and src != "unavailable":
            detail = (f"mask_identical={plane.get('mask_identical')}"
                      + (f" repro={plane['repro_bundle']}"
                         if plane.get("repro_bundle") else ""))
        elif name == "quality" and src != "unavailable":
            detail = f"zap_frac={plane.get('zap_frac')}"
        elif name == "cache":
            detail = (f"served_by={plane.get('served_by') or 'replica'} "
                      f"fleet_cache_hit={plane.get('fleet_cache_hit')} "
                      f"batch_k={plane.get('coalesced_batch_k')}")
        elif name == "slo":
            detail = (f"journeys={','.join(plane.get('journeys') or [])} "
                      f"latency_s={plane.get('latency_s')} "
                      f"failing={plane.get('failing_journeys')}")
        lines.append(f"  {name:<8} [{src:^11}] {detail}".rstrip())
    return "\n".join(lines)


def explain_main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="ict-clean explain",
        description="Fetch one job's seven-plane causal report from a "
                    "fleet router (GET /fleet/explain/<job_id>): trace, "
                    "cost/roofline, zap attribution, audit verdict, "
                    "quality, cache/coalesce disposition, SLO journeys "
                    "— each stamped live/spool/unavailable.")
    p.add_argument("job_id", help="the fleet job id (the id the 202 "
                                  "carried)")
    p.add_argument("--router", default="http://127.0.0.1:8790",
                   metavar="URL", help="fleet router base URL "
                                       "(default http://127.0.0.1:8790)")
    p.add_argument("--timeout_s", type=float, default=10.0)
    p.add_argument("--json", action="store_true",
                   help="emit the raw report JSON instead of the "
                        "human rendering")
    args = p.parse_args(argv)
    code, report = fetch_explain(args.router, args.job_id,
                                 timeout_s=args.timeout_s)
    if code != 200:
        print(json.dumps(report) if args.json
              else f"error: {report.get('error', f'HTTP {code}')}",
              file=sys.stderr)
        return 1
    print(json.dumps(report) if args.json else render_explain(report))
    return 0
