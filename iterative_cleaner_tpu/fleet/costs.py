"""Fleet-wide cost federation: tenant showback, budgets, and the
conservation check (the router half of ISSUE 15; replica half in
obs/costs.py).

Zero new traffic by construction: the replica ledgers render their
aggregates as ``ict_cost_*`` counters on the ``/metrics`` exposition the
router's poll tick ALREADY scrapes (fleet/obs.ScrapeCache); this module
folds those cached parsed families into the ``GET /fleet/costs`` view —
per-tenant / per-bucket / per-replica breakdowns — once per tick, the
fleet/capacity.py pattern.

Budgets are **advisory**: ``--tenant NAME:QUOTA:WEIGHT[:BUDGET]`` grows
an optional device-seconds budget that feeds default alert RULES (never
admission changes — quotas stay the only admission lever).  The router
rebuilds the ``ict_fleet_tenant_budget_used_pct{tenant}`` gauge whole
each tick from the ALIVE replicas' scraped per-life counters, and
:func:`budget_rules` installs two rules per budgeted tenant over it:
``tenant_budget_burn:<name>`` (warning at 80%) and
``tenant_budget_exhausted:<name>`` (critical at 100%).  Because the
gauge is rebuilt from live scrapes, a replica that restarts clean (its
pre-registered counters read an explicit 0) or leaves the fleet drops
its usage from the gauge and a fired budget alert RESOLVES — the PR 12
freeze-on-missing lesson, designed in rather than patched in.

The **conservation check** rides the same fold: per replica,
``Σ ict_cost_device_seconds_total`` over tenants divided by
``ict_service_dispatch_s`` must sit within 1% of 1.0 whenever the
replica has dispatched at all — attribution that doesn't conserve is
fiction, and the ratio is exported
(``ict_fleet_cost_conservation_ratio{replica}``) so the invariant is a
scrapeable fact, not a test-only assertion.
"""

from __future__ import annotations

import time

from iterative_cleaner_tpu.fleet import alerts as fleet_alerts
from iterative_cleaner_tpu.fleet.capacity import (
    counter_value,
    labeled_gauge_values,
)
from iterative_cleaner_tpu.fleet.tenants import SYNTHETIC_TENANT
from iterative_cleaner_tpu.obs import metrics as obs_metrics

#: |conservation_ratio - 1| beyond this is an attribution bug (the smoke
#: and the e2e tests assert it; float split error is ~1e-9, so 1% is
#: pure headroom for counter-read skew between the two families).
CONSERVATION_TOLERANCE = 0.01


def _labeled_counter_sums(families, family: str, label_key: str,
                          ) -> dict[str, float]:
    """``{label value -> summed sample value}`` for one labeled counter
    family out of a parsed scrape.  Walks the RAW samples (not the
    capacity gauge helper, which keeps last-wins per label value): two
    samples sharing a ``label_key`` value but differing on some other
    label dimension must SUM, or the fold under-reports the tenant and
    the conservation ratio reads falsely low."""
    out: dict[str, float] = {}
    for fam in families:
        if fam.name != family:
            continue
        for _sname, label_pairs, raw in fam.samples:
            d = dict(label_pairs)
            if label_key not in d:
                continue
            try:
                value = obs_metrics.sample_value(raw)
            except ValueError:
                continue
            out[d[label_key]] = out.get(d[label_key], 0.0) + value
    return out


def fold(replica_rows: list[dict], scrapes: dict[str, dict],
         budgets: dict[str, float] | None = None) -> dict:
    """One tick's fleet cost view from the registry + scrape-cache
    snapshots the router already took.  Only ALIVE replicas contribute
    (a departed or restarted-clean replica's usage leaves the fold —
    the advisory-budget resolution semantics documented above); each
    contributing replica also gets its conservation ratio."""
    budgets = dict(budgets or {})
    tenants: dict[str, dict] = {}
    buckets: dict[str, dict] = {}
    routes: dict[str, dict] = {}
    replicas: dict[str, dict] = {}

    def tenant_row(name: str) -> dict:
        return tenants.setdefault(name, {
            "device_s": 0.0, "jobs": 0.0, "compile_s": 0.0,
            "bytes_accessed": 0.0, "cache_hits": 0.0,
            "avoided_device_s": 0.0, "avoided_bytes": 0.0,
        })

    for row in replica_rows:
        if not row.get("alive"):
            continue
        rid = row.get("replica_id") or row.get("base_url", "")
        rec = scrapes.get(rid)
        families = (rec or {}).get("families") or []
        if not families:
            continue
        per_tenant = _labeled_counter_sums(
            families, "ict_cost_device_seconds_total", "tenant")
        for tenant, v in per_tenant.items():
            tenant_row(tenant)["device_s"] += v
        for family, key in (("ict_cost_jobs_total", "jobs"),
                            ("ict_cost_compile_seconds_total", "compile_s"),
                            ("ict_cost_bytes_accessed_total",
                             "bytes_accessed"),
                            ("ict_cost_cache_hits_total", "cache_hits"),
                            ("ict_cost_cache_avoided_device_seconds_total",
                             "avoided_device_s"),
                            ("ict_cost_cache_avoided_bytes_total",
                             "avoided_bytes")):
            for tenant, v in _labeled_counter_sums(
                    families, family, "tenant").items():
                tenant_row(tenant)[key] += v
        for bucket, v in _labeled_counter_sums(
                families, "ict_cost_bucket_device_seconds_total",
                "shape_bucket").items():
            buckets.setdefault(bucket, {"device_s": 0.0,
                                        "attainment": None})
            buckets[bucket]["device_s"] += v
        for bucket, v in labeled_gauge_values(
                families, "ict_cost_attainment_ratio",
                "shape_bucket").items():
            rec_b = buckets.setdefault(bucket, {"device_s": 0.0,
                                                "attainment": None})
            # Latest-known attainment per bucket; max across replicas
            # (the gauge-merge "peaks don't average" rationale).
            if v and (rec_b["attainment"] is None
                      or v > rec_b["attainment"]):
                rec_b["attainment"] = v
        for route, v in _labeled_counter_sums(
                families, "ict_cost_route_device_seconds_total",
                "route").items():
            routes.setdefault(route, {"device_s": 0.0})
            routes[route]["device_s"] += v
        cost_s = sum(per_tenant.values())
        dispatch_s = counter_value(families, "ict_service_dispatch_s")
        replicas[rid] = {
            "device_s": round(cost_s, 6),
            "dispatch_s": round(dispatch_s, 6),
            "conservation_ratio": (round(cost_s / dispatch_s, 6)
                                   if dispatch_s > 0 else None),
        }

    # Canary traffic is excluded from SHOWBACK, not from conservation:
    # the reserved synthetic tenant's device time stays in each replica's
    # cost_s sum above (attribution must still conserve against dispatch
    # seconds — probe work is real work), but it is nobody's bill, so the
    # tenant table never grows a "_canary" row (ISSUE 18).
    tenants.pop(SYNTHETIC_TENANT, None)
    for tenant, budget in budgets.items():
        row = tenant_row(tenant)
        row["budget_device_s"] = float(budget)
    for tenant, row in tenants.items():
        budget = budgets.get(tenant)
        row["budget_used_pct"] = (
            round(100.0 * row["device_s"] / budget, 3)
            if budget else None)
        for key in ("device_s", "compile_s", "avoided_device_s"):
            row[key] = round(row[key], 6)
    return {
        "ts": round(time.time(), 3),
        "tenants": {k: tenants[k] for k in sorted(tenants)},
        "buckets": {k: buckets[k] for k in sorted(buckets)},
        "routes": {k: routes[k] for k in sorted(routes)},
        "replicas": {k: replicas[k] for k in sorted(replicas)},
        "budgets": {k: float(v) for k, v in sorted(budgets.items())},
    }


def gauge_families(snap: dict, budgets: dict[str, float] | None = None,
                   ) -> dict[str, dict[tuple, float]]:
    """The fold rendered for ``RouterMetrics.replace_gauge_family`` —
    families replaced whole each tick, so a departed replica's ratio and
    a resolved tenant's usage drop off instead of freezing.  Every
    BUDGETED tenant always has a ``used_pct`` sample (0.0 before any
    usage): the budget rules are gt thresholds, and an absent series
    would freeze instead of resolving."""
    budgets = dict(budgets or {})
    used: dict[tuple, float] = {
        (("tenant", t),): 0.0 for t in budgets}
    for tenant, row in (snap.get("tenants") or {}).items():
        pct = row.get("budget_used_pct")
        if pct is not None:
            used[(("tenant", tenant),)] = float(pct)
    conservation = {
        (("replica", rid),): float(rec["conservation_ratio"])
        for rid, rec in (snap.get("replicas") or {}).items()
        if rec.get("conservation_ratio") is not None}
    return {
        "fleet_tenant_budget_used_pct": used,
        "fleet_cost_conservation_ratio": conservation,
    }


def budget_rules(budgets: dict[str, float],
                 ) -> list["fleet_alerts.AlertRule"]:
    """Two advisory rules per budgeted tenant over the router-computed
    ``ict_fleet_tenant_budget_used_pct`` gauge: warning at 80%, critical
    at 100% (rules, never admission changes).  Named per tenant (the
    engine requires unique names); an operator ``--alert_rule`` re-using
    a name replaces it, the default-pack override convention."""
    rules = []
    for tenant in sorted(budgets):
        if float(budgets[tenant]) <= 0:
            continue
        rules.append(fleet_alerts.parse_rule({
            "name": f"tenant_budget_burn:{tenant}",
            "source": "budget",
            "severity": "warning",
            "family": "ict_fleet_tenant_budget_used_pct",
            "labels": {"tenant": tenant},
            "predicate": {"op": "gt", "value": 80.0},
            "for_ticks": 1,
            "description": f"tenant {tenant!r} has burned over 80% of its "
                           "advisory device-seconds budget "
                           "(docs/OBSERVABILITY.md \"Cost & efficiency "
                           "accounting\")"}))
        rules.append(fleet_alerts.parse_rule({
            "name": f"tenant_budget_exhausted:{tenant}",
            "source": "budget",
            "severity": "critical",
            "family": "ict_fleet_tenant_budget_used_pct",
            "labels": {"tenant": tenant},
            "predicate": {"op": "ge", "value": 100.0},
            "for_ticks": 1,
            "description": f"tenant {tenant!r} has exhausted its advisory "
                           "device-seconds budget — showback only, "
                           "admission is untouched"}))
    return rules
