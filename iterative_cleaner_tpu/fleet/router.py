"""The fleet router: one front door for N cleaning-daemon replicas.

Placement policy (docs/SERVING.md "Fleet"):

- **least-loaded-by-bucket** — candidates are ranked by the scalar load
  off their last ``/healthz`` snapshot (open jobs + every queue depth +
  placements routed since that snapshot), minus a **warm-cache affinity
  bonus** when the submission declares its shape bucket (optional
  ``"shape": [nsub, nchan, nbin]`` in the POST body): a replica whose
  warm pool holds the bucket's executables — or that already has cubes
  of that bucket queued — is preferred, because on it the job compiles
  nothing;
- **drain/death eviction** — a draining replica (``/healthz`` says
  ``draining: true``) or a dead one (``dead_after`` consecutive
  unreachable polls) gets no new placements; a dead replica's open
  placements are **re-routed** to surviving replicas carrying the same
  idempotency key, so the job runs at most once per replica and the
  fleet serves it exactly once while the dead replica stays dead;
- **failover retries** — submission-path transport failures walk the
  remaining candidates, then back off with **full jitter**
  (utils/backoff.py; ``ICT_BACKOFF_SEED`` pins schedules in tests) so N
  routers (or one router's N queued failovers) recovering from the same
  incident don't thundering-herd the revived replica;
- **multi-tenant admission** — per-tenant open-placement quotas (429 +
  ``Retry-After`` on breach) and weighted fair queueing over placement
  grants when submissions contend for the ``--max_inflight`` budget
  (fleet/tenants.py; ``X-ICT-Tenant`` header, absent -> "default").

The router is just another stdlib-HTTP daemon — ``serve-fleet`` on the
CLI, ``ThreadingHTTPServer`` + ``urllib`` inside, zero new dependencies
— and it exposes its own ``/metrics`` (placements, failovers, per-tenant
admissions/rejections, per-replica queue-depth gauges) so the obs tower
sees the fleet as one system.  Trace context crosses the hop: the
router forwards ``X-ICT-Trace`` on proxied submissions and emits
``fleet_placement`` / ``fleet_failover`` events into the event log and
the flight ring.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.parse
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from iterative_cleaner_tpu.campaign.orchestrator import CampaignOrchestrator
from iterative_cleaner_tpu.campaign.store import CampaignStore
from iterative_cleaner_tpu.fleet import alerts as fleet_alerts
from iterative_cleaner_tpu.fleet import autoscale as fleet_autoscale
from iterative_cleaner_tpu.fleet import cache as fleet_cache
from iterative_cleaner_tpu.fleet import canary as fleet_canary
from iterative_cleaner_tpu.fleet import capacity as fleet_capacity
from iterative_cleaner_tpu.fleet import costs as fleet_costs
from iterative_cleaner_tpu.fleet import history as fleet_history
from iterative_cleaner_tpu.fleet import obs as fleet_obs
from iterative_cleaner_tpu.fleet import slo as fleet_slo
from iterative_cleaner_tpu.fleet import trends as fleet_trends
from iterative_cleaner_tpu.fleet.client import (
    ReplicaClient,
    ReplicaRefused,
    ReplicaUnreachable,
)
from iterative_cleaner_tpu.fleet.registry import Replica, ReplicaRegistry
from iterative_cleaner_tpu.fleet.tenants import (
    DEFAULT_TENANT,
    SYNTHETIC_TENANT,
    QuotaExceeded,
    TenantAdmission,
    WeightedFairQueue,
)
from iterative_cleaner_tpu.fleet import explain as fleet_explain
from iterative_cleaner_tpu.obs import events, flight
from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.obs import tracing as obs_tracing
from iterative_cleaner_tpu.proving import recorder as fleet_recorder
from iterative_cleaner_tpu.service.scheduler import bucket_label
from iterative_cleaner_tpu.utils import backoff

#: Placement-score bonus for a replica whose warm pool already holds the
#: submission's shape bucket (it will compile nothing), and the smaller
#: bonus for one that merely has the bucket queued (its compile is paid
#: or in flight).  Units are "queued cubes": a warm replica wins ties
#: and small load deficits, but a deeply-backlogged warm replica still
#: loses to an idle cold one.
AFFINITY_WARM = 2.5
AFFINITY_QUEUED = 1.25

#: Placement-score PENALTY for a replica the straggler detector has
#: flagged (fleet/obs.py): bigger than both affinity bonuses combined, so
#: a slow-but-warm replica still loses to a healthy cold one, yet finite
#: — a fleet whose every survivor is flagged still places.
STRAGGLER_PENALTY = 4.0

#: Consecutive 404 status polls before an open placement is declared
#: lost (its replica restarted with a cleared spool and genuinely does
#: not know the job) and failed terminally.
MISSING_POLLS_LOST = 3

#: Default ceiling on the file size the fleet cache will hash at
#: placement time (the check runs synchronously in the HTTP handler);
#: ``ICT_FLEET_CACHE_MAX_BYTES`` overrides.
FLEET_CACHE_MAX_BYTES = 256 << 20


def _fleet_cache_max_bytes() -> int:
    try:
        return int(os.environ.get("ICT_FLEET_CACHE_MAX_BYTES",
                                  FLEET_CACHE_MAX_BYTES))
    except ValueError:
        return FLEET_CACHE_MAX_BYTES


class FleetBusy(RuntimeError):
    """No replica could take the job right now (all dead, draining, or
    at capacity, or the placement-grant wait timed out) — HTTP 503 with
    Retry-After, the replica admission-cap convention."""


@dataclass
class FleetConfig:
    replicas: tuple = ()             # replica base URLs, e.g. http://h:8750
    host: str = "127.0.0.1"
    port: int = 8790                 # 0 = ephemeral (tests)
    router_id: str = ""              # "" = mint one per process life
    poll_interval_s: float = 1.0     # health-poll + failover-sweep cadence
    dead_after: int = 3              # consecutive unreachable polls -> dead
    replica_timeout_s: float = 10.0  # per router->replica HTTP call
    max_inflight: int = 0            # fleet-wide open-placement budget
                                     # (0 = unbounded); contention beyond it
                                     # is arbitrated by weighted fair queueing
    queue_timeout_s: float = 30.0    # max wait for a placement grant
    failover_retries: int = 2        # extra candidate sweeps per submission
    retry_backoff_s: float = 0.25    # full-jitter base between sweeps
    placement_keep: int = 10000      # terminal placement records kept
    tenant_quotas: dict = field(default_factory=dict)
    tenant_weights: dict = field(default_factory=dict)
    tenant_budgets: dict = field(default_factory=dict)
                                     # advisory device-seconds budgets
                                     # (--tenant NAME:QUOTA:WEIGHT:BUDGET)
                                     # feeding tenant_budget_burn alert
                                     # rules — showback, never admission
                                     # (fleet/costs.py)
    default_quota: int = 0           # per-tenant open-placement cap (0 = off)
    default_weight: float = 1.0
    telemetry: str = ""              # JSON-lines event log (obs/events)
    spool_dir: str = "./ict_fleet_spool"   # router-side durable dir:
                                     # flight-ring dumps (<spool>/flight)
                                     # and incident bundles
                                     # (<spool>/fleet-incidents)
    straggler_factor: float = 3.0    # p50 multiple of the fleet median
                                     # that flags a replica (fleet/obs.py)
    straggler_polls: int = 3         # consecutive slow polls before firing
    straggler_window: int = 8        # polls of latency deltas per p50
    straggler_phase: str = "service_dispatch"  # the watched phase family
    slo_grant_s: float = 1.0         # per-tenant SLO on the WFQ grant
                                     # wait; beyond it (or a grant
                                     # timeout) burns fleet_slo_burn_total
    capacity_window: int = 8         # poll ticks per capacity-model rate
                                     # window (fleet/capacity.py)
    autoscale: str = "off"           # off | advise | act — the elastic
                                     # scaling loop (fleet/autoscale.py);
                                     # advise only emits recommendations
    min_replicas: int = 1            # alive floor the scaler respects
    max_replicas: int = 4            # alive ceiling
    scale_up_eta_s: float = 10.0     # backlog-drain ETA that counts as
                                     # "behind" toward a scale-up
    scale_up_polls: int = 3          # hysteresis: consecutive behind polls
    scale_down_polls: int = 6        # hysteresis: consecutive idle polls
    scale_idle_util: float = 0.05    # fleet utilization under this = idle
    scale_cooldown_s: float = 30.0   # quiet period after any decision
    spawn_retries: int = 3           # full-jitter spawn retry ladder depth
    spawn_args: tuple = ()           # extra ict-serve args for spawned
                                     # subprocess replicas (--spawn_arg)
    history_ticks: int = 128         # poll ticks of federated-metrics
                                     # history retained (fleet/history.py;
                                     # GET /fleet/metrics/history)
    default_alerts: bool = True      # install the default SLO rule pack
                                     # (fleet/alerts.py)
    alert_rules: tuple = ()          # extra rule specs (dicts, the
                                     # --alert_rule JSON shape) on top of
                                     # the default pack
    alert_webhook: str = ""          # POST each firing/resolved
                                     # transition here (full-jitter retry)
    alert_cmd: str = ""              # shell command per transition
                                     # (the JSON on stdin)
    alert_retries: int = 3           # delivery retries per sink
    canary_ticks: int = 0            # poll ticks between canary probe
                                     # rounds (fleet/canary.py; 0 = off)
    slo: tuple = ()                  # declarative SLO objective specs
                                     # (--slo JOURNEY:TARGET:WINDOW_TICKS;
                                     # fleet/slo.py)
    recorder: bool = True            # the production flight recorder
                                     # (proving/recorder.py): always on
                                     # unless --no_recorder / ICT_RECORDER=0
    recorder_segment_kb: int = 256   # open-segment size cap before a
                                     # seal rotates it
    recorder_keep: int = 16          # sealed segments retained
    trends: bool = True              # the durable performance-trend plane
                                     # (fleet/trends.py): multi-resolution
                                     # spool-persisted rollups + the
                                     # regression sentinel; off via
                                     # --no_trends / ICT_TRENDS=0
    trend_keep_raw: int = 128        # raw per-tick points kept per series
    trend_signals: tuple = ()        # extra/override fingerprint signal
                                     # specs (dicts, the --trend_signal
                                     # JSON shape) on top of the default
                                     # set; same-name specs replace
    trend_sentinel_k: int = 3        # consecutive out-of-band windows
                                     # before the sentinel fires
    trend_min_samples: int = 8       # accepted windows before a
                                     # fingerprint arms
    trend_band_mad: float = 4.0      # fingerprint band half-width in
                                     # MAD units
    trend_persist_every: int = 16    # poll ticks between trend-store
                                     # spool writes (stop() always
                                     # persists)
    quiet: bool = False


@dataclass
class Placement:
    """One routed job.  ``job_id`` is the fleet-visible identity — the id
    the FIRST accepting replica minted, which the client holds from its
    202; after a failover the serving replica (and its inner job id)
    change underneath while the fleet id stays stable, and proxied reads
    rewrite the manifest back to it."""

    job_id: str
    tenant: str
    trace_id: str
    payload: dict                   # forwarded verbatim on re-route, with
                                    # the idempotency key inside — the same
                                    # key is what makes re-routes dedupe
    base_url: str
    replica_id: str
    replica_job_id: str
    state: str = "open"             # open -> done | error
    error: str = ""
    attempts: int = 1               # placements incl. failover re-routes
    submitted_s: float = 0.0
    # Every (replica, replica_job_id) this placement has lived on, in
    # placement order — the cross-hop trace assembly walks these to
    # stitch a failed-over job's telemetry from BOTH replicas
    # (fleet/obs.py; mutated only under the router's placement lock).
    hops: list = field(default_factory=list)
    # Fleet-cache hits are placements born terminal: the recorded result
    # summary is served directly by job_manifest (no replica proxy, the
    # origin replica may be long gone) — None for ordinary placements.
    cached: dict | None = None
    missing_polls: int = 0          # consecutive status polls the serving
                                    # replica answered 404 — a revived
                                    # replica whose spool was cleared has
                                    # genuinely lost the job, and the
                                    # placement must fail terminally
                                    # instead of leaking its slot forever
    synthetic: bool = False         # a canary probe placement: it never
                                    # took an admission slot, a WFQ
                                    # grant, or capacity demand, so the
                                    # terminal transition must not hand
                                    # any of them back (fleet/canary.py)


def new_router_id() -> str:
    return f"fr-{uuid.uuid4().hex[:8]}"


def _json_safe(obj):
    """Replace IEEE specials with their string spellings so HTTP replies
    stay strict JSON (json.dumps would emit the non-standard
    ``Infinity`` token; ``float("inf")`` parses the string back)."""
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, float) and (obj != obj or obj in
                                   (float("inf"), float("-inf"))):
        return "nan" if obj != obj else (
            "inf" if obj > 0 else "-inf")
    return obj


class RouterMetrics:
    """The router's own tiny metric registry, rendered as Prometheus
    text on ``/metrics``.  Deliberately NOT the process-global
    obs.tracing registry: fleet tests run a router and three replicas in
    one process, and the router's counters must not bleed into (or read
    from) the replicas' — each HTTP surface exposes exactly its own
    process role."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (family, ((label, value), ...)) -> float
        self._counters: dict = {}  # ict: guarded-by(self._lock)
        self._gauges: dict = {}  # ict: guarded-by(self._lock)
        # (family, label_pairs) -> [per-bucket counts (len(HIST_BOUNDS)
        # + 1, trailing +Inf overflow), running sum] on the fixed log2
        # bounds — the canary journey-latency histograms.
        self._hists: dict = {}  # ict: guarded-by(self._lock)

    @staticmethod
    def _key(family: str, labels: dict | None):
        return (family, tuple(sorted((labels or {}).items())))

    def count(self, family: str, labels: dict | None = None,
              inc: float = 1.0) -> None:
        key = self._key(family, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + inc

    def counter_value(self, family: str, labels: dict | None = None) -> float:
        with self._lock:
            return self._counters.get(self._key(family, labels), 0.0)

    def counter_total(self, family: str) -> float:
        with self._lock:
            return sum(v for (fam, _), v in self._counters.items()
                       if fam == family)

    def set_gauge(self, family: str, labels: dict | None,
                  value: float) -> None:
        with self._lock:
            self._gauges[self._key(family, labels)] = float(value)

    def observe_hist(self, family: str, labels: dict | None,
                     value: float) -> None:
        """One observation into a fixed log2-bounds histogram series
        (the obs/tracing bucket walk; series appear on first
        observation or via :meth:`ensure_hist`)."""
        key = self._key(family, labels)
        with self._lock:
            rec = self._hists.get(key)
            if rec is None:
                rec = [[0.0] * (len(obs_tracing.HIST_BOUNDS) + 1), 0.0]
                self._hists[key] = rec
            buckets = rec[0]
            for i, bound in enumerate(obs_tracing.HIST_BOUNDS):
                if value <= bound:
                    buckets[i] += 1.0
                    break
            else:
                buckets[-1] += 1.0
            rec[1] += float(value)

    def ensure_hist(self, family: str, labels: dict | None) -> None:
        """Pre-register one zero-count histogram series (the gauge
        pre-registration lesson applied to histograms: a documented
        family must be live on the first scrape)."""
        key = self._key(family, labels)
        with self._lock:
            self._hists.setdefault(
                key, [[0.0] * (len(obs_tracing.HIST_BOUNDS) + 1), 0.0])

    def replace_gauge_family(self, family: str,
                             entries: dict[tuple, float]) -> None:
        """Swap every sample of one gauge family atomically — per-replica
        and per-bucket gauges are rebuilt from each health poll, and a
        bucket that drained (or a replica that left) must drop off the
        exposition rather than freeze at its last value."""
        with self._lock:
            for key in [k for k in self._gauges if k[0] == family]:
                del self._gauges[key]
            for labels, value in entries.items():
                self._gauges[(family, tuple(sorted(labels)))] = float(value)

    def render(self) -> str:
        """Prometheus text exposition via the ONE shared renderer in
        obs/metrics.py (render_registries) — the registry is deliberately
        separate from the process-global one, the grammar implementation
        is not (pinned by the strict-regex test in tests/test_fleet.py)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {key: (obs_tracing.HIST_BOUNDS, list(rec[0]), rec[1])
                     for key, rec in self._hists.items()}
        return obs_metrics.render_registries(counters, gauges, hists=hists)


class _Ticket:
    """One submission waiting for a placement grant; written only under
    the router's placement condition lock."""

    __slots__ = ("granted", "abandoned")

    def __init__(self) -> None:
        self.granted = False
        self.abandoned = False


class FleetRouter:
    """Lifecycle + the placement engine.  Thread layout (all daemonic):
    the ThreadingHTTPServer's per-request threads (submissions block in
    the WFQ grant wait; reads are lock-snapshot cheap) and ONE poll
    thread (health refresh, placement-status refresh, failover sweep,
    gauge rebuild).  All shared state sits behind ``self._cond``'s lock
    (placements, inflight budget, WFQ) or the registry's/metrics' own
    locks — acquisition order is always router -> registry/metrics,
    never the reverse."""

    def __init__(self, cfg: FleetConfig, replica_factory=None) -> None:
        if not cfg.replicas:
            raise ValueError("a fleet needs at least one --replica URL")
        self.cfg = cfg
        self.router_id = cfg.router_id or new_router_id()
        self.started_s = time.time()
        self.client = ReplicaClient(timeout_s=cfg.replica_timeout_s)
        self.registry = ReplicaRegistry(
            [u.rstrip("/") for u in cfg.replicas],
            dead_after=cfg.dead_after)
        self.admission = TenantAdmission(
            quotas=cfg.tenant_quotas, default_quota=cfg.default_quota)
        self.metrics = RouterMetrics()
        # RLock, deliberately: the grant pump (_grant_free_slots) takes it
        # lexically so every _inflight mutation sits under a visible
        # ``with self._lock:`` (the ICT007 discipline), and its callers
        # already hold the lock when pumping after a state change.
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._wfq = WeightedFairQueue(
            weights=cfg.tenant_weights, default_weight=cfg.default_weight)
        # The fleet observability plane (fleet/obs.py): per-replica
        # /metrics + flight-ring scrape cache, the bounded cross-hop span
        # store, and the windowed straggler detector — each owns its own
        # lock, always acquired AFTER the router's (never while holding
        # theirs), so the router -> registry/metrics order extends to
        # router -> obs cleanly.
        self.scrapes = fleet_obs.ScrapeCache()
        self.traces = fleet_obs.TraceStore()
        self.straggler = fleet_obs.StragglerDetector(
            factor=cfg.straggler_factor, polls=cfg.straggler_polls,
            window=cfg.straggler_window)
        # The capacity model (fleet/capacity.py): fed by the same poll
        # tick, rendered as ict_fleet_capacity_* gauges and
        # GET /fleet/capacity; its lock too sits strictly after the
        # router's in the acquisition order.
        self.capacity = fleet_capacity.CapacityModel(
            window=cfg.capacity_window,
            dispatch_phase=cfg.straggler_phase)
        # The alerting plane (ISSUE 12): the bounded federated-metrics
        # history ring fed once per poll tick from the exposition the
        # router already serves (zero new scrape traffic), and the
        # declarative rule engine evaluated over it.  Both own their own
        # locks, acquired strictly AFTER the router's RLock and never
        # while calling out — the router -> obs/capacity order extends to
        # history/alerts unchanged.
        self.history = fleet_history.MetricsHistory(keep=cfg.history_ticks)
        rules: list[fleet_alerts.AlertRule] = []
        if cfg.default_alerts:
            rules.extend(fleet_alerts.default_rule_pack(
                poll_interval_s=cfg.poll_interval_s,
                scale_up_eta_s=cfg.scale_up_eta_s,
                autoscale=cfg.autoscale))
        # Tenant budgets install their advisory burn rules next to the
        # default pack (fleet/costs.py; present regardless of
        # --no_default_alerts — a declared budget nobody watches would
        # be a lie); operator --alert_rule names still override.
        rules.extend(fleet_costs.budget_rules(cfg.tenant_budgets))
        # SLO burn-rate rules (fleet/slo.py; ISSUE 18): two multiwindow
        # rules per declared objective over the router-computed
        # ict_sli_burn_rate gauge, installed the budget_rules way —
        # before the operator loop, so --alert_rule names still replace.
        self._slo_objectives = fleet_slo.parse_slo_specs(cfg.slo)
        rules.extend(fleet_slo.burn_rules(self._slo_objectives))
        # The regression sentinel's bridge rule (fleet/trends.py; ISSUE
        # 20): one source="trend" rule over the
        # ict_fleet_perf_regression gauge the trend plane republishes
        # each tick — it fires per series, so one rule covers every
        # fingerprint key.  Installed the budget_rules way, before the
        # operator loop, so --alert_rule names still replace.
        self._trends_enabled = (cfg.trends
                                and os.environ.get("ICT_TRENDS",
                                                   "1") != "0")
        if self._trends_enabled:
            rules.extend(fleet_trends.trend_rules())
        for spec in cfg.alert_rules:
            rule = (spec if isinstance(spec, fleet_alerts.AlertRule)
                    else fleet_alerts.parse_rule(spec))
            # An operator rule re-using a default name REPLACES the
            # default (how a threshold is tuned without --no_default_alerts).
            rules = [r for r in rules if r.name != rule.name]
            rules.append(rule)
        self.alerts = fleet_alerts.AlertEngine(
            rules, history_ticks=cfg.history_ticks)
        self.alert_sinks = fleet_alerts.AlertSinks(
            webhook=cfg.alert_webhook, command=cfg.alert_cmd,
            retries=cfg.alert_retries,
            retry_backoff_s=cfg.retry_backoff_s, quiet=cfg.quiet,
            note=lambda sink, status: self.metrics.count(
                "fleet_alert_notifications_total",
                {"sink": sink, "status": status}))
        # The elastic-scaling loop (fleet/autoscale.py), off by default.
        # The supervisor spawns in-process replicas when the embedder
        # hands in a factory (tests, the autoscale smoke) and real
        # ict-serve subprocesses otherwise, rooted under the router
        # spool.
        self.supervisor = None
        self.autoscaler = None
        if cfg.autoscale != "off":
            factory = replica_factory or fleet_autoscale.\
                SubprocessReplicaFactory(
                    os.path.join(cfg.spool_dir, "replicas"),
                    extra_args=cfg.spawn_args)
            self.supervisor = fleet_autoscale.ReplicaSupervisor(
                factory, self.registry, self.client,
                spawn_retries=cfg.spawn_retries,
                retry_backoff_s=cfg.retry_backoff_s,
                note_spawn_failure=lambda: self.metrics.count(
                    "fleet_scale_events_total",
                    {"direction": "up", "reason": "spawn_failed"}),
                quiet=cfg.quiet)
            self.autoscaler = fleet_autoscale.Autoscaler(
                fleet_autoscale.AutoscaleConfig(
                    mode=cfg.autoscale,
                    min_replicas=cfg.min_replicas,
                    max_replicas=cfg.max_replicas,
                    scale_up_eta_s=cfg.scale_up_eta_s,
                    up_polls=cfg.scale_up_polls,
                    down_polls=cfg.scale_down_polls,
                    idle_utilization=cfg.scale_idle_util,
                    cooldown_s=cfg.scale_cooldown_s))
        # The fleet-wide content-addressed result index (fleet/cache.py;
        # ROADMAP item 2's reuse half): learned from the terminal
        # manifests the status polls already observe, checked at
        # placement time so byte-identical resubmissions return without
        # touching any replica.  Owns its own lock, acquired strictly
        # after the router's, never while calling out.
        self.result_index = fleet_cache.FleetResultIndex()
        # The cost-accounting fold (fleet/costs.py): rebuilt once per
        # poll tick from the scrape cache, served at GET /fleet/costs.
        self._costs_snapshot: dict = {}  # ict: guarded-by(self._lock)
        # Pre-register the budget gauge at 0 for every budgeted tenant
        # (the daemon's ict_cost_* pre-registration lesson, router
        # side): the burn rules are gt thresholds, and the series must
        # exist before the first placement for firing AND resolution to
        # work from the first tick.
        self.metrics.replace_gauge_family(
            "fleet_tenant_budget_used_pct",
            {(("tenant", t),): 0.0 for t in cfg.tenant_budgets})
        # The survey-campaign orchestrator (campaign/): spool-persisted
        # under <spool>/campaigns/, rehydrated NOW so a restarted router
        # resumes open campaigns from its first poll tick.  Its lock
        # orders strictly after the router's: it snapshots its own state,
        # calls place_job/job_manifest UNLOCKED, then re-acquires to
        # record (campaign/orchestrator.py).
        self.campaigns = CampaignOrchestrator(
            CampaignStore(os.path.join(cfg.spool_dir, "campaigns")),
            self, quiet=cfg.quiet)
        # Pre-register every ict_campaign_* gauge family — zero-valued
        # aggregates plus whatever the rehydrate brought back — so the
        # documented families are live on every exposition from the
        # first scrape (the budget-gauge pre-registration lesson;
        # tests/test_metric_docs.py), not only once a campaign exists.
        for family, entries in self.campaigns.gauge_families().items():
            self.metrics.replace_gauge_family(family, entries)
        # The SLI/error-budget plane (fleet/slo.py) — ALWAYS constructed
        # (SLIs render for every journey even with no --slo objectives;
        # the spool-persisted ledger rehydrates budget accounting across
        # restarts) — and the black-box canary prober (fleet/canary.py)
        # probing the router's own public HTTP surface on the
        # --canary_ticks cadence.
        self.slo = fleet_slo.SloPlane(
            self._slo_objectives, cfg.spool_dir, metrics=self.metrics,
            quiet=cfg.quiet)
        self.canary = fleet_canary.CanaryProber(
            cfg.spool_dir,
            lambda: f"http://{self.cfg.host}:{self.port}",
            quiet=cfg.quiet)
        self.canary.slo = self.slo
        self.canary.on_mask_mismatch = self._canary_mismatch
        # Poll ticks until the next canary round (counts down each
        # _slo_tick when probing is enabled; first round fires on the
        # first tick so the smoke and a fresh fleet get a verdict
        # immediately).
        self._ticks_to_canary = 1 if cfg.canary_ticks > 0 else 0  # ict: guarded-by(self._lock)
        # Pre-register the whole SLI/canary surface at zero (the budget
        # gauge lesson): gauges via the plane's own families, counters
        # and the journey-latency histogram as explicit zero series, so
        # every documented ict_sli_*/ict_canary_* family is live on the
        # first scrape and burn rules can fire AND resolve from tick 1.
        for family, entries in self.slo.gauge_families().items():
            self.metrics.replace_gauge_family(family, entries)
        for j in fleet_slo.JOURNEYS:
            self.metrics.count("sli_good_events_total", {"journey": j},
                               inc=0.0)
            self.metrics.count("sli_bad_events_total", {"journey": j},
                               inc=0.0)
        for j in fleet_slo.CANARY_JOURNEYS:
            for outcome in ("ok", "fail"):
                self.metrics.count("canary_probes_total",
                                   {"journey": j, "outcome": outcome},
                                   inc=0.0)
            self.metrics.count("canary_mask_mismatches_total",
                               {"journey": j}, inc=0.0)
            self.metrics.ensure_hist(fleet_slo.CANARY_HIST_FAMILY,
                                     {"journey": j})
        # The production flight recorder (proving/recorder.py; ISSUE 19):
        # every REAL submission lands on a bounded, rotated segment set
        # under <spool>/fleet-traces in the proving-ground trace grammar
        # — synthetic canary/soak traffic is refused inside record(), by
        # construction, and ICT_RECORDER=0 (or --no_recorder) disables
        # recording while keeping the read surface live.  Its lock sits
        # strictly after the router's, file appends only, never HTTP.
        self.recorder = fleet_recorder.FlightRecorder(
            os.path.join(cfg.spool_dir, "fleet-traces"),
            max_segment_kb=cfg.recorder_segment_kb,
            keep=cfg.recorder_keep,
            enabled=(cfg.recorder
                     and os.environ.get("ICT_RECORDER", "1") != "0"),
            quiet=cfg.quiet)
        # Counter mirrors are delta-fed from the recorder's own totals
        # once per poll tick (_recorder_tick); the whole ict_recorder_*
        # surface is pre-registered at zero NOW (the budget-gauge
        # lesson) so every documented family is live on the first scrape.
        self._recorder_seen: dict = {}  # ict: guarded-by(self._lock)
        for fam in ("recorder_entries_total", "recorder_excluded_total",
                    "recorder_dropped_total",
                    "recorder_segments_sealed_total"):
            self.metrics.count(fam, inc=0.0)
        self._recorder_tick()
        # The durable performance-trend plane (fleet/trends.py; ISSUE
        # 20): multi-resolution spool-persisted rollups over the SAME
        # parsed exposition the history ring records (zero new scrape
        # traffic), performance fingerprints, and the regression
        # sentinel.  Rehydrated NOW from <spool>/trends so the rings
        # survive a restart; its locks sit strictly after the router's.
        self.trends = None
        if self._trends_enabled:
            specs = {s.name: s for s in fleet_trends.default_signals()}
            for spec in cfg.trend_signals:
                s = (spec if isinstance(spec, fleet_trends.SignalSpec)
                     else fleet_trends.parse_signal(spec))
                specs[s.name] = s
            baseline = os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                "docs", "bench_baseline_cpu.json")
            self.trends = fleet_trends.TrendPlane(fleet_trends.TrendConfig(
                spool_dir=cfg.spool_dir,
                keep_raw=cfg.trend_keep_raw,
                signals=tuple(specs.values()),
                sentinel_k=cfg.trend_sentinel_k,
                min_samples=cfg.trend_min_samples,
                band_mad=cfg.trend_band_mad,
                persist_every=cfg.trend_persist_every,
                baseline_path=baseline if os.path.isfile(baseline) else "",
                quiet=cfg.quiet))
        # Pre-register the whole ict_fleet_trend_* surface at zero (the
        # budget-gauge lesson) so every documented family is live on the
        # first scrape regardless of whether a rollup has sealed or a
        # persist has run; the regression counter rides along so the
        # sentinel's firing increment is a delta on an existing series.
        self.metrics.count("fleet_trend_ticks_total", inc=0.0)
        for res in fleet_trends.RESOLUTIONS:
            self.metrics.count("fleet_trend_rollups_total",
                               {"resolution": f"{res}s"}, inc=0.0)
        self.metrics.count("fleet_trend_persist_total", inc=0.0)
        self.metrics.count("fleet_trend_persist_errors_total", inc=0.0)
        self.metrics.count("fleet_perf_regressions_total", inc=0.0)
        self.metrics.set_gauge("fleet_trend_enabled", None,
                               1.0 if self.trends is not None else 0.0)
        self.metrics.set_gauge("fleet_trend_series", None,
                               float(self.trends.store.series_count())
                               if self.trends is not None else 0.0)
        # Persist-counter delta mirror (the recorder discipline: the
        # plane's totals are authoritative, counters only move forward).
        self._trend_persist_seen: dict = {}  # ict: guarded-by(self._lock)
        # Streaming-session proxy routes: fleet session id -> (replica
        # base_url, trace_id), bounded FIFO so an abandoned session can
        # never grow the map without bound.
        self._session_routes: dict[str, tuple] = {}  # ict: guarded-by(self._lock)
        # Last observed (audit_divergences, backend) per replica: the
        # incident watch fires a bundle when divergences move or a
        # replica demotes jax -> numpy between polls.
        self._health_seen: dict[str, tuple[float, str]] = {}  # ict: guarded-by(self._lock)
        self._last_poll_mono = 0.0  # monotonic stamp of the last completed poll_tick  # ict: guarded-by(self._lock)
        self._placements: dict[str, Placement] = {}  # ict: guarded-by(self._lock)
        # True while an acted scale-up's spawn thread runs: the
        # autoscaler takes no new verdict mid-spawn (the fleet's size is
        # in motion), but the poll loop itself stays live behind it.
        self._scale_in_flight = False  # ict: guarded-by(self._lock)
        # idempotency key -> fleet job id ("" while a placement carrying
        # the key is in flight): the ROUTER-side half of the dedupe — a
        # client retry with a pinned key must not run the job again on a
        # DIFFERENT replica (the replica-side map only covers retries
        # that land on the same one).  Trimmed with the placement table.
        self._idem_index: dict[str, str] = {}  # ict: guarded-by(self._lock)
        self._inflight = 0  # ict: guarded-by(self._lock)
        # One shared full-jitter RNG for failover backoff; drawn under its
        # own lock (random.Random is not documented thread-safe, and the
        # ICT_BACKOFF_SEED test hook wants one reproducible stream).
        self._rng_lock = threading.Lock()
        self._backoff_rng = backoff.make_rng()  # ict: guarded-by(self._rng_lock)
        self._stop_evt = threading.Event()
        self._threads: list[threading.Thread] = []
        self._server = None
        self.port = cfg.port

    @property
    def flight_dir(self) -> str:
        return os.path.join(self.cfg.spool_dir, "flight")

    @property
    def incident_dir(self) -> str:
        return os.path.join(self.cfg.spool_dir, "fleet-incidents")

    @property
    def alert_dir(self) -> str:
        return os.path.join(self.cfg.spool_dir, "fleet-alerts")

    # --- lifecycle ---

    def start(self) -> None:
        # Same contract as the daemon: telemetry="" must MEAN "honor
        # ICT_TELEMETRY / disabled", never inherit a predecessor's sink.
        events.configure(self.cfg.telemetry or None)
        flight.note("router_starting", router_id=self.router_id,
                    replicas=len(self.cfg.replicas))
        # Synchronous first poll: replica identities and load snapshots
        # exist before the first placement decision.
        self.registry.poll_once(self.client)
        self._update_replica_gauges()
        th = threading.Thread(target=self._poll_loop, daemon=True,
                              name=f"ict-fleet-poll-{self.router_id}")
        th.start()
        self._threads.append(th)
        self._server = ThreadingHTTPServer(
            (self.cfg.host, self.cfg.port), _RouterHandler)
        self._server.daemon_threads = True
        self._server.router = self
        self.port = self._server.server_address[1]
        th = threading.Thread(target=self._server.serve_forever, daemon=True,
                              name=f"ict-fleet-http-{self.router_id}")
        th.start()
        self._threads.append(th)
        if not self.cfg.quiet:
            alive = sum(1 for r in self.registry.snapshot() if r["alive"])
            print(f"ict-fleet: router {self.router_id} listening on "
                  f"http://{self.cfg.host}:{self.port} "
                  f"({alive}/{len(self.cfg.replicas)} replicas alive)",
                  file=sys.stderr)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self.supervisor is not None:
            # Managed replicas die with their router (their spools keep
            # any unfinished accepted work for the next life).
            self.supervisor.stop_all()
        self.alert_sinks.stop()
        self._stop_evt.set()
        with self._lock:
            self._cond.notify_all()
        for th in self._threads:
            th.join(timeout=10)
        # Final trend-store persist AFTER the poll thread is down (no
        # tick can race the snapshot): a restarted router rehydrates
        # rings byte-identical to what this life last saw.
        if self.trends is not None:
            self.trends.persist(force=True)

    # --- the poll loop: health, status refresh, failover, gauges ---

    def _poll_loop(self) -> None:
        while not self._stop_evt.wait(self.cfg.poll_interval_s):
            try:
                self.poll_tick()
            except Exception as exc:  # noqa: BLE001 — the fleet control
                # loop (death detection, failover, grant pump) must
                # outlive any one tick's surprise; poll_tick itself stays
                # raising so tests and the smoke see errors loudly.
                self.metrics.count("fleet_poll_errors_total")
                if not self.cfg.quiet:
                    print(f"ict-fleet: poll tick failed ({exc!r}); "
                          "continuing", file=sys.stderr)

    def poll_tick(self) -> None:
        """One maintenance pass; public so tests (and the smoke check)
        can drive the loop deterministically instead of sleeping."""
        newly_dead = self.registry.poll_once(self.client)
        for rep in newly_dead:
            if not self.cfg.quiet:
                print(f"ict-fleet: replica {rep.replica_id or rep.base_url} "
                      f"is dead after {rep.consecutive_failures} failed "
                      "health checks; re-routing its open placements",
                      file=sys.stderr)
            # Death eviction takes its flight ring and metrics to the
            # grave — except for what the scrape cache already holds:
            # snapshot it into an incident bundle NOW.
            self._note_incident("replica_death",
                                replica_id=rep.replica_id or rep.base_url)
        self._scrape_replicas()
        self._watch_replica_health()
        self._refresh_open_placements()
        self._failover_sweep()
        self._update_replica_gauges()
        self._update_capacity()
        self._update_costs()
        self._campaign_tick()
        self._slo_tick()
        self._recorder_tick()
        self._autoscale_tick()
        self._history_alert_tick()
        self._trim_placements()
        with self._lock:
            self._last_poll_mono = time.monotonic()
        # Replica capacity may have freed (placements turned terminal) —
        # wake any submissions parked in the WFQ grant wait.
        self._grant_free_slots()

    def _scrape_replicas(self) -> None:
        """Metrics federation's inbound half: pull every live replica's
        ``/metrics`` (strict-parsed) and ``/debug/flight`` (the
        best-effort pre-death cache) into the scrape cache, feed the
        straggler detector, and rebuild the staleness/straggler gauges.
        Scrapes run CONCURRENTLY (the registry poll_once discipline): one
        wedged replica costs the tick one timeout, not one per healthy
        replica behind it."""
        # Every ALIVE replica is scraped — a draining one still serves
        # accepted work and its latency belongs in the fleet view.
        rows = [r for r in self.registry.snapshot() if r["alive"]]

        def scrape(row: dict):
            rid = row["replica_id"] or row["base_url"]
            try:
                text = self.client.metrics_text(row["base_url"])
                families = obs_metrics.parse_exposition(text)
            except (ReplicaUnreachable, ReplicaRefused, ValueError):
                # Liveness is the health poll's job; a failed scrape just
                # marks the cached copy stale (visible on the age gauge).
                return rid, None, None, None
            try:
                ring = self.client.flight(row["base_url"])
                flight_events = list(ring.get("events", []))
            except (ReplicaUnreachable, ReplicaRefused):
                flight_events = None   # keep the previous cached ring
            return rid, text, families, flight_events

        if rows:
            with ThreadPoolExecutor(
                    max_workers=min(8, len(rows)),
                    thread_name_prefix="ict-fleet-scrape") as pool:
                results = list(pool.map(scrape, rows))
        else:
            results = []
        for rid, text, families, flight_events in results:
            if families is None:
                self.scrapes.note_failure(rid)
            else:
                self.scrapes.update(rid, text, families, flight_events)
        snap = self.scrapes.snapshot()
        cum = {rid: fleet_obs.phase_hist_cum(rec["families"],
                                             self.cfg.straggler_phase)
               for rid, rec in snap.items() if rec["ok"]}
        verdict = self.straggler.update(cum)
        for rid in verdict["fired"]:
            self.metrics.count("fleet_straggler_flags_total",
                               {"replica": rid})
            if events.active():
                events.emit("fleet_straggler", replica_id=rid,
                            p50_s=verdict["p50"].get(rid),
                            fleet_median_s=verdict["median"])
            if not self.cfg.quiet:
                print(f"ict-fleet: replica {rid} flagged as a straggler "
                      f"(p50 {verdict['p50'].get(rid)}s vs fleet median "
                      f"{verdict['median']}s); de-prioritizing placements",
                      file=sys.stderr)
        for rid in verdict["cleared"]:
            if events.active():
                events.emit("fleet_straggler_cleared", replica_id=rid)
            if not self.cfg.quiet:
                print(f"ict-fleet: replica {rid} recovered; straggler "
                      "flag cleared", file=sys.stderr)
        ages = self.scrapes.ages()
        self.metrics.replace_gauge_family(
            "fleet_scrape_ok",
            {(("replica", rid),): (1.0 if rec["ok"] else 0.0)
             for rid, rec in snap.items()})
        self.metrics.replace_gauge_family(
            "fleet_scrape_age_seconds",
            {(("replica", rid),): age for rid, age in ages.items()})
        # A flagged replica whose scrape just failed stays on the gauge
        # (the detector keeps its flag) — union, not just this tick's cum.
        self.metrics.replace_gauge_family(
            "fleet_stragglers",
            {(("replica", rid),): (1.0 if rid in verdict["stragglers"]
                                   else 0.0)
             for rid in set(cum) | verdict["stragglers"]})
        self.metrics.replace_gauge_family(
            "fleet_replica_p50_seconds",
            {(("replica", rid),): p for rid, p in verdict["p50"].items()})

    def _watch_replica_health(self) -> None:
        """Fire an incident bundle when a replica's correctness health
        moves between polls: audit divergences counted up, or the backend
        demoted jax -> numpy (the worker ladder's top rung)."""
        for row in self.registry.snapshot():
            rid = row["replica_id"] or row["base_url"]
            if not row["alive"]:
                continue
            div = float(row.get("audit_divergences", 0) or 0)
            backend = str(row.get("backend", "") or "")
            with self._lock:
                prev = self._health_seen.get(rid)
                self._health_seen[rid] = (div, backend)
            if prev is None:
                continue
            if div > prev[0]:
                self._note_incident("audit_divergence", replica_id=rid)
            if prev[1] == "jax" and backend == "numpy":
                self._note_incident("backend_demotion", replica_id=rid)

    def _note_incident(self, reason: str, replica_id: str = "",
                       job_id: str = "", trace_id: str = "") -> str | None:
        """Snapshot the fleet's state into one incident bundle
        (fleet/obs.py): placement table, registry, the replica's last
        good scrape + cached flight ring, and — for job-scoped incidents
        — the stitched trace."""
        scrape = self.scrapes.snapshot().get(replica_id, {})
        trace = None
        if trace_id:
            code, payload = self.fleet_trace(trace_id)
            if code == 200:
                trace = payload
        with self._lock:
            placements = [{
                "job_id": p.job_id, "tenant": p.tenant,
                "trace_id": p.trace_id, "state": p.state,
                "replica_id": p.replica_id, "attempts": p.attempts,
            } for p in self._placements.values()]
        path = fleet_obs.write_incident_bundle(
            self.incident_dir, reason=reason, replica_id=replica_id,
            job_id=job_id, trace_id=trace_id, placements=placements,
            replicas=self.registry.snapshot(),
            metrics_text=scrape.get("text", ""),
            flight_events=scrape.get("flight"), trace=trace)
        self.metrics.count("fleet_incidents_total", {"reason": reason})
        if events.active():
            events.emit("fleet_incident", trace_id=trace_id, reason=reason,
                        replica_id=replica_id, job_id=job_id,
                        bundle=path or "")
        return path

    def _refresh_open_placements(self) -> None:
        with self._lock:
            open_now = [p for p in self._placements.values()
                        if p.state == "open"]
        # One wedged replica must not stall every placement's refresh for
        # a timeout each: after the first transport failure to a URL this
        # tick, its remaining placements are skipped (the death countdown
        # and the failover sweep own them from here).
        unreachable_now: set[str] = set()
        for p in open_now:
            rep = self.registry.get(p.base_url)
            if (rep is None or not rep.alive
                    or p.base_url in unreachable_now):
                continue   # the failover sweep owns unreachable replicas
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
            except ReplicaRefused as exc:
                if exc.status != 404:
                    continue
                # A 404 right after accept is just spool-visibility lag —
                # but a replica that KEEPS not knowing the job has lost it
                # (restarted with a cleared spool inside the death
                # window): fail the placement terminally instead of
                # leaking its slot and quota forever.
                with self._lock:
                    p.missing_polls += 1
                    gone = p.missing_polls >= MISSING_POLLS_LOST
                if gone:
                    self._mark_terminal(
                        p, "error",
                        error=f"job {p.replica_job_id} vanished from "
                              f"replica {p.replica_id} (restarted with a "
                              "cleared spool?)")
                continue
            except ReplicaUnreachable:
                unreachable_now.add(p.base_url)
                dead = self.registry.note_unreachable(p.base_url)
                if dead is not None:
                    if not self.cfg.quiet:
                        print(f"ict-fleet: replica {dead.replica_id} died "
                              "mid-status-poll", file=sys.stderr)
                    # Every alive->dead flip writes its incident bundle,
                    # whichever path observed it (poll_tick covers the
                    # health-poll flips).
                    self._note_incident(
                        "replica_death",
                        replica_id=dead.replica_id or dead.base_url)
                continue
            with self._lock:
                p.missing_polls = 0
            self._observe_manifest(p, manifest)

    def _failover_sweep(self) -> None:
        """Re-route every open placement whose replica is dead.  Runs on
        the poll thread only; a sweep that cannot place (everyone busy)
        leaves the placement open for the next tick — re-routing is
        idempotent because the replica-side idempotency key rides inside
        the stored payload."""
        with self._lock:
            stranded = [p for p in self._placements.values()
                        if p.state == "open"]
        for p in stranded:
            rep = self.registry.get(p.base_url)
            if rep is not None and rep.alive:
                continue
            from_id = p.replica_id or p.base_url
            try:
                new_rep, body = self._submit_with_failover(
                    p.payload, p.trace_id, exclude={p.base_url})
            except FleetBusy:
                continue           # next tick retries
            except ReplicaRefused as exc:
                # A re-route the fleet *rejected* (e.g. the surviving
                # replicas' --root refuses the path): the job can never
                # complete — surface it as a terminal error instead of
                # sweeping it forever.
                self._mark_terminal(p, "error", error=str(exc))
                continue
            with self._lock:
                p.base_url = new_rep.base_url
                p.replica_id = new_rep.replica_id
                p.replica_job_id = str(body.get("id", p.replica_job_id))
                p.attempts += 1
                p.hops.append({"replica_id": new_rep.replica_id,
                               "base_url": new_rep.base_url,
                               "replica_job_id": p.replica_job_id,
                               "ts": round(time.time(), 6)})
            self.metrics.count("fleet_failovers_total",
                               {"from_replica": from_id})
            self.traces.record(p.trace_id, "fleet_failover",
                               job_id=p.job_id, from_replica=from_id,
                               to_replica=new_rep.replica_id,
                               attempts=p.attempts)
            if events.active():
                events.emit("fleet_failover", trace_id=p.trace_id,
                            job_id=p.job_id, from_replica=from_id,
                            to_replica=new_rep.replica_id,
                            tenant=p.tenant, attempts=p.attempts)
            if not self.cfg.quiet:
                print(f"ict-fleet: job {p.job_id} re-routed "
                      f"{from_id} -> {new_rep.replica_id}", file=sys.stderr)
            # The failover incident carries the stitched trace — the
            # dead hop's spans come from the pre-death flight cache.
            self._note_incident("failover", replica_id=from_id,
                                job_id=p.job_id, trace_id=p.trace_id)

    def _update_replica_gauges(self) -> None:
        snap = self.registry.snapshot()
        states = {"alive": 0, "draining": 0, "dead": 0}
        depth: dict[tuple, float] = {}
        buckets: dict[tuple, float] = {}
        for row in snap:
            rid = row["replica_id"] or row["base_url"]
            if not row["alive"]:
                states["dead"] += 1
            elif row["draining"]:
                states["draining"] += 1
            else:
                states["alive"] += 1
            for queue in ("open_jobs", "load_queue_depth",
                          "dispatch_queue_depth", "bucketed_cubes"):
                depth[(("queue", queue), ("replica", rid))] = float(
                    row.get(queue, 0) or 0)
            for bucket, n in row["bucket_queue_depths"].items():
                buckets[(("bucket", str(bucket)), ("replica", rid))] = float(n)
        self.metrics.replace_gauge_family(
            "fleet_replicas",
            {(("state", s),): float(n) for s, n in states.items()})
        self.metrics.replace_gauge_family("fleet_replica_queue_depth", depth)
        self.metrics.replace_gauge_family(
            "fleet_replica_bucket_queue_depth", buckets)
        with self._lock:
            open_n = sum(1 for p in self._placements.values()
                         if p.state == "open")
            queued = len(self._wfq)
        self.metrics.replace_gauge_family(
            "fleet_open_placements", {(): float(open_n)})
        self.metrics.replace_gauge_family(
            "fleet_queued_submissions", {(): float(queued)})

    def _update_capacity(self) -> None:
        """Fold this tick's registry + scrape snapshots into the capacity
        model and republish every ict_fleet_capacity_* /
        ict_fleet_backlog_eta_seconds gauge family whole (fleet/capacity.py
        — the figures every scale decision must be reconstructible
        from)."""
        self.capacity.update(self.registry.snapshot(),
                             self.scrapes.snapshot())
        for family, entries in self.capacity.gauge_families().items():
            self.metrics.replace_gauge_family(family, entries)

    def _update_costs(self) -> None:
        """Fold this tick's scrape cache into the fleet cost view
        (fleet/costs.py) and republish the budget-usage and
        conservation-ratio gauge families whole — the same
        snapshot-then-replace discipline as the capacity model, zero new
        scrape traffic."""
        snap = fleet_costs.fold(self.registry.snapshot(),
                                self.scrapes.snapshot(),
                                self.cfg.tenant_budgets)
        with self._lock:
            self._costs_snapshot = snap
        for family, entries in fleet_costs.gauge_families(
                snap, self.cfg.tenant_budgets).items():
            self.metrics.replace_gauge_family(family, entries)

    def _campaign_tick(self) -> None:
        """Advance every open campaign (observe placed archives, submit
        pending ones under their pinned idempotency keys, finish settled
        campaigns — campaign/orchestrator.py) and republish the
        ``ict_campaign_*`` gauge families whole, the capacity/cost
        snapshot-then-replace discipline.  Runs right after the cost
        fold so a tick that completes an archive also sees its
        CostRecord land in the same pass."""
        self.campaigns.tick()
        for family, entries in self.campaigns.gauge_families().items():
            self.metrics.replace_gauge_family(family, entries)

    def _slo_tick(self) -> None:
        """One tick of the SLI/error-budget plane (fleet/slo.py): fold
        the PR-10 grant-wait counters into the derived ``admission``
        journey, kick a canary probe round when the --canary_ticks
        cadence says so (on the prober's own thread — the poll loop
        never blocks on a probe), close the ledger tick, and republish
        every ``ict_sli_*`` gauge family whole (the capacity/cost
        snapshot-then-replace discipline).  Runs BEFORE the autoscale
        tick so this tick's budget state is the signal the scaler
        reads."""
        # Good events for the admission journey are the admissions the
        # router granted (synthetic probes skip admission entirely, so
        # canary traffic can never move this SLI); bad events are the
        # PR-10 grant-wait burns.
        self.slo.note_admission(
            burned_total=self.metrics.counter_total("fleet_slo_burn_total"),
            placed_total=self.metrics.counter_total(
                "fleet_tenant_admissions_total"))
        if self.cfg.canary_ticks > 0:
            with self._lock:
                self._ticks_to_canary -= 1
                fire = self._ticks_to_canary <= 0
                if fire:
                    self._ticks_to_canary = self.cfg.canary_ticks
            if fire:
                self.canary.maybe_start()
        self.slo.end_tick()
        for family, entries in self.slo.gauge_families().items():
            self.metrics.replace_gauge_family(family, entries)

    def _canary_mismatch(self, verdict: dict) -> None:
        """A canary probe observed a mask that is NOT bit-identical to
        the stored oracle answer — the one correctness signal the fleet
        exists to protect.  Full incident bundle, the audit-divergence
        discipline."""
        self._note_incident("canary_mask_mismatch",
                            job_id=str(verdict.get("job_id", "") or ""),
                            trace_id=str(verdict.get("trace_id", "") or ""))
        if not self.cfg.quiet:
            print(f"ict-fleet: CANARY mask mismatch on journey "
                  f"{verdict.get('journey')!r} "
                  f"(trace {verdict.get('trace_id') or '-'})",
                  file=sys.stderr)

    def _autoscale_tick(self) -> None:
        """The control loop's acting half: reap finished drains, ask the
        Autoscaler for this tick's verdict, and (in act mode) execute it
        — spawn on the supervisor's full-jitter ladder (on its OWN
        thread: a slow or failing spawn must not stall health polling,
        failover sweeps, or grant refresh — the one-wedged-replica
        discipline applies to spawns too), or drain-then-stop the
        least-loaded managed replica.  Every decision, advised, acted,
        or un-executable, is counted
        (fleet_scale_events_total{direction,reason}), event-logged,
        flight-ringed, and written as an incident-style decision
        bundle."""
        if self.autoscaler is None:
            return
        for rec in self.supervisor.reap_drained():
            # Drain-then-stop completed: the replica finished its
            # accepted work and left the fleet — scrub its scrape,
            # straggler, and health-watch state so the gauges don't
            # carry a ghost.  Those caches key on the id the replica
            # ADVERTISED, which need not equal the managed id.
            rid = rec["replica_id"]
            self.scrapes.forget(rid)
            self.straggler.forget(rid)
            self.alerts.forget(rid)
            with self._lock:
                self._health_seen.pop(rid, None)
            if events.active():
                events.emit("fleet_scale_down_complete", replica_id=rid,
                            managed_id=rec["managed_id"])
            flight.note("fleet_scale_down_complete", replica_id=rid,
                        managed_id=rec["managed_id"])
            if not self.cfg.quiet:
                print(f"ict-fleet: managed replica {rid} drained and "
                      "stopped", file=sys.stderr)
        with self._lock:
            if self._scale_in_flight:
                return   # one lifecycle action at a time: no new verdict
                # while a spawn thread runs (its outcome changes `alive`)
        snap = self.registry.snapshot()
        alive = sum(1 for r in snap if r["alive"] and not r["draining"])
        decision = self.autoscaler.tick(
            self.capacity.snapshot(), alive=alive,
            managed_up=len(self.supervisor.up_ids()),
            slo_burn_total=self.metrics.counter_total(
                "fleet_slo_burn_total"),
            stragglers=len(self.straggler.stragglers()),
            slo_budget_remaining=self.slo.min_budget_remaining())
        if decision is None:
            return
        direction, reason = decision["direction"], decision["reason"]
        # The decision exists from this point on, whatever its outcome:
        # counted first, so the counter can never miss one.
        self.metrics.count("fleet_scale_events_total",
                           {"direction": direction, "reason": reason})
        if self.cfg.autoscale != "act":
            self._record_scale_outcome(decision, "fleet_scale_advised",
                                       acted=False)
            return
        if direction == "up":
            with self._lock:
                self._scale_in_flight = True
            # Daemonic and deliberately NOT in self._threads: stop()
            # must not wait out a 60 s spawn; a spawn that completes
            # after stop() is unwound inside _execute_scale_up.
            threading.Thread(
                target=self._execute_scale_up, args=(decision,),
                daemon=True,
                name=f"ict-fleet-scale-{self.router_id}").start()
            return
        # direction == "down": one bounded HTTP call (replica_timeout_s),
        # fine on the poll thread; the drain itself completes over later
        # ticks (reap_drained above).
        victim = self._pick_scale_down_victim()
        veto = self._canary_scale_veto(victim) if victim else ""
        if veto:
            # Budget state as an autoscaler input (ISSUE 18): a failing
            # canary journey means users may already be getting wrong or
            # no answers — shrinking the last replica warm for the
            # canary bucket would destroy the capacity serving the very
            # journey that is failing.  The decision is consumed (the
            # Autoscaler armed its cooldown), so it must stay visible:
            # recorded as vetoed, never silently dropped.
            decision["error"] = veto
            self._record_scale_outcome(decision, "fleet_scale_vetoed",
                                       acted=False)
            return
        if not victim or not self.supervisor.begin_drain(victim):
            # Un-executable down decision (nothing drainable, or the
            # drain call failed).  The Autoscaler already consumed the
            # decision — cooldown armed, streaks reset — so it must NOT
            # vanish from the telemetry: record it as failed.
            decision["error"] = ("no drainable managed replica"
                                 if not victim else
                                 f"drain of {victim} refused/unreachable")
            self._record_scale_outcome(decision, "fleet_scale_failed",
                                       acted=False)
            return
        decision["replica_id"] = victim
        if events.active():
            events.emit("fleet_drain_requested", replica_id=victim,
                        drain=True, initiator="autoscaler")
        flight.note("fleet_drain_requested", replica_id=victim,
                    drain=True, initiator="autoscaler")
        self.registry.poll_once(self.client)
        self._record_scale_outcome(decision, "fleet_scale_down",
                                   acted=True)

    def _execute_scale_up(self, decision: dict) -> None:
        """The spawn half of an acted scale-up, off the poll thread.
        While it runs, `_scale_in_flight` parks further verdicts (the
        fleet's size is in motion); the poll loop itself keeps running —
        health, failover, capacity all stay live behind a slow spawn."""
        try:
            try:
                handle = self.supervisor.spawn_replica()
            except fleet_autoscale.SpawnFailed as exc:
                # Every failed attempt was already counted under
                # reason="spawn_failed"; the giving-up is recorded too.
                decision["error"] = str(exc)
                self._record_scale_outcome(decision, "fleet_scale_failed",
                                           acted=False)
                return
            if self._stop_evt.is_set():
                # The router stopped while the spawn was in flight:
                # unwind rather than leak a replica nobody supervises.
                handle.stop()
                self.registry.remove(handle.base_url)
                return
            decision["replica_id"] = handle.replica_id
            # The new replica joins the registry now; poll it immediately
            # so it is placeable on the next decision, not the one after.
            self.registry.poll_once(self.client)
            self._record_scale_outcome(decision, "fleet_scale_up",
                                       acted=True)
        finally:
            with self._lock:
                self._scale_in_flight = False

    def _record_scale_outcome(self, decision: dict, event: str,
                              acted: bool) -> None:
        """The explainability tail every decision gets: event log +
        flight ring + stderr + the incident-style decision bundle."""
        replica_id = decision.get("replica_id", "")
        if events.active():
            events.emit(event, direction=decision["direction"],
                        reason=decision["reason"], replica_id=replica_id,
                        error=decision.get("error", ""),
                        signals=decision.get("signals", {}))
        flight.note(event, direction=decision["direction"],
                    reason=decision["reason"], replica_id=replica_id)
        if not self.cfg.quiet:
            verb = ("scaling" if acted else
                    "advising scale" if event == "fleet_scale_advised"
                    else "FAILED scaling")
            print(f"ict-fleet: {verb} {decision['direction']} "
                  f"(reason: {decision['reason']}"
                  f"{'; replica ' + replica_id if replica_id else ''}"
                  f"{'; ' + decision['error'] if decision.get('error') else ''})",
                  file=sys.stderr)
        # The decision bundle: the write_incident_bundle discipline
        # applied to scale decisions — the signals that fired it ride in
        # the manifest, the capacity gauges in metrics.prom.  Bundle
        # reason mirrors the event: scale_up / scale_down /
        # scale_advised / scale_failed.
        self._note_scale_bundle(decision, event[len("fleet_"):])

    def _canary_scale_veto(self, victim: str) -> str:
        """The scale-down veto (ISSUE 18): with any canary journey
        failing, refuse to drain the LAST replica serving the canary
        shape bucket — removing it would take down the only capacity the
        failing journey still routes to.  Returns the veto reason, or ""
        to let the drain proceed.  ``victim`` is the supervisor's
        managed id; the registry speaks base URLs, so the check maps
        through ``up_urls``."""
        failing = self.slo.failing_journeys()
        if not failing:
            return ""
        by_managed = {mid: url
                      for url, mid in self.supervisor.up_urls().items()}
        victim_url = by_managed.get(victim, "")
        bucket = bucket_label(fleet_canary.CANARY_SHAPE)
        others_warm = [
            rep for rep in self.registry.candidates()
            if rep.base_url != victim_url
            # A numpy replica has no executables to warm — it serves any
            # bucket at full speed immediately, so it always counts.
            and (rep.health.get("backend") == "numpy"
                 or bucket in rep.warm_buckets()
                 or rep.queued_buckets().get(bucket, 0) > 0)]
        if others_warm:
            return ""
        return (f"scale-down vetoed: canary journey(s) "
                f"{', '.join(sorted(failing))} failing and no other "
                f"replica serves the canary bucket {bucket!r}")

    def _pick_scale_down_victim(self) -> str:
        """The least-loaded managed-up replica — never a statically
        configured one (operators own those), never the last replica.
        Matched by base URL (the supervisor's identity), not by the
        replica's self-reported id, which any --replica_id can set."""
        managed = self.supervisor.up_urls()
        if not managed:
            return ""
        cands = [(rep.load(), managed[rep.base_url])
                 for rep in self.registry.candidates()
                 if rep.base_url in managed]
        return min(cands)[1] if cands else ""

    def _note_scale_bundle(self, decision: dict, reason: str) -> None:
        """One incident-style decision bundle per scale decision: the
        manifest carries the decision + its input signals, metrics.prom
        the router's own exposition (the capacity gauges included), so
        the decision replays from the exported figures alone."""
        with self._lock:
            placements = [{
                "job_id": p.job_id, "tenant": p.tenant,
                "trace_id": p.trace_id, "state": p.state,
                "replica_id": p.replica_id, "attempts": p.attempts,
            } for p in self._placements.values()]
        path = fleet_obs.write_incident_bundle(
            self.incident_dir, reason=reason,
            replica_id=decision.get("replica_id", ""),
            placements=placements, replicas=self.registry.snapshot(),
            metrics_text=self.metrics.render(),
            flight_events=None,
            trace={"decision": decision,
                   "capacity": self.capacity.snapshot(),
                   "autoscale": self.autoscaler.state()})
        self.metrics.count("fleet_incidents_total", {"reason": reason})
        if events.active():
            events.emit("fleet_incident", reason=reason,
                        replica_id=decision.get("replica_id", ""),
                        bundle=path or "")

    def _history_alert_tick(self) -> None:
        """One tick of the alerting plane: append the CURRENT federated
        exposition (router registry + cached per-replica series + merged
        families — the exact ``GET /fleet/metrics`` body, built from the
        snapshots this tick already took, so alert evaluation never adds
        scrape traffic) to the history ring, evaluate every rule over the
        ring, and fan out each transition — counter, gauge, event log,
        flight ring, on-disk bundle (firings), webhook/command sinks.

        The render-then-parse round trip is deliberate, not an
        oversight: it guarantees the history records EXACTLY what a
        scraper of ``GET /fleet/metrics`` would parse (same family
        grouping, same collision semantics, one grammar implementation)
        at the cost of re-tokenizing one exposition per tick — a few ms
        at fleet scale, on the poll thread's 1 s cadence."""
        families = obs_metrics.parse_exposition(self.fleet_metrics())
        rec = self.history.append(families)
        # The trend plane folds the SAME parsed tick in (zero extra
        # parse work) and republishes the regression gauge; like
        # fleet_alerts_firing below, that gauge lands in the NEXT
        # tick's history record, which is exactly when the
        # perf_regression rule evaluates it.
        self._trend_tick(families, rec["ts"])
        verdict = self.alerts.evaluate(self.history)
        for alert in verdict["fired"]:
            self.metrics.count("fleet_alerts_total",
                               {"rule": alert["rule"],
                                "severity": alert["severity"]})
            if events.active():
                events.emit("fleet_alert_firing", rule=alert["rule"],
                            severity=alert["severity"],
                            labels=alert["labels"], value=alert["value"])
            flight.note("fleet_alert_firing", rule=alert["rule"],
                        severity=alert["severity"], labels=alert["labels"])
            # The firing bundle: the rule, the evaluated samples, and the
            # history window that fired it — reconstructible from disk.
            window = int(alert["predicate"].get("window", 1)) + 1
            rule = next((r for r in self.alerts.rules
                         if r.name == alert["rule"]), None)
            bundle = fleet_alerts.write_alert_bundle(
                self.alert_dir, alert=alert,
                rule=rule.to_json() if rule else {},
                window=self.history.to_json(ticks=window)["ticks"])
            if not self.cfg.quiet:
                print(f"ict-fleet: ALERT {alert['severity']} "
                      f"{alert['rule']} firing "
                      f"({alert['labels'] or 'fleet'}; "
                      f"value {alert['value']}"
                      f"{'; bundle ' + bundle if bundle else ''})",
                      file=sys.stderr)
            self.alert_sinks.notify(alert)
        for alert in verdict["resolved"]:
            if events.active():
                events.emit("fleet_alert_resolved", rule=alert["rule"],
                            severity=alert["severity"],
                            labels=alert["labels"], value=alert["value"])
            flight.note("fleet_alert_resolved", rule=alert["rule"],
                        severity=alert["severity"], labels=alert["labels"])
            if not self.cfg.quiet:
                print(f"ict-fleet: alert {alert['rule']} resolved "
                      f"({alert['labels'] or 'fleet'})", file=sys.stderr)
            self.alert_sinks.notify(alert)
        # Firing gauge: rebuilt whole per tick (resolution reads as 0,
        # not absence).  It lands in the NEXT tick's history record —
        # the gauge describes the ring, so it cannot also be inside the
        # tick it describes.
        self.metrics.replace_gauge_family(
            "fleet_alerts_firing",
            {(("rule", name),): float(n)
             for name, n in self.alerts.firing_counts().items()})

    def _trend_tick(self, families: list, ts: float) -> None:
        """One tick of the trend plane (fleet/trends.py): fold the
        already-parsed exposition into the multi-resolution store,
        evaluate due fingerprint windows, republish the
        ``ict_fleet_perf_regression`` gauge (every ever-fired key stays
        present at 0 — the alert engine freezes on missing series), and
        fan each sentinel transition out: counter, event log, flight
        ring, and — for firings — a trend incident bundle carrying the
        offending window, the violated fingerprint, and the bench
        baseline cross-check where the signal is machine-independent."""
        if self.trends is None:
            return
        out = self.trends.tick(families, ts)
        self.metrics.count("fleet_trend_ticks_total")
        for res_label, sealed in out["rollups"].items():
            if sealed:
                self.metrics.count("fleet_trend_rollups_total",
                                   {"resolution": res_label},
                                   inc=float(sealed))
        self.metrics.set_gauge("fleet_trend_series", None,
                               float(self.trends.store.series_count()))
        pstats = self.trends.persist_stats()
        with self._lock:
            prev = self._trend_persist_seen
            self._trend_persist_seen = dict(pstats)
            deltas = {k: pstats[k] - prev.get(k, 0) for k in pstats}
        for fam, key in (("fleet_trend_persist_total", "persist_total"),
                         ("fleet_trend_persist_errors_total",
                          "persist_errors")):
            if deltas.get(key, 0) > 0:
                self.metrics.count(fam, inc=float(deltas[key]))
        self.metrics.replace_gauge_family("fleet_perf_regression",
                                          out["gauge"])
        for firing in out["fired"]:
            self.metrics.count("fleet_perf_regressions_total")
            bundle = fleet_trends.write_trend_bundle(
                self.trends.bundle_dir,
                firing={k: firing[k] for k in ("signal", "labels",
                                               "value", "band", "center",
                                               "streak", "spec")},
                fingerprint=firing["fingerprint"],
                window=firing.get("window") or [],
                baseline_check=firing.get("baseline_check"))
            if events.active():
                events.emit("fleet_perf_regression",
                            signal=firing["signal"],
                            labels=firing["labels"],
                            value=firing["value"], band=firing["band"])
            flight.note("fleet_perf_regression", signal=firing["signal"],
                        labels=firing["labels"], value=firing["value"])
            if not self.cfg.quiet:
                print(f"ict-fleet: PERF REGRESSION {firing['signal']} "
                      f"({firing['labels'] or 'fleet'}; value "
                      f"{firing['value']:.4g} outside {firing['band']}"
                      f"{'; bundle ' + bundle if bundle else ''})",
                      file=sys.stderr)
        for rec2 in out["resolved"]:
            if events.active():
                events.emit("fleet_perf_regression_resolved",
                            signal=rec2["signal"], labels=rec2["labels"],
                            value=rec2["value"])
            flight.note("fleet_perf_regression_resolved",
                        signal=rec2["signal"], labels=rec2["labels"])
            if not self.cfg.quiet:
                print(f"ict-fleet: perf regression {rec2['signal']} "
                      f"recovered ({rec2['labels'] or 'fleet'})",
                      file=sys.stderr)

    def _trim_placements(self) -> None:
        """Bound the placement table by evicting the oldest TERMINAL
        records beyond ``placement_keep`` (job ids are time-sortable, the
        spool-trim rationale) — open placements are never touched."""
        with self._lock:
            terminal = sorted(jid for jid, p in self._placements.items()
                              if p.state != "open")
            for jid in terminal[: max(0, len(terminal)
                                      - self.cfg.placement_keep)]:
                del self._placements[jid]
            # The idempotency index follows the placement table: an entry
            # whose placement was trimmed can no longer dedupe (in-flight
            # "" reservations are owned by their placing thread).
            for key in [k for k, jid in self._idem_index.items()
                        if jid and jid not in self._placements]:
                del self._idem_index[key]

    # --- placement ---

    def place_job(self, payload: dict, tenant: str, trace_id: str) -> dict:
        """Admit + grant + place one submission; returns the 202 body.
        Raises QuotaExceeded (-> 429), FleetBusy (-> 503), ReplicaRefused
        (the replica's own 4xx passes through)."""
        # The tenant is stamped INTO the payload here, authoritatively —
        # not just by the HTTP handler — so every in-process caller (the
        # campaign orchestrator) and every failover re-route of this
        # payload carries the same identity the admission ledger and the
        # cost showback charged; a payload already stamped (a retried
        # submission) keeps its tenant rather than silently rebranding
        # to the default.
        tenant = str(tenant or payload.get("tenant", "") or DEFAULT_TENANT)
        # Synthetic canary traffic (fleet/canary.py) is normalized HERE,
        # authoritatively: the flag and the reserved tenant imply each
        # other, so every downstream exclusion (admission, WFQ grant,
        # capacity demand, cost showback, cache-salt scoping) keys on one
        # consistent identity however the probe entered (direct POST, a
        # synthetic campaign's orchestrator placement, a failover
        # re-route of either).
        if payload.get("synthetic") or tenant == SYNTHETIC_TENANT:
            payload["synthetic"] = True
            tenant = SYNTHETIC_TENANT
        payload["tenant"] = tenant
        key = str(payload.get("idempotency_key", "") or "")
        known = self._resolve_idem(key)
        if known is not None:
            return known
        try:
            cached = self._resolve_cached(payload, tenant, trace_id, key)
            if cached is not None:
                return cached
            return self._place_fresh(payload, tenant, trace_id, key)
        except BaseException:
            self._drop_idem_reservation(key)
            raise

    def _resolve_idem(self, key: str) -> dict | None:
        """Router-side idempotency: a key this router already placed
        resolves to its existing fleet job (whatever replica serves it
        now) instead of running again — the replica-side map only covers
        retries that happen to land on the same replica.  Returns the
        reply to serve, or None after reserving the key for a fresh
        placement (the caller owns the reservation)."""
        if not key:
            return None
        with self._lock:
            known = self._idem_index.get(key)
            if known is None:
                self._idem_index[key] = ""   # reservation: we place it
                return None
        if known == "":
            # Another handler thread is mid-placement on this key; a 503
            # tells the client to retry into the resolved entry.
            raise FleetBusy(f"a submission with idempotency key {key!r} "
                            "is being placed; retry shortly")
        code, manifest = self.job_manifest(known)
        if code == 200:
            self.metrics.count("fleet_deduped_submissions_total")
            return {**manifest, "router_id": self.router_id}
        # The placement was trimmed from the table: place afresh.
        with self._lock:
            self._idem_index[key] = ""
        return None

    def _drop_idem_reservation(self, key: str) -> None:
        with self._lock:
            if key and self._idem_index.get(key) == "":
                del self._idem_index[key]

    def _resolve_cached(self, payload: dict, tenant: str, trace_id: str,
                        key: str) -> dict | None:
        """Fleet-wide content-addressed reuse, checked at placement time
        (fleet/cache.py): hash the submitted file's bytes and, when every
        alive candidate replica advertises the same config/version salt,
        answer a recorded byte-identical submission with its finished
        result — a fleet job born terminal.  No quota, no WFQ grant, no
        placement, and deliberately NO demand counted toward the
        capacity model: a cache hit consumes no fleet capacity.  Returns
        the 202 body to serve, or None to place normally."""
        if payload.get("audit") or payload.get("profile"):
            # An explicit per-job audit (shadow-oracle replay) or
            # profiler capture needs a replica: answering from the cache
            # would silently skip the very check the submitter asked for
            # (the replica-side tier honors audit-on-hit; the router
            # tier cannot).
            self.metrics.count("fleet_cache_skips_total",
                               {"reason": "per_job_flags"})
            return None
        if len(self.result_index) == 0:
            return None       # cold index: don't pay the file hash
        try:
            size = os.path.getsize(str(payload.get("path", "") or ""))
        except OSError:
            return None
        if size > _fleet_cache_max_bytes():
            # Bound the placement-path I/O: hashing runs synchronously in
            # the HTTP handler, and a campaign of huge unique archives
            # would pay a full extra file read per submission for mostly
            # misses.  The reuse tier targets small-cube campaign
            # traffic; big cubes place normally.
            self.metrics.count("fleet_cache_skips_total",
                               {"reason": "file_too_large"})
            return None
        salt = fleet_cache.unanimous_salt(self.registry.snapshot())
        if not salt:
            # Mixed-salt fleet (mid-rollout) or nobody advertises one:
            # never guess which config would have served the job.
            self.metrics.count("fleet_cache_skips_total",
                               {"reason": "no_unanimous_salt"})
            return None
        if payload.get("synthetic"):
            # Canary probes live in their own salt scope (the recording
            # half suffixes identically): a probe can hit entries other
            # probes learned — the cache journey NEEDS that — but can
            # never be served a real tenant's entry nor seed one real
            # traffic would reuse.
            salt = salt + "|synthetic"
        from iterative_cleaner_tpu.ingest import cas

        digest = cas.file_digest(str(payload.get("path", "") or ""))
        if not digest:
            return None
        entry = self.result_index.lookup(digest, salt)
        if entry is None:
            self.metrics.count("fleet_cache_misses_total")
            return None
        if not entry.get("out_path") or not os.path.exists(
                entry["out_path"]):
            # The recorded output no longer exists (operator archived or
            # swept the cleaned files; the index outlives them): place
            # normally so the submission regenerates its output — a
            # born-terminal manifest pointing at a dead path would be a
            # lie.  The replica-side cache tier still spares the device
            # work and writes a fresh output for THIS path.
            self.metrics.count("fleet_cache_skips_total",
                               {"reason": "output_missing"})
            return None
        origin = entry.pop("origin")
        # Time-sortable like replica-minted job ids ('{ms:013d}-{hex}'):
        # _trim_placements evicts the lexically-smallest terminal ids,
        # and an unsortable prefix would let stale cache stubs outlive
        # (and crowd out) recent real placements.
        job_id = f"{int(time.time() * 1000):013d}-fc{uuid.uuid4().hex[:6]}"
        manifest = {**entry, "path": str(payload.get("path", "") or ""),
                    "served_by": "fleet-cache", "origin": origin}
        # The served manifest's cost record is the HIT's (zero device
        # time, the origin's figures as avoided cost) — not the origin's
        # own record, which stays under its own job id.
        origin_cost = entry.get("cost") or {}
        manifest["cost"] = {
            "tenant": tenant, "route": "fleet-cache", "cache_hit": True,
            "device_s": 0.0, "compile_s": 0.0,
            "avoided_device_s": float(origin_cost.get("device_s", 0.0)
                                      or 0.0),
            "avoided_bytes_accessed": float(
                origin_cost.get("bytes_accessed", 0.0) or 0.0),
        }
        placement = Placement(
            job_id=job_id, tenant=tenant, trace_id=trace_id,
            payload=payload, base_url="",
            replica_id=origin.get("replica_id", ""),
            replica_job_id=origin.get("job_id", ""), state="done",
            submitted_s=time.time(), cached=manifest,
            synthetic=bool(payload.get("synthetic")))
        with self._lock:
            self._placements[job_id] = placement
            if key:
                self._idem_index[key] = job_id
        self.metrics.count("fleet_cache_hits_total")
        # Cube bytes that never moved because of this hit (f32 cube of
        # the recorded shape) — the campaign-dedupe savings figure.
        shape = entry.get("shape") or []
        if shape:
            nbytes = 4.0
            for dim in shape:
                nbytes *= float(dim)
            self.metrics.count("fleet_cache_bytes_saved_total",
                               inc=nbytes)
        # Avoided cost, attributed to the SUBMITTING tenant with the
        # origin job's recorded figures (obs/costs.py's cache-hit rule,
        # router tier): the manifest the index learned carries the
        # origin's CostRecord.
        self.metrics.count("fleet_cost_cache_avoided_seconds_total",
                           {"tenant": tenant},
                           inc=float(origin_cost.get("device_s", 0.0)
                                     or 0.0))
        # Born-terminal placements get a COMPLETE trace (submit →
        # fleet_cache_hit → done): there is no replica hop to walk, so
        # the stitcher serves these router spans alone — never an
        # "unavailable" hop probe at the long-gone origin replica.
        self.traces.record(trace_id, "fleet_submit", job_id=job_id,
                           tenant=tenant)
        self.traces.record(trace_id, "fleet_cache_hit", job_id=job_id,
                           origin_job_id=origin.get("job_id", ""),
                           replica_id=origin.get("replica_id", ""),
                           tenant=tenant)
        self.traces.record(trace_id, "fleet_done", job_id=job_id,
                           served_by="fleet-cache")
        if events.active():
            # path/idem_key/shape ride along so a cache-served submission
            # (which never reaches a replica's job_submitted) is still
            # replayable from the event log (proving/traces.py).
            events.emit("fleet_cache_hit", trace_id=trace_id,
                        job_id=job_id,
                        origin_job_id=origin.get("job_id", ""),
                        replica_id=origin.get("replica_id", ""),
                        tenant=tenant,
                        path=str(payload.get("path", "") or ""),
                        idem_key=key,
                        shape=[int(v) for v in shape],
                        cache_salt=salt)
        # Recorder hook, cache half: a born-terminal hit never reaches a
        # replica's job_submitted, so this is its ONLY tape entry
        # (entry="cache", the grammar's cache-served marker).
        self.recorder.record(
            path=str(payload.get("path", "") or ""), tenant=tenant,
            idem_key=key, shape=tuple(shape), bucket=self._bucket_of(payload),
            salt=salt, trace_id=trace_id, entry="cache",
            synthetic=bool(payload.get("synthetic")))
        # Deliberately NOT counted in fleet_jobs_completed_total: that
        # counter is the exactly-once ledger of placements the fleet
        # actually ran, and the smoke/tests pin it against replica-side
        # completions; reuse has its own hit/byte counters.
        return {**manifest, "id": job_id, "state": "done",
                "tenant": tenant, "trace_id": trace_id,
                "replica_id": origin.get("replica_id", ""),
                "router_id": self.router_id}

    def _place_fresh(self, payload: dict, tenant: str, trace_id: str,
                     key: str) -> dict:
        synthetic = bool(payload.get("synthetic"))
        # Synthetic canary probes bypass the ENTIRE admission plane:
        # no quota ledger entry, no admissions count (the admission
        # journey's good-event source), no WFQ grant (they must never
        # displace a real tenant's slot) — the terminal transition
        # releases nothing for them (Placement.synthetic, symmetric).
        if not synthetic:
            try:
                self.admission.admit(tenant)
            except QuotaExceeded:
                self.metrics.count("fleet_tenant_rejections_total",
                                   {"tenant": tenant})
                raise
            self.metrics.count("fleet_tenant_admissions_total",
                               {"tenant": tenant})
            try:
                self._await_grant(tenant)
            except BaseException:
                self.admission.release(tenant)
                raise
        try:
            rep, body = self._submit_with_failover(payload, trace_id)
        except BaseException:
            if not synthetic:
                self._release_slot()
                self.admission.release(tenant)
            raise
        placement = Placement(
            job_id=str(body.get("id", "")),
            tenant=tenant, trace_id=trace_id, payload=payload,
            base_url=rep.base_url, replica_id=rep.replica_id,
            replica_job_id=str(body.get("id", "")),
            submitted_s=time.time(), synthetic=synthetic)
        placement.hops.append({"replica_id": rep.replica_id,
                               "base_url": rep.base_url,
                               "replica_job_id": placement.replica_job_id,
                               "ts": round(time.time(), 6)})
        with self._lock:
            existing = self._placements.get(placement.job_id)
            duplicate = existing is not None and existing.state == "open"
            if not duplicate:
                self._placements[placement.job_id] = placement
            if key:
                self._idem_index[key] = placement.job_id
        if duplicate:
            # The replica deduped a client-pinned idempotency key onto a
            # job this router already tracks as OPEN: the original
            # placement keeps the in-flight slot and the quota count, so
            # the retry's admit/grant must be handed back here — silently
            # replacing the record would leak one of each per retry.
            if not synthetic:
                self._release_slot()
                self.admission.release(tenant)
            return {**body, "tenant": tenant, "router_id": self.router_id}
        self.metrics.count("fleet_placements_total",
                           {"replica": rep.replica_id or rep.base_url})
        # Fresh demand only: failover re-routes and idempotent dedupes
        # never reach here, so the capacity model's demand rate counts
        # each submission exactly once.  Synthetic probes count NOTHING:
        # demand the canary itself injected would feed the very
        # autoscaler signal the canary is supposed to measure.
        if not synthetic:
            self.capacity.note_placement(self._bucket_of(payload))
        self.traces.record(trace_id, "fleet_submit", job_id=placement.job_id,
                           tenant=tenant)
        self.traces.record(trace_id, "fleet_placement",
                           job_id=placement.job_id,
                           replica_id=rep.replica_id, tenant=tenant,
                           bucket=self._bucket_of(payload))
        if events.active():
            events.emit("fleet_placement", trace_id=trace_id,
                        job_id=placement.job_id,
                        replica_id=rep.replica_id, tenant=tenant,
                        bucket=self._bucket_of(payload),
                        idem_key=key)
        # The production flight recorder's fresh-placement hook: one
        # entry per real submission, as it happens (synthetic probes are
        # refused inside record(), by construction; failover re-routes
        # and idempotent dedupes never reach here, so each arrival is
        # recorded exactly once — the record_trace dedupe, live).
        self.recorder.record(
            path=str(payload.get("path", "") or ""), tenant=tenant,
            idem_key=key, shape=tuple(payload.get("shape") or ()),
            bucket=self._bucket_of(payload), trace_id=trace_id,
            entry="service", synthetic=synthetic)
        return {**body, "tenant": tenant, "router_id": self.router_id}

    def _await_grant(self, tenant: str) -> None:
        """Weighted-fair wait for an in-flight slot.  With no budget
        configured the grant is immediate; under contention, grants pop
        in WFQ order as slots free (placements observed terminal).  A
        grant wait beyond the per-tenant SLO target (``slo_grant_s``) —
        or a timeout — burns ``fleet_slo_burn_total{tenant}``, the
        admission-path half of the SLO layer (fleet/obs.py)."""
        ticket = _Ticket()
        t0 = time.monotonic()
        deadline = t0 + self.cfg.queue_timeout_s
        try:
            with self._lock:
                self._wfq.push(tenant, ticket)
                self._grant_free_slots()
                while not ticket.granted:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop_evt.is_set():
                        ticket.abandoned = True
                        raise FleetBusy(
                            f"no placement slot within "
                            f"{self.cfg.queue_timeout_s:g}s "
                            f"({self._inflight} in flight at the "
                            f"--max_inflight budget); retry later")
                    self._cond.wait(remaining)
        except FleetBusy:
            self.metrics.count("fleet_slo_burn_total", {"tenant": tenant})
            raise
        if time.monotonic() - t0 > self.cfg.slo_grant_s:
            self.metrics.count("fleet_slo_burn_total", {"tenant": tenant})

    def _grant_free_slots(self) -> None:
        """Pop WFQ tickets into free in-flight slots and wake their
        waiters.  Takes the (reentrant) placement lock itself, so every
        call site — callers already holding it included — keeps the
        mutation lexically guarded."""
        with self._lock:
            while len(self._wfq) and (
                    not self.cfg.max_inflight
                    or self._inflight < self.cfg.max_inflight):
                popped = self._wfq.pop()
                if popped is None:
                    break
                _tenant, ticket = popped
                if ticket.abandoned:
                    continue
                ticket.granted = True
                self._inflight += 1
            self._cond.notify_all()

    def _release_slot(self) -> None:
        with self._lock:
            self._inflight -= 1
            self._grant_free_slots()

    @staticmethod
    def _bucket_of(payload: dict) -> str:
        shape = payload.get("shape")
        if (isinstance(shape, (list, tuple)) and len(shape) == 3
                and all(isinstance(v, (int, float)) for v in shape)):
            return bucket_label(shape)
        return ""

    def _ranked_candidates(self, bucket: str,
                           exclude: set[str]) -> list[Replica]:
        cands = [r for r in self.registry.candidates()
                 if r.base_url not in exclude]
        flagged = self.straggler.stragglers()

        def score(rep: Replica) -> float:
            s = rep.load()
            if bucket:
                if bucket in rep.warm_buckets():
                    s -= AFFINITY_WARM
                if rep.queued_buckets().get(bucket, 0) > 0:
                    s -= AFFINITY_QUEUED
            # A flagged straggler is de-prioritized, never excluded: a
            # fleet whose every survivor is slow must still place.
            if rep.replica_id in flagged:
                s += STRAGGLER_PENALTY
            return s

        # Deterministic tie-break on replica identity, so tests (and two
        # routers sharing one fleet) rank identically from identical
        # snapshots.
        cands.sort(key=lambda r: (score(r), r.replica_id or r.base_url))
        return cands

    def _submit_with_failover(self, payload: dict, trace_id: str,
                              exclude: set[str] | None = None):
        """Walk the ranked candidates; on transport failure note the
        death countdown and move on; on 503 (busy/draining) move on; on
        any other refusal propagate (the client's problem, not the
        fleet's).  Between sweeps, full-jitter backoff."""
        exclude = set(exclude or ())
        bucket = self._bucket_of(payload)
        last_err: Exception | None = None
        for sweep in range(1 + max(self.cfg.failover_retries, 0)):
            if sweep:
                with self._rng_lock:
                    delay = backoff.full_jitter(
                        self.cfg.retry_backoff_s, sweep - 1,
                        rng=self._backoff_rng)
                time.sleep(delay)
            for rep in self._ranked_candidates(bucket, exclude):
                try:
                    body = self.client.submit(rep.base_url, payload,
                                              trace_id=trace_id)
                except ReplicaUnreachable as exc:
                    last_err = exc
                    dead = self.registry.note_unreachable(rep.base_url)
                    if dead is not None:
                        self._note_incident(
                            "replica_death",
                            replica_id=dead.replica_id or dead.base_url)
                    continue
                except ReplicaRefused as exc:
                    if exc.status == 503:   # at capacity, or draining
                        last_err = exc
                        continue
                    raise
                self.registry.note_placed(rep.base_url)
                return rep, body
        raise FleetBusy(f"no replica accepted the job: "
                        f"{last_err or 'no live replicas'}")

    # --- reads ---

    def placement_snapshot(self, job_id: str) -> dict | None:
        """One placement's routing facts as a plain dict (the explain
        plane's substrate) — copied under the lock, no live references
        escape."""
        with self._lock:
            p = self._placements.get(job_id)
            if p is None:
                return None
            return {
                "job_id": p.job_id, "tenant": p.tenant,
                "trace_id": p.trace_id, "state": p.state,
                "error": p.error, "replica_id": p.replica_id,
                "base_url": p.base_url,
                "replica_job_id": p.replica_job_id,
                "attempts": p.attempts, "submitted_s": p.submitted_s,
                "shape": list(p.payload.get("shape") or []),
                "hops": [dict(h) for h in p.hops],
                "cached": dict(p.cached) if p.cached is not None else None,
                "synthetic": p.synthetic,
            }

    def job_manifest(self, job_id: str) -> tuple[int, dict]:
        with self._lock:
            p = self._placements.get(job_id)
        if p is None:
            return 404, {"error": "no such job"}
        if p.cached is not None:
            # A fleet-cache hit: born terminal, served from the recorded
            # summary — the origin replica may be gone, no proxy call.
            return 200, {**p.cached, "id": p.job_id, "state": p.state,
                         "replica_id": p.replica_id, "tenant": p.tenant,
                         "trace_id": p.trace_id}
        rep = self.registry.get(p.base_url)
        if p.state == "open" and (rep is None or rep.alive):
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
            except ReplicaRefused as exc:
                return exc.status, exc.body
            except ReplicaUnreachable:
                dead = self.registry.note_unreachable(p.base_url)
                if dead is not None:
                    self._note_incident(
                        "replica_death",
                        replica_id=dead.replica_id or dead.base_url)
                manifest = None
            if manifest is not None:
                self._observe_manifest(p, manifest)
                return 200, {**manifest, "id": p.job_id,
                             "replica_id": p.replica_id,
                             "tenant": p.tenant}
        if p.state == "open":
            # The replica is unreachable and the failover sweep has not
            # re-placed the job yet: report it still pending so clients
            # keep polling through the hole.
            return 200, {"id": p.job_id, "state": "pending",
                         "replica_id": p.replica_id, "tenant": p.tenant,
                         "trace_id": p.trace_id, "attempts": p.attempts,
                         "detail": "replica unreachable; failover pending"}
        # Terminal and remembered: serve the replica's full manifest when
        # it is KNOWN reachable, the cached summary otherwise — a dead
        # replica (it may stay dead for days) must not cost every read a
        # connection timeout and a pinned handler thread.
        if rep is not None and rep.alive:
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
                # Re-record the (idempotent, newest-wins) cache entry:
                # the FIRST done observation can precede the replica's
                # CostRecord finalization (it rides the post-dispatch
                # telemetry pass, seconds late on a bucket's first
                # dispatch), so a later read refreshes the learned
                # entry with the finalized avoided-cost figures.
                if manifest.get("state") == "done":
                    self._cache_record(p, manifest)
                return 200, {**manifest, "id": p.job_id,
                             "replica_id": p.replica_id, "tenant": p.tenant}
            except ReplicaRefused:
                pass
            except ReplicaUnreachable:
                dead = self.registry.note_unreachable(p.base_url)
                if dead is not None:
                    self._note_incident(
                        "replica_death",
                        replica_id=dead.replica_id or dead.base_url)
        return 200, {"id": p.job_id, "state": p.state,
                     "error": p.error or None,
                     "replica_id": p.replica_id, "tenant": p.tenant,
                     "trace_id": p.trace_id, "attempts": p.attempts}

    def _observe_manifest(self, p: Placement, manifest: dict) -> None:
        state = str(manifest.get("state", ""))
        if state in ("done", "error"):
            self._mark_terminal(p, state,
                                error=str(manifest.get("error") or ""))
        if state == "done":
            # The fleet cache's learning half: every DONE manifest that
            # carries its content keys (file_digest + cache_salt, stamped
            # at replica ingest) becomes the recorded answer for the next
            # byte-identical submission — observed here because the
            # status polls already fetch these manifests, zero extra
            # traffic.
            if self._cache_record(p, manifest):
                self.metrics.replace_gauge_family(
                    "fleet_cache_entries",
                    {(): float(len(self.result_index))})

    def _cache_record(self, p: Placement, manifest: dict) -> bool:
        """Record one DONE manifest into the fleet result index, with a
        synthetic placement's entry re-salted into the canary scope
        (``<salt>|synthetic``, the `_resolve_cached` lookup's twin) so
        probe results and real tenants' results can never serve each
        other."""
        if p.synthetic and manifest.get("cache_salt"):
            manifest = {**manifest,
                        "cache_salt": str(manifest["cache_salt"])
                        + "|synthetic"}
        return self.result_index.record(manifest,
                                        origin_replica=p.replica_id)

    def _mark_terminal(self, p: Placement, state: str,
                       error: str = "") -> None:
        """Idempotent terminal transition: the quota and in-flight slot
        are released exactly once however many readers observe it."""
        with self._lock:
            if p.state != "open":
                return
            p.state = state
            p.error = error
            if not p.synthetic:
                self._inflight -= 1
                self._grant_free_slots()
        # A synthetic probe never took a quota entry or an in-flight
        # slot (fleet/canary.py), so there is nothing to hand back —
        # releasing would corrupt the real tenants' accounting.
        if not p.synthetic:
            self.admission.release(p.tenant)
        self.metrics.count("fleet_jobs_completed_total", {"state": state})
        self.traces.record(p.trace_id, f"fleet_{state}", job_id=p.job_id,
                           replica_id=p.replica_id,
                           **({"error": error} if error else {}))

    def fleet_metrics(self) -> str:
        """``GET /fleet/metrics``: the router's own exposition, then every
        cached replica scrape re-labeled ``{replica=...}``, then the
        merged fleet families — all three sections from consistent
        snapshots, so the merged totals equal the per-replica sums they
        sit next to (fleet/obs.py)."""
        snap = self.scrapes.snapshot()
        scrapes = {rid: rec["families"] for rid, rec in snap.items()
                   if rec.get("families")}
        return self.metrics.render() + fleet_obs.federated_exposition(scrapes)

    def fleet_capacity(self) -> dict:
        """``GET /fleet/capacity``: the capacity model's last snapshot
        (fleet figures, per-replica utilization/rates, per-bucket
        backlog/demand/cost/ETA) plus the autoscaler's state — the JSON
        twin of the ict_fleet_capacity_* gauge families.  IEEE specials
        are stringified (``"inf"``) so the reply is STRICT JSON — the
        gauge twin keeps the numeric ``+Inf`` under the exposition
        grammar."""
        snap = _json_safe(self.capacity.snapshot())
        snap.setdefault("fleet", {})
        snap.setdefault("replicas", {})
        snap.setdefault("buckets", {})
        snap["stragglers"] = sorted(self.straggler.stragglers())
        snap["autoscale"] = (self.autoscaler.state()
                             if self.autoscaler is not None else None)
        # Keyed by the replica's ADVERTISED id (joinable against the
        # /healthz rows and the capacity per-replica figures), with the
        # supervisor's managed id alongside — the two id domains need
        # not agree (--replica_id is the daemon's own business).
        managed: dict[str, dict] = {}
        if self.supervisor is not None:
            by_url = {r["base_url"]: (r["replica_id"] or r["base_url"])
                      for r in self.registry.snapshot()}
            for mid, rec in self.supervisor.managed_info().items():
                rid = by_url.get(rec["base_url"], rec["base_url"])
                managed[rid] = {"state": rec["state"], "managed_id": mid}
        snap["managed_replicas"] = managed
        return snap

    def fleet_alerts(self) -> dict:
        """``GET /fleet/alerts``: the firing set, the rule table (with
        per-rule firing-series counts), the recent firing/resolved
        transitions, and the on-disk bundle inventory — strict JSON, the
        ``/fleet/capacity`` IEEE-specials discipline."""
        return _json_safe({
            "firing": self.alerts.firing(),
            "rules": self.alerts.rules_table(),
            "recent": self.alerts.recent(),
            "bundles": fleet_alerts.list_alert_bundles(self.alert_dir),
            "history_ticks": self.history.size(),
            "sinks": {"webhook": bool(self.cfg.alert_webhook),
                      "cmd": bool(self.cfg.alert_cmd)},
        })

    def fleet_costs(self) -> dict:
        """``GET /fleet/costs``: the cost-accounting fold — per-tenant
        showback rows (device-seconds, jobs, compile-seconds, cache
        savings, budget usage), per-bucket device time + attainment,
        per-route split, and per-replica conservation ratios — strict
        JSON, the ``/fleet/capacity`` IEEE-specials discipline."""
        with self._lock:
            snap = dict(self._costs_snapshot)
        return _json_safe({**snap, "router_id": self.router_id,
                           "conservation_tolerance":
                               fleet_costs.CONSERVATION_TOLERANCE})

    def fleet_metrics_history(self, ticks: int | None = None,
                              families: tuple = ()) -> dict:
        """``GET /fleet/metrics/history``: the bounded ring of per-tick
        federated expositions, lossless (each tick's families re-render
        byte-exact).  Sample values are the exposition's raw strings —
        ``+Inf``/``NaN`` spellings included — so the reply stays strict
        JSON with no IEEE specials to stringify.  ``families`` (the
        ``?families=`` comma-separated name-prefix filter) narrows each
        tick to the matching families so trend/alert tooling stops
        shipping the full exposition per tick; the filtered ticks stay
        round-trippable through the same strict grammar."""
        return self.history.to_json(ticks=ticks, families=families)

    def fleet_trends(self, family: str = "", resolution: str = "raw",
                     window: int | None = None) -> dict:
        """``GET /fleet/trends``: the trend plane's fingerprint export,
        firing regressions, bundle inventory, and — with ``?family=`` —
        the ring data at one resolution (fleet/trends.py).  Strict JSON,
        the ``/fleet/capacity`` IEEE-specials discipline."""
        if self.trends is None:
            return {"enabled": False}
        return _json_safe(self.trends.trends_json(
            family=family, resolution=resolution, window=window))

    def _recorder_tick(self) -> None:
        """Republish the recorder's gauge families and delta-feed its
        counter mirrors from the recorder's own totals (counters only
        move forward; the recorder's figures are authoritative)."""
        st = self.recorder.stats()
        with self._lock:
            prev = self._recorder_seen
            self._recorder_seen = {
                k: st[k] for k in ("entries_total", "excluded_total",
                                   "dropped_total", "sealed_total")}
            deltas = {k: st[k] - prev.get(k, 0)
                      for k in self._recorder_seen}
        for fam, key in (
                ("recorder_entries_total", "entries_total"),
                ("recorder_excluded_total", "excluded_total"),
                ("recorder_dropped_total", "dropped_total"),
                ("recorder_segments_sealed_total", "sealed_total")):
            if deltas.get(key, 0) > 0:
                self.metrics.count(fam, inc=float(deltas[key]))
        self.metrics.set_gauge("recorder_enabled", None,
                               1.0 if st["enabled"] else 0.0)
        self.metrics.set_gauge("recorder_segments", None,
                               float(st["segments"]))
        self.metrics.set_gauge("recorder_segment_bytes", None,
                               float(st["segment_bytes"]))
        self.metrics.set_gauge("recorder_open_entries", None,
                               float(st["open_entries"]))

    def fleet_traces(self, segment: str = "",
                     t_start: float | None = None,
                     t_end: float | None = None) -> tuple[int, dict]:
        """``GET /fleet/traces``: the recorder's sealed-segment inventory
        (+ live stats), or — with ``?segment=`` / ``?t0=&t1=`` — one
        windowed export as a replayable trace document (``trace`` is the
        JSON-line list: write each element as one line and the file
        loads through ``proving.traces.load_trace`` unchanged)."""
        if segment or t_start is not None or t_end is not None:
            try:
                doc = self.recorder.export(segment=segment,
                                           t_start=t_start, t_end=t_end)
            except KeyError:
                return 404, {"error": f"no sealed segment {segment!r}"}
            return 200, {"router_id": self.router_id, "trace": doc}
        return 200, {"router_id": self.router_id,
                     "directory": self.recorder.out_dir,
                     "recorder": self.recorder.stats(),
                     "segments": self.recorder.segments()}

    def fleet_explain_job(self, job_id: str) -> tuple[int, dict]:
        """``GET /fleet/explain/<job_id>``: the seven-plane causal
        report for one job (fleet/explain.py) — trace, cost/roofline,
        zap attribution, audit verdict, quality, cache/coalesce
        disposition, SLO journeys — each stamped with live/spool/
        unavailable provenance.  Strict JSON (the /fleet/capacity
        IEEE-specials discipline: SLO quantiles can be infinite)."""
        code, report = fleet_explain.explain_job(self, job_id)
        return code, _json_safe(report)

    def fleet_trace(self, trace_id: str) -> tuple[int, dict]:
        """``GET /fleet/trace/<id>``: one stitched cross-hop timeline.

        Router spans (submit/placement/failover/terminal) interleave with
        each hop's replica-side spans: a live hop's come from its
        persisted ``GET /jobs/<id>/trace``; a dead hop's from the
        pre-death flight-ring cache (filtered to this trace id)."""
        router_spans = self.traces.spans(trace_id)
        job_id = self.traces.job_for(trace_id)
        with self._lock:
            p = self._placements.get(job_id) if job_id else None
            if p is None:
                # The span store may have evicted an old trace the
                # placement table still remembers (or vice versa).
                p = next((q for q in self._placements.values()
                          if q.trace_id == trace_id), None)
            if p is not None:
                job_id = p.job_id
                state = p.state
                hops = [dict(h) for h in p.hops]
            else:
                state, hops = "", []
        if not router_spans and p is None:
            return 404, {"error": f"no trace {trace_id!r} in the span "
                                  "store or the placement table"}
        sources: dict[str, str] = {}
        hop_spans: dict[str, list[dict]] = {}
        for hop in hops:
            rid = hop["replica_id"] or hop["base_url"]
            rep = self.registry.get(hop["base_url"])
            if rep is not None and rep.alive:
                try:
                    tr = self.client.job_trace(hop["base_url"],
                                               hop["replica_job_id"])
                except (ReplicaUnreachable, ReplicaRefused):
                    pass
                else:
                    spans = [{"source": rid, "event": "replica_job",
                              "state": tr.get("state"),
                              "served_by": tr.get("served_by"),
                              "loops": tr.get("loops"),
                              "termination": tr.get("termination")}]
                    spans += [{"source": rid, "event": "iteration", **rec}
                              for rec in tr.get("timeline", [])]
                    hop_spans[rid] = spans
                    sources[rid] = "live"
            if rid not in hop_spans:
                # The dead-hop path: whatever of this trace the poll
                # loop's flight-ring cache caught before the replica died.
                cached = [{"source": rid, **rec}
                          for rec in self.scrapes.flight_events(rid)
                          if rec.get("trace_id") == trace_id]
                if cached:
                    hop_spans[rid] = cached
                    sources[rid] = "flight-cache"
                else:
                    hop_spans[rid] = [{"source": rid,
                                       "event": "replica_trace_unavailable"}]
                    sources[rid] = "unavailable"
        stitched: list[dict] = []
        for span in sorted(router_spans, key=lambda s: s.get("ts", 0.0)):
            stitched.append(span)
            rid = span.get("to_replica") or span.get("replica_id") or ""
            if (span.get("event") in ("fleet_placement", "fleet_failover")
                    and rid in hop_spans):
                stitched.extend(hop_spans.pop(rid))
        for leftovers in hop_spans.values():
            stitched.extend(leftovers)
        return 200, {"trace_id": trace_id, "job_id": job_id,
                     "state": state, "hops": hops, "sources": sources,
                     "spans": stitched}

    # --- the streaming-session proxy (the canary session journey's
    # substrate, and a real user path: one front door for streams too) ---

    #: Bound on remembered session routes (FIFO eviction) — an abandoned
    #: session must not grow the map forever.
    SESSION_ROUTES_KEEP = 1024

    def session_open(self, body: dict) -> tuple[int, dict]:
        """``POST /sessions``: place a streaming session on the
        least-loaded candidate and remember the route (session id ->
        replica) for its blocks/finish/status calls.  Sessions pin to
        ONE replica for their whole life — a stream's state lives in
        that replica's OnlineSession; there is no failover re-route."""
        cands = self._ranked_candidates("", set())
        if not cands:
            return 503, {"error": "no live replica to host the session"}
        rep = cands[0]
        try:
            reply = self.client.session_open(rep.base_url, body)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 502, {"error": f"replica unreachable on session "
                                  f"open: {exc}"}
        sid = str(reply.get("id", ""))
        trace_id = str(reply.get("trace_id", "") or "")
        if sid:
            with self._lock:
                self._session_routes[sid] = (rep.base_url, trace_id)
                while (len(self._session_routes)
                       > self.SESSION_ROUTES_KEEP):
                    self._session_routes.pop(
                        next(iter(self._session_routes)))
        if trace_id:
            # The router adopts the REPLICA-minted trace id (the create
            # reply carries it), so the fleet-side spans interleave with
            # the replica's own session telemetry under one id.
            self.traces.record(trace_id, "fleet_session_open",
                               session_id=sid,
                               replica_id=rep.replica_id)
        return 201, {**reply, "replica_id": rep.replica_id,
                     "router_id": self.router_id}

    def _session_route(self, sid: str) -> tuple | None:
        with self._lock:
            return self._session_routes.get(sid)

    def session_block(self, sid: str,
                      payload: bytes) -> tuple[int, dict]:
        route = self._session_route(sid)
        if route is None:
            return 404, {"error": f"no session {sid!r} routed through "
                                  "this router"}
        try:
            reply = self.client.session_block(route[0], sid, payload)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 502, {"error": f"replica unreachable mid-stream: {exc}"}
        return 200, {**reply, "router_id": self.router_id}

    def session_finish(self, sid: str) -> tuple[int, dict]:
        route = self._session_route(sid)
        if route is None:
            return 404, {"error": f"no session {sid!r} routed through "
                                  "this router"}
        try:
            reply = self.client.session_finish(route[0], sid)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 502, {"error": f"replica unreachable on finish: {exc}"}
        if route[1]:
            self.traces.record(route[1], "fleet_session_finish",
                               session_id=sid,
                               state=str(reply.get("state", "")))
        return 200, {**reply, "router_id": self.router_id}

    def session_get(self, sid: str) -> tuple[int, dict]:
        route = self._session_route(sid)
        if route is None:
            return 404, {"error": f"no session {sid!r} routed through "
                                  "this router"}
        try:
            reply = self.client.session_get(route[0], sid)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 502, {"error": f"replica unreachable: {exc}"}
        return 200, {**reply, "router_id": self.router_id}

    def fleet_slo(self) -> dict:
        """``GET /fleet/slo``: the SLI/error-budget report (per-journey
        availability/correctness/latency quantiles, burn rates, budget
        remaining, last verdicts) plus the prober's own state — strict
        JSON, the /fleet/capacity IEEE-specials discipline."""
        return _json_safe({
            **self.slo.report(),
            "canary": {
                "enabled": self.cfg.canary_ticks > 0,
                "cadence_ticks": self.cfg.canary_ticks,
                "rounds": self.canary.rounds(),
                "busy": self.canary.busy(),
            },
            "router_id": self.router_id,
        })

    def health(self) -> dict:
        from iterative_cleaner_tpu import __version__

        snap = self.registry.snapshot()
        ages = self.scrapes.ages()
        for row in snap:
            # Per-replica scrape staleness on the router's own health
            # contract (the satellite parity with replica /healthz).
            row["scrape_age_s"] = ages.get(
                row["replica_id"] or row["base_url"])
        with self._lock:
            open_n = sum(1 for p in self._placements.values()
                         if p.state == "open")
            queued = len(self._wfq)
            inflight = self._inflight
            last_poll = self._last_poll_mono
        return {
            "status": "ok",
            "router_id": self.router_id,
            "version": __version__,
            "uptime_s": round(time.time() - self.started_s, 3),
            "last_poll_age_s": (round(time.monotonic() - last_poll, 3)
                                if last_poll else None),
            "replicas": snap,
            "replicas_alive": sum(1 for r in snap
                                  if r["alive"] and not r["draining"]),
            "stragglers": sorted(self.straggler.stragglers()),
            "open_placements": open_n,
            "queued_submissions": queued,
            "inflight": inflight,
            "max_inflight": self.cfg.max_inflight,
            # The capacity/autoscale state (ISSUE 11): the same figures
            # the gauges export, summarized for load balancers and
            # fleet_top.
            "capacity": _json_safe(
                self.capacity.snapshot().get("fleet", {})),
            "autoscale": (self.autoscaler.state()
                          if self.autoscaler is not None else None),
            # The alerting plane's firing summary (ISSUE 12): enough for
            # a load balancer or fleet_top to see "something is firing"
            # without a second request; GET /fleet/alerts has the rest.
            "alerts": self._alerts_summary(),
            # The campaign plane (campaign/): open-campaign count,
            # aggregate archive states, and recent per-campaign rows —
            # the fleet_top CAMPAIGNS section's feed.
            "campaigns": _json_safe(self.campaigns.summary()),
            # The fleet result cache (fleet/cache.py): index size and
            # cumulative hit/miss counters, summarized for fleet_top.
            "result_cache": {
                "entries": len(self.result_index),
                "hits": int(self.metrics.counter_value(
                    "fleet_cache_hits_total")),
                "misses": int(self.metrics.counter_value(
                    "fleet_cache_misses_total")),
            },
            # The SLI/error-budget plane (fleet/slo.py): enough for a
            # load balancer or fleet_top to see "a journey is failing"
            # without a second request; GET /fleet/slo has the rest.
            "slo": {
                "objectives": len(self._slo_objectives),
                "failing_journeys": self.slo.failing_journeys(),
                "min_budget_remaining_pct": _json_safe(
                    self.slo.min_budget_remaining()),
                "canary_enabled": self.cfg.canary_ticks > 0,
                "canary_rounds": self.canary.rounds(),
            },
        }

    def _alerts_summary(self) -> dict:
        firing = self.alerts.firing()
        return {
            "firing": len(firing),
            "critical": sum(1 for a in firing
                            if a["severity"] == "critical"),
            "rules": sorted({a["rule"] for a in firing}),
        }

    def drain_replica(self, replica_id: str, flag: bool) -> tuple[int, dict]:
        rep = self.registry.by_id(replica_id)
        if rep is None:
            return 404, {"error": f"no replica {replica_id!r} in the fleet"}
        try:
            body = self.client.drain(rep.base_url, flag)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 503, {"error": f"replica unreachable: {exc}"}
        # Operator-initiated drains leave a trace-level record (event log
        # + flight ring) — a replica that stopped taking placements must
        # be explainable from the telemetry, not just observable in the
        # registry.
        if events.active():
            events.emit("fleet_drain_requested", replica_id=replica_id,
                        drain=bool(flag), initiator="operator")
        flight.note("fleet_drain_requested", replica_id=replica_id,
                    drain=bool(flag), initiator="operator")
        # Reflect the drain in the registry immediately — waiting for the
        # next poll would leave a placement window on a draining replica.
        self.registry.poll_once(self.client)
        return 200, body


class _RouterHandler(BaseHTTPRequestHandler):
    # Bound every socket read (the replica-API rule): a client that
    # under-sends its declared body must time out, not pin this handler
    # thread and its FD forever.
    timeout = 30.0

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        if not self.server.router.cfg.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: dict | None = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if isinstance(payload, dict) and payload.get("trace_id"):
            self.send_header("X-ICT-Trace", str(payload["trace_id"]))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self, limit: int = 1 << 20) -> bytes:
        # POST /campaigns raises the cap to 8 MB: a survey manifest
        # listing tens of thousands of absolute paths is legitimate
        # input, while every other route keeps the tight default.
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            n = 0
        return self.rfile.read(max(0, min(n, limit)))

    def do_GET(self) -> None:  # noqa: N802 — stdlib signature
        router = self.server.router
        if self.path == "/healthz":
            self._reply(200, router.health())
        elif self.path == "/metrics":
            body = router.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/fleet/metrics":
            body = router.fleet_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.split("?", 1)[0] == "/fleet/metrics/history":
            query = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            try:
                ticks = int(query["ticks"][0]) if "ticks" in query else None
            except ValueError:
                ticks = -1
            if ticks is not None and ticks < 0:
                self._reply(400, {"error": "bad ?ticks= value; want an "
                                           "int >= 0"})
                return
            families = tuple(
                p for p in str(query.get("families", [""])[0]).split(",")
                if p)
            self._reply(200, router.fleet_metrics_history(
                ticks=ticks, families=families))
        elif self.path.split("?", 1)[0] == "/fleet/trends":
            query = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            resolution = str(query.get("resolution", ["raw"])[0])
            try:
                window = (int(query["window"][0])
                          if "window" in query else None)
                if window is not None and window < 1:
                    raise ValueError
            except ValueError:
                self._reply(400, {"error": "bad ?window= value; want an "
                                           "int >= 1"})
                return
            try:
                self._reply(200, router.fleet_trends(
                    family=str(query.get("family", [""])[0]),
                    resolution=resolution, window=window))
            except ValueError as exc:
                self._reply(400, {"error": str(exc)})
        elif self.path == "/fleet/alerts":
            self._reply(200, router.fleet_alerts())
        elif self.path == "/fleet/capacity":
            self._reply(200, router.fleet_capacity())
        elif self.path == "/fleet/costs":
            self._reply(200, router.fleet_costs())
        elif self.path == "/fleet/slo":
            self._reply(200, router.fleet_slo())
        elif self.path.split("?", 1)[0] == "/fleet/traces":
            query = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)

            def _f(name):
                if name not in query:
                    return None
                return float(query[name][0])

            try:
                segment = str(query.get("segment", [""])[0])
                t_start, t_end = _f("t0"), _f("t1")
            except ValueError:
                self._reply(400, {"error": "bad ?t0=/?t1= value; want "
                                           "absolute unix seconds"})
                return
            code, payload = router.fleet_traces(
                segment=segment, t_start=t_start, t_end=t_end)
            self._reply(code, payload)
        elif self.path.startswith("/fleet/explain/"):
            jid = self.path[len("/fleet/explain/"):]
            code, payload = router.fleet_explain_job(jid)
            self._reply(code, payload)
        elif self.path.startswith("/fleet/trace/"):
            tid = self.path[len("/fleet/trace/"):]
            code, payload = router.fleet_trace(tid)
            self._reply(code, payload)
        elif self.path == "/fleet/incidents":
            self._reply(200, {
                "directory": router.incident_dir,
                "incidents": fleet_obs.list_incidents(router.incident_dir)})
        elif self.path == "/replicas":
            self._reply(200, {"replicas": router.registry.snapshot()})
        elif self.path == "/campaigns":
            self._reply(200, {"campaigns": _json_safe(
                router.campaigns.list())})
        elif self.path.startswith("/campaigns/"):
            cid = self.path[len("/campaigns/"):]
            view = router.campaigns.get(cid)
            if view is None:
                self._reply(404, {"error": f"no campaign {cid!r}"})
            else:
                self._reply(200, _json_safe(view))
        elif self.path.startswith("/jobs/"):
            jid = self.path[len("/jobs/"):]
            code, payload = router.job_manifest(jid)
            self._reply(code, payload)
        elif self.path.startswith("/sessions/"):
            sid = self.path[len("/sessions/"):]
            code, payload = router.session_get(sid)
            self._reply(code, payload)
        else:
            self._reply(404, {"error": f"no such route {self.path!r}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib signature
        router = self.server.router
        if self.path == "/jobs":
            self._post_job()
            return
        if self.path == "/campaigns":
            try:
                manifest = json.loads(
                    self._read_body(limit=8 << 20) or b"{}")
            except ValueError as exc:
                self._reply(400, {"error": f"bad manifest JSON: {exc}"})
                return
            try:
                row = router.campaigns.create(manifest)
            except ValueError as exc:
                self._reply(400, {"error": str(exc)})
                return
            self._reply(200, _json_safe(row))
            return
        if (self.path.startswith("/campaigns/")
                and self.path.endswith("/cancel")):
            cid = self.path[len("/campaigns/"): -len("/cancel")]
            row = router.campaigns.cancel(cid)
            if row is None:
                self._reply(404, {"error": f"no campaign {cid!r}"})
            else:
                self._reply(200, _json_safe(row))
            return
        if self.path == "/sessions":
            try:
                body = json.loads(self._read_body() or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as exc:
                self._reply(400, {"error": f"bad session body: {exc}"})
                return
            code, payload = router.session_open(body)
            self._reply(code, payload)
            return
        if (self.path.startswith("/sessions/")
                and self.path.endswith("/blocks")):
            sid = self.path[len("/sessions/"): -len("/blocks")]
            # Raw block bytes, same cap the single-replica daemon
            # enforces (online/blocks.py) so the proxy never truncates
            # a body the replica would have accepted.
            from iterative_cleaner_tpu.online.blocks import MAX_BLOCK_BYTES
            code, payload = router.session_block(
                sid, self._read_body(limit=MAX_BLOCK_BYTES))
            self._reply(code, payload)
            return
        if (self.path.startswith("/sessions/")
                and self.path.endswith("/finish")):
            sid = self.path[len("/sessions/"): -len("/finish")]
            code, payload = router.session_finish(sid)
            self._reply(code, payload)
            return
        if (self.path.startswith("/replicas/")
                and self.path.endswith("/drain")):
            rid = self.path[len("/replicas/"): -len("/drain")]
            try:
                body = json.loads(self._read_body() or b"{}")
                flag = bool(body.get("drain", True)) \
                    if isinstance(body, dict) else True
            except ValueError:
                flag = True
            code, payload = router.drain_replica(rid, flag)
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"no such route {self.path!r}"})

    def _post_job(self) -> None:
        router = self.server.router
        try:
            body = json.loads(self._read_body() or b"{}")
            path = body["path"]
            payload = {
                "path": str(path),
                "profile": bool(body.get("profile", False)),
                "audit": bool(body.get("audit", False)),
                # The client may pin its own idempotency key (its retry
                # across routers then dedupes too); otherwise the router
                # mints one — it is what makes failover re-routes safe.
                "idempotency_key": str(body.get("idempotency_key", "")
                                       or f"fleet-{uuid.uuid4().hex[:16]}"),
                # Canary probes self-identify; place_job rebrands them
                # onto the reserved synthetic tenant so every exclusion
                # plane (admission, capacity, costs, cache salt) keys
                # off one identity (fleet/slo.py "synthetic traffic").
                "synthetic": bool(body.get("synthetic", False)),
            }
            shape = body.get("shape")
            if shape is not None:
                payload["shape"] = [int(v) for v in shape]
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"error": f"bad request body: {exc!r}; expected "
                                       '{"path": "/abs/archive"}'})
            return
        tenant = str(self.headers.get("X-ICT-Tenant", "")
                     or DEFAULT_TENANT)
        # The tenant crosses the hop inside the payload (and therefore
        # rides failover re-routes verbatim): the replica stamps it on
        # the job so the cost ledger's showback attribution and the
        # router's admission accounting can never disagree about who a
        # job belongs to (obs/costs.py).
        payload["tenant"] = tenant
        trace_id = str(self.headers.get("X-ICT-Trace", "")
                       or events.new_trace_id())
        try:
            reply = router.place_job(payload, tenant, trace_id)
        except QuotaExceeded as exc:
            self._reply(429, {"error": str(exc)},
                        headers={"Retry-After": "5"})
            return
        except FleetBusy as exc:
            self._reply(503, {"error": str(exc)},
                        headers={"Retry-After": "5"})
            return
        except ReplicaRefused as exc:
            self._reply(exc.status, exc.body)
            return
        except Exception as exc:  # noqa: BLE001 — the client deserves a 500
            self._reply(500, {"error": f"placement failed: {exc}"})
            return
        self._reply(202, reply)


# --- CLI ---

def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ict-serve-fleet",
        description="Fleet router: spreads jobs across N ict-serve "
                    "replicas with shape-bucket affinity, drain/death "
                    "failover, and multi-tenant admission "
                    '(docs/SERVING.md "Fleet")')
    p.add_argument("--replica", action="append", default=[], metavar="URL",
                   help="replica base URL, e.g. http://host:8750 "
                        "(repeatable; at least one unless --smoke)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8790,
                   help="router HTTP port (0 = ephemeral; default 8790)")
    p.add_argument("--router_id", default="", metavar="ID",
                   help="stable router identity on /healthz and event-log "
                        "lines (default: mint one per process life)")
    p.add_argument("--poll_interval_s", type=float, default=1.0, metavar="S",
                   help="health-poll / failover-sweep cadence (default 1.0)")
    p.add_argument("--dead_after", type=int, default=3, metavar="N",
                   help="consecutive unreachable health checks before a "
                        "replica is dead and its open placements re-route "
                        "(default 3)")
    p.add_argument("--max_inflight", type=int, default=0, metavar="N",
                   help="fleet-wide open-placement budget; submissions "
                        "beyond it wait in weighted-fair order "
                        "(0 = unbounded; default 0)")
    p.add_argument("--queue_timeout_s", type=float, default=30.0, metavar="S",
                   help="max wait for a placement slot before 503 "
                        "(default 30)")
    p.add_argument("--failover_retries", type=int, default=2, metavar="N",
                   help="extra full-jitter candidate sweeps per submission "
                        "(default 2)")
    p.add_argument("--retry_backoff_s", type=float, default=0.25, metavar="S",
                   help="full-jitter backoff base between sweeps "
                        "(default 0.25)")
    p.add_argument("--tenant", action="append", default=[],
                   metavar="NAME:QUOTA:WEIGHT[:BUDGET]",
                   help="per-tenant admission spec (repeatable): QUOTA open "
                        "placements (0 = unbounded), WFQ WEIGHT, and an "
                        "optional ADVISORY device-seconds BUDGET feeding "
                        "the tenant_budget_burn alert rules (warning at "
                        "80%%, critical at 100%% — rules, never admission "
                        "changes), e.g. --tenant survey:64:3:3600 "
                        "--tenant adhoc:8:1")
    p.add_argument("--default_quota", type=int, default=0, metavar="N",
                   help="open-placement quota for undeclared tenants "
                        "(0 = unbounded; default 0)")
    p.add_argument("--default_weight", type=float, default=1.0, metavar="W",
                   help="WFQ weight for undeclared tenants (default 1.0)")
    p.add_argument("--telemetry", default="", metavar="PATH",
                   help="append fleet_placement/fleet_failover events to "
                        "PATH as JSON lines (ICT_TELEMETRY equivalent)")
    p.add_argument("--spool", default="./ict_fleet_spool", metavar="DIR",
                   help="router-side durable directory: flight-ring dumps "
                        "on SIGTERM/SIGINT (DIR/flight) and incident "
                        "bundles on death eviction / failover / "
                        "audit-divergence demotion (DIR/fleet-incidents; "
                        "default ./ict_fleet_spool)")
    p.add_argument("--straggler_factor", type=float, default=3.0,
                   metavar="F",
                   help="flag a replica whose latency p50 exceeds F times "
                        "the fleet median (default 3.0; must be > 1)")
    p.add_argument("--straggler_polls", type=int, default=3, metavar="K",
                   help="consecutive slow polls before the straggler flag "
                        "fires (default 3)")
    p.add_argument("--straggler_window", type=int, default=8, metavar="N",
                   help="polls of latency-histogram deltas in each p50 "
                        "window (default 8)")
    p.add_argument("--straggler_phase", default="service_dispatch",
                   metavar="PHASE",
                   help="the scraped latency-histogram phase the straggler "
                        "p50s watch (default service_dispatch)")
    p.add_argument("--slo_grant_s", type=float, default=1.0, metavar="S",
                   help="per-tenant SLO on the placement-grant wait; a "
                        "longer wait (or a grant timeout) burns "
                        "fleet_slo_burn_total{tenant} (default 1.0)")
    p.add_argument("--capacity_window", type=int, default=8, metavar="N",
                   help="poll ticks per capacity-model rate window "
                        "(utilization / service / demand rates; default 8)")
    p.add_argument("--autoscale", choices=("off", "advise", "act"),
                   default="off",
                   help="elastic scaling loop driven by the capacity "
                        "model + SLO/straggler signals: 'advise' only "
                        "emits recommendations (events, counters, "
                        "decision bundles), 'act' spawns/drains replicas "
                        "(default off; docs/OBSERVABILITY.md)")
    p.add_argument("--min_replicas", type=int, default=1, metavar="N",
                   help="alive-replica floor the scaler respects "
                        "(default 1)")
    p.add_argument("--max_replicas", type=int, default=4, metavar="N",
                   help="alive-replica ceiling for scale-ups (default 4)")
    p.add_argument("--scale_up_eta_s", type=float, default=10.0,
                   metavar="S",
                   help="backlog-drain ETA that counts one poll as "
                        "'behind'; --scale_up_polls consecutive behind "
                        "polls fire a scale-up (default 10)")
    p.add_argument("--scale_up_polls", type=int, default=3, metavar="K",
                   help="hysteresis: consecutive behind polls before a "
                        "scale-up decision (default 3)")
    p.add_argument("--scale_down_polls", type=int, default=6, metavar="K",
                   help="hysteresis: consecutive idle polls (zero "
                        "backlog + demand, utilization under "
                        "--scale_idle_util) before a drain-then-stop "
                        "scale-down (default 6)")
    p.add_argument("--scale_idle_util", type=float, default=0.05,
                   metavar="F",
                   help="fleet utilization below which an idle poll "
                        "counts toward scale-down (default 0.05)")
    p.add_argument("--scale_cooldown_s", type=float, default=30.0,
                   metavar="S",
                   help="quiet period after any scale decision — the "
                        "anti-flapping guard (default 30)")
    p.add_argument("--spawn_retries", type=int, default=3, metavar="N",
                   help="full-jitter retries when a replica spawn fails "
                        "(default 3; each failure counts "
                        "fleet_scale_events_total{reason=spawn_failed})")
    p.add_argument("--spawn_arg", action="append", default=[],
                   metavar="ARG",
                   help="extra ict-serve argument for autoscaler-spawned "
                        "subprocess replicas (repeatable), e.g. "
                        "--spawn_arg=--backend=numpy")
    p.add_argument("--history_ticks", type=int, default=128, metavar="N",
                   help="poll ticks of federated-metrics history retained "
                        "and served at GET /fleet/metrics/history; the "
                        "alert predicates evaluate over this ring "
                        "(default 128)")
    p.add_argument("--alert_rule", action="append", default=[],
                   metavar="JSON",
                   help="one declarative alert rule as a JSON object "
                        '(repeatable), e.g. \'{"name": "hot", "severity": '
                        '"warning", "family": '
                        '"ict_fleet_backlog_eta_seconds", "predicate": '
                        '{"op": "gt", "value": 30}, "for_ticks": 3}\'; a '
                        "rule re-using a default-pack name replaces that "
                        'default (docs/OBSERVABILITY.md "Alerting & '
                        'history")')
    p.add_argument("--alert_rules", default="", metavar="PATH",
                   help="JSON file holding a list of alert-rule objects "
                        "(same shape as --alert_rule), applied after the "
                        "default pack")
    p.add_argument("--no_default_alerts", action="store_true",
                   help="do not install the default SLO rule pack (audit "
                        "divergence, scrape staleness, unscaled backlog, "
                        "backend demotion, spool disk, compile-cache "
                        "thrash)")
    p.add_argument("--alert_webhook", default="", metavar="URL",
                   help="POST each alert firing/resolved transition to "
                        "URL as JSON (full-jitter retries; delivery "
                        "outcomes on "
                        "ict_fleet_alert_notifications_total)")
    p.add_argument("--alert_cmd", default="", metavar="CMD",
                   help="run CMD (a shell command) per alert transition "
                        "with the JSON on stdin — the pager/hook shape "
                        "(full-jitter retries, 10 s timeout)")
    p.add_argument("--alert_retries", type=int, default=3, metavar="N",
                   help="full-jitter delivery retries per alert sink "
                        "(default 3)")
    p.add_argument("--canary_ticks", type=int, default=0, metavar="N",
                   help="poll ticks between black-box canary probe rounds "
                        "through the router's own HTTP surface (fresh job, "
                        "cache resubmit, streaming session, micro-campaign; "
                        "each verdict bit-checks the mask against a stored "
                        "oracle; 0 = off, the default)")
    p.add_argument("--slo", action="append", default=[],
                   metavar="JOURNEY:TARGET:WINDOW_TICKS",
                   help="declarative SLO objective, repeatable — e.g. "
                        "fresh:0.99:512; registers two multiwindow "
                        "burn-rate alert rules per objective and a "
                        "spool-persisted error-budget ledger "
                        "(journeys: " + ", ".join(fleet_slo.JOURNEYS) + ")")
    p.add_argument("--no_recorder", action="store_true",
                   help="disable the production flight recorder (on by "
                        "default: every real submission is appended to a "
                        "bounded, rotated trace-segment set under "
                        "<spool>/fleet-traces, replayable via 'ict-clean "
                        "prove --replay'; ICT_RECORDER=0 equivalent)")
    p.add_argument("--recorder_segment_kb", type=int, default=256,
                   metavar="KB",
                   help="open-segment size cap before the recorder seals "
                        "and rotates it (default 256)")
    p.add_argument("--recorder_keep", type=int, default=16, metavar="N",
                   help="sealed trace segments retained; the oldest are "
                        "swept beyond it (default 16)")
    p.add_argument("--no_trends", action="store_true",
                   help="disable the durable performance-trend plane (on "
                        "by default: multi-resolution rollup rings over "
                        "the federated exposition persisted under "
                        "<spool>/trends, per-bucket performance "
                        "fingerprints, and the regression sentinel "
                        "firing ict_fleet_perf_regression through the "
                        "alert engine; ICT_TRENDS=0 equivalent)")
    p.add_argument("--trend_signal", action="append", default=[],
                   metavar="JSON",
                   help="one fingerprint signal spec as a JSON object "
                        "(repeatable), e.g. '{\"name\": \"warm_jobs\", "
                        "\"mode\": \"gauge\", \"direction\": \"low\", "
                        "\"family\": "
                        "\"ict_fleet_capacity_replica_service_rate\", "
                        "\"group_by\": [\"replica\"]}'; a spec re-using "
                        "a default-set name replaces that default "
                        '(docs/OBSERVABILITY.md "Performance trends")')
    p.add_argument("--trend_keep_raw", type=int, default=128, metavar="N",
                   help="raw per-tick trend points retained per series "
                        "before the 1-minute/1-hour rollup rings take "
                        "over (default 128)")
    p.add_argument("--trend_sentinel_k", type=int, default=3, metavar="K",
                   help="consecutive out-of-band windows before the "
                        "regression sentinel fires (default 3)")
    p.add_argument("--trend_min_samples", type=int, default=8, metavar="N",
                   help="accepted in-band windows before a fingerprint "
                        "arms its sentinel (default 8)")
    p.add_argument("--trend_band_mad", type=float, default=4.0,
                   metavar="X",
                   help="fingerprint band half-width in MAD units "
                        "(default 4.0)")
    p.add_argument("--trend_persist_every", type=int, default=16,
                   metavar="N",
                   help="poll ticks between trend-store spool writes; "
                        "stop() always persists (default 16)")
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="offline self-check: 2 in-process replicas behind "
                        "the router, jobs submitted through it, one replica "
                        "killed mid-queue, every job must complete exactly "
                        "once with oracle-identical masks; one JSON line")
    return p


def parse_tenant_specs(specs: list[str]) -> tuple[dict, dict, dict]:
    """``NAME:QUOTA:WEIGHT[:BUDGET]`` -> (quotas, weights, budgets).
    BUDGET is an optional ADVISORY device-seconds budget (> 0) feeding
    the tenant_budget_burn alert rules (fleet/costs.py) — it never
    changes admission; quotas stay the only admission lever."""
    quotas: dict[str, int] = {}
    weights: dict[str, float] = {}
    budgets: dict[str, float] = {}
    for spec in specs:
        try:
            parts = spec.split(":")
            if len(parts) == 3:
                name, quota, weight = parts
                budget = ""
            elif len(parts) == 4:
                name, quota, weight, budget = parts
            else:
                raise ValueError
            if not name:
                raise ValueError
            quotas[name] = int(quota)
            weights[name] = float(weight)
            if quotas[name] < 0 or weights[name] <= 0:
                raise ValueError
            if len(parts) == 4:
                # An EMPTY fourth field ('survey:64:3:' — a trailing
                # colon typo, or an empty $BUDGET shell variable) is the
                # malformation that looks most like an intended budget:
                # reject it loudly instead of silently unmetering the
                # tenant.
                budgets[name] = float(budget)
                if budgets[name] <= 0:
                    raise ValueError
        except ValueError:
            raise ValueError(
                f"bad --tenant spec {spec!r}; expected "
                "NAME:QUOTA:WEIGHT[:BUDGET] like survey:64:3 or "
                "survey:64:3:3600 (quota >= 0, weight > 0, optional "
                "advisory device-seconds budget > 0)") from None
    return quotas, weights, budgets


def fleet_config_from_args(args: argparse.Namespace) -> FleetConfig:
    if not args.replica and not args.smoke:
        raise ValueError("at least one --replica URL is required "
                         "(or --smoke for the self-check)")
    if args.dead_after < 1:
        raise ValueError(f"--dead_after must be >= 1, got {args.dead_after}")
    if args.max_inflight < 0:
        raise ValueError(f"--max_inflight must be >= 0 (0 = unbounded), "
                         f"got {args.max_inflight}")
    if args.straggler_factor <= 1:
        raise ValueError(f"--straggler_factor must be > 1 (a replica AT "
                         f"the median is not a straggler), got "
                         f"{args.straggler_factor}")
    if args.straggler_polls < 1:
        raise ValueError(f"--straggler_polls must be >= 1, got "
                         f"{args.straggler_polls}")
    if args.straggler_window < 1:
        raise ValueError(f"--straggler_window must be >= 1, got "
                         f"{args.straggler_window}")
    if args.capacity_window < 1:
        raise ValueError(f"--capacity_window must be >= 1, got "
                         f"{args.capacity_window}")
    if args.min_replicas < 1:
        raise ValueError(f"--min_replicas must be >= 1, got "
                         f"{args.min_replicas}")
    if args.max_replicas < args.min_replicas:
        raise ValueError(f"--max_replicas ({args.max_replicas}) must be "
                         f">= --min_replicas ({args.min_replicas})")
    if args.scale_up_polls < 1 or args.scale_down_polls < 1:
        raise ValueError("--scale_up_polls/--scale_down_polls must be "
                         ">= 1 (the hysteresis windows)")
    if args.scale_cooldown_s < 0:
        raise ValueError(f"--scale_cooldown_s must be >= 0, got "
                         f"{args.scale_cooldown_s}")
    if args.history_ticks < 1:
        raise ValueError(f"--history_ticks must be >= 1, got "
                         f"{args.history_ticks}")
    if args.alert_retries < 0:
        raise ValueError(f"--alert_retries must be >= 0, got "
                         f"{args.alert_retries}")
    if args.canary_ticks < 0:
        raise ValueError(f"--canary_ticks must be >= 0 (0 = off), got "
                         f"{args.canary_ticks}")
    if args.recorder_segment_kb < 1:
        raise ValueError(f"--recorder_segment_kb must be >= 1, got "
                         f"{args.recorder_segment_kb}")
    if args.recorder_keep < 1:
        raise ValueError(f"--recorder_keep must be >= 1, got "
                         f"{args.recorder_keep}")
    if args.trend_keep_raw < 1:
        raise ValueError(f"--trend_keep_raw must be >= 1, got "
                         f"{args.trend_keep_raw}")
    if args.trend_sentinel_k < 1:
        raise ValueError(f"--trend_sentinel_k must be >= 1, got "
                         f"{args.trend_sentinel_k}")
    if args.trend_min_samples < 2:
        raise ValueError(f"--trend_min_samples must be >= 2 (a band "
                         f"needs a spread), got {args.trend_min_samples}")
    if args.trend_band_mad <= 0:
        raise ValueError(f"--trend_band_mad must be > 0, got "
                         f"{args.trend_band_mad}")
    if args.trend_persist_every < 1:
        raise ValueError(f"--trend_persist_every must be >= 1, got "
                         f"{args.trend_persist_every}")
    trend_signals: list[dict] = []
    for raw in args.trend_signal:
        try:
            spec = json.loads(raw)
        except ValueError as exc:
            raise ValueError(f"bad --trend_signal JSON {raw!r}: {exc}"
                             ) from None
        fleet_trends.parse_signal(spec)  # validate NOW, at the CLI surface
        trend_signals.append(spec)
    fleet_slo.parse_slo_specs(args.slo)  # validate NOW, at the CLI surface
    alert_rules: list[dict] = []
    for raw in args.alert_rule:
        try:
            spec = json.loads(raw)
        except ValueError as exc:
            raise ValueError(f"bad --alert_rule JSON {raw!r}: {exc}"
                             ) from None
        fleet_alerts.parse_rule(spec)   # validate NOW, at the CLI surface
        alert_rules.append(spec)
    if args.alert_rules:
        try:
            with open(args.alert_rules) as fh:
                file_rules = json.load(fh)
        except (OSError, ValueError) as exc:
            raise ValueError(f"cannot read --alert_rules "
                             f"{args.alert_rules!r}: {exc}") from None
        if not isinstance(file_rules, list):
            raise ValueError(f"--alert_rules {args.alert_rules!r} must "
                             "hold a JSON list of rule objects")
        for spec in file_rules:
            fleet_alerts.parse_rule(spec)
            alert_rules.append(spec)
    quotas, weights, budgets = parse_tenant_specs(args.tenant)
    return FleetConfig(
        replicas=tuple(args.replica),
        host=args.host,
        port=args.port,
        router_id=args.router_id,
        poll_interval_s=args.poll_interval_s,
        dead_after=args.dead_after,
        max_inflight=args.max_inflight,
        queue_timeout_s=args.queue_timeout_s,
        failover_retries=args.failover_retries,
        retry_backoff_s=args.retry_backoff_s,
        tenant_quotas=quotas,
        tenant_weights=weights,
        tenant_budgets=budgets,
        default_quota=args.default_quota,
        default_weight=args.default_weight,
        telemetry=args.telemetry,
        spool_dir=args.spool,
        straggler_factor=args.straggler_factor,
        straggler_polls=args.straggler_polls,
        straggler_window=args.straggler_window,
        straggler_phase=args.straggler_phase,
        slo_grant_s=args.slo_grant_s,
        capacity_window=args.capacity_window,
        autoscale=args.autoscale,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        scale_up_eta_s=args.scale_up_eta_s,
        scale_up_polls=args.scale_up_polls,
        scale_down_polls=args.scale_down_polls,
        scale_idle_util=args.scale_idle_util,
        scale_cooldown_s=args.scale_cooldown_s,
        spawn_retries=args.spawn_retries,
        spawn_args=tuple(args.spawn_arg),
        history_ticks=args.history_ticks,
        default_alerts=not args.no_default_alerts,
        alert_rules=tuple(alert_rules),
        alert_webhook=args.alert_webhook,
        alert_cmd=args.alert_cmd,
        alert_retries=args.alert_retries,
        canary_ticks=args.canary_ticks,
        slo=tuple(args.slo),
        recorder=not args.no_recorder,
        recorder_segment_kb=args.recorder_segment_kb,
        recorder_keep=args.recorder_keep,
        trends=not args.no_trends,
        trend_keep_raw=args.trend_keep_raw,
        trend_signals=tuple(trend_signals),
        trend_sentinel_k=args.trend_sentinel_k,
        trend_min_samples=args.trend_min_samples,
        trend_band_mad=args.trend_band_mad,
        trend_persist_every=args.trend_persist_every,
        quiet=args.quiet,
    )


def _merged_counters_equal(families) -> bool:
    """Check one parsed /fleet/metrics exposition's federation invariant:
    every merged counter total equals the sum of the per-replica series
    it was built from (summed in sorted-replica order, the same order the
    merge used, so float totals match bit-for-bit).  Shared by the smoke
    and the e2e tests."""
    merged: dict[tuple, float] = {}
    per_replica: dict[tuple, list] = {}
    for fam in families:
        if fam.kind != "counter":
            continue
        for name, labels, raw in fam.samples:
            value = obs_metrics.sample_value(raw)
            d = dict(labels)
            if fam.name.startswith("ict_fleet_"):
                merged[(name, labels)] = value
            elif "replica" in d:
                rid = d.pop("replica")
                key = (fleet_obs.merged_name(name),
                       tuple(p for p in labels if p[0] != "replica"))
                per_replica.setdefault(key, []).append((rid, value))
    if not per_replica:
        return False
    for key, entries in per_replica.items():
        total = 0.0
        for _rid, value in sorted(entries):
            total += value
        if merged.get(key) != total:
            return False
    return True


def run_fleet_smoke(cfg: FleetConfig) -> int:
    """Offline fleet self-check: 2 in-process replicas behind one router;
    several jobs submitted THROUGH the router; the replica holding a
    parked (undispatched) job is killed; every job must complete exactly
    once with masks bit-identical to the numpy oracle and the shadow
    audit clean; at least one failover must be recorded.  The fleet
    observability plane is asserted end to end on top: the merged
    ``GET /fleet/metrics`` scrape passes the strict exposition grammar
    with merged counters exactly equal to the per-replica sums and a
    nonzero ``fleet_jobs_completed``, the induced failover yields a
    stitched ``GET /fleet/trace`` spanning both replicas, and at least
    one incident bundle lands on disk.  A campaign lane (ISSUE 16) then
    runs a small survey manifest through ``POST /campaigns`` — one
    duplicate archive served born-terminal by the fleet result cache, a
    late-joined third replica killed mid-campaign — and asserts
    exactly-once completion, oracle-identical masks, and a QA roll-up +
    per-campaign cost row on the view.  A trend lane (ISSUE 20) arms an
    injected fingerprint on a synthetic speed gauge, drives a synthetic
    slowdown through sentinel firing -> ``perf_regression`` alert ->
    trend incident bundle -> live ``GET /fleet/trends`` view, then
    recovery until both resolve.  One JSON line, rc 0/1 — the CI lane
    next to ``serve --smoke``."""
    import tempfile
    import urllib.request

    import numpy as np

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.io.npz import NpzIO
    from iterative_cleaner_tpu.io.synthetic import make_archive
    from iterative_cleaner_tpu.obs import tracing
    from iterative_cleaner_tpu.ops.preprocess import preprocess
    from iterative_cleaner_tpu.parallel.batch import finalize_weights
    from iterative_cleaner_tpu.service.daemon import CleaningService
    from iterative_cleaner_tpu.service.daemon import ServeConfig
    from iterative_cleaner_tpu.service.jobs import TERMINAL

    def serve_cfg(tag: str, tmp: str, deadline_s: float,
                  bucket_cap: int = 0, coalesce: int = 1) -> ServeConfig:
        return ServeConfig(
            spool_dir=os.path.join(tmp, f"spool_{tag}"), port=0,
            replica_id=f"smoke-{tag}", deadline_s=deadline_s,
            bucket_cap=bucket_cap, coalesce=coalesce,
            quiet=True, clean=CleanConfig(backend="jax", quiet=True))

    result = {"smoke": "FAIL"}
    with tempfile.TemporaryDirectory(prefix="ict_fleet_smoke_") as tmp:
        paths = []
        for i in range(3):
            p = os.path.join(tmp, f"smoke{i}.npz")
            NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                      seed=200 + i), p)
            paths.append(p)
        # Replica a parks decoded cubes (huge deadline + a wide explicit
        # bucket that never fills): the job placed on it is accepted-but-
        # undispatched when it dies — exactly the failover case the
        # router must cover.  Replica b drains fast.
        svc_a = CleaningService(serve_cfg("a", tmp, deadline_s=3600.0,
                                          bucket_cap=8))
        # Replica b runs the coalescing rung (bucket_cap 1 x coalesce 2 =
        # a 2-cube flush threshold): the throughput-tier phase below
        # submits two same-shape cubes back to back and asserts they
        # shared ONE dispatch, masks bit-identical throughout.
        svc_b = CleaningService(serve_cfg("b", tmp, deadline_s=1.0,
                                          bucket_cap=1, coalesce=2))
        svc_a.start()
        svc_b.start()
        # Hermetic overrides only (the run_smoke idiom): replicas and the
        # port are the smoke's own; every other operator flag
        # (--dead_after, --poll_interval_s, tenant specs, --telemetry, -q)
        # is honored so the smoke exercises the configured behavior —
        # with a faster-than-default poll/death cadence when the operator
        # left them at the defaults, to keep the CI lane snappy.
        poll_s = (0.2 if cfg.poll_interval_s == FleetConfig.poll_interval_s
                  else cfg.poll_interval_s)
        dead_after = (2 if cfg.dead_after == FleetConfig.dead_after
                      else cfg.dead_after)
        router = FleetRouter(FleetConfig(**{
            **cfg.__dict__,
            "replicas": (f"http://127.0.0.1:{svc_a.port}",
                         f"http://127.0.0.1:{svc_b.port}"),
            "port": 0,
            "poll_interval_s": poll_s,
            "dead_after": dead_after,
            # Hermetic: incident bundles and flight dumps land in the
            # smoke's own tempdir, never the operator's spool.
            "spool_dir": os.path.join(tmp, "router_spool"),
            # The alerts lane (ISSUE 12): a tiny-threshold injected rule
            # that MUST fire while placements are open and resolve once
            # the fleet drains — one full firing -> resolved lifecycle
            # cycle, asserted below alongside the operator's own rules.
            "alert_rules": tuple(cfg.alert_rules) + ({
                "name": "smoke_open_placements", "severity": "info",
                "family": "ict_fleet_open_placements",
                "predicate": {"op": "gt", "value": 0}, "for_ticks": 1,
                "description": "serve-fleet --smoke injected rule"},),
            # The costs lane (ISSUE 15): a deliberately tiny advisory
            # budget that ONE dispatch's device-seconds must blow
            # through, driving a full tenant_budget_burn firing ->
            # resolved cycle through the alert plane below.
            "tenant_budgets": {**cfg.tenant_budgets, "smokecost": 1e-4},
            # The canary/SLO lane (ISSUE 18): a default objective per
            # journey when the operator gave none, so the burn-rate
            # rules register and the error-budget ledger runs.  Probe
            # cadence stays OFF — the lane drives one round
            # synchronously so the exactly-once deltas asserted above
            # stay deterministic.
            "slo": tuple(cfg.slo) or tuple(
                f"{j}:0.99:64" for j in fleet_slo.JOURNEYS),
            "canary_ticks": 0,
            # The trend lane (ISSUE 20): a synthetic per-replica speed
            # gauge published straight into the router registry, watched
            # by an injected fingerprint signal with a tiny arm/fire
            # ladder — the lane below drives healthy ticks (arms),
            # a slowdown (sentinel fires -> perf_regression alert ->
            # trend incident bundle), then recovery (resolves).
            "trend_signals": tuple(cfg.trend_signals) + ({
                "name": "smoke_speed", "mode": "gauge",
                "direction": "low",
                "family": "ict_fleet_smoke_trend_speed",
                "group_by": ["replica"], "window": 1,
                "min_samples": 3, "sentinel_k": 2},),
        }))
        router.start()
        jobs = {}
        svc_c = None    # the campaign lane's late-joining third replica
        try:
            base = f"http://{router.cfg.host}:{router.port}"
            before_done = tracing.counters_snapshot().get(
                "service_jobs_done", 0)
            for p in paths:
                req = urllib.request.Request(
                    f"{base}/jobs",
                    data=json.dumps({"path": p, "audit": True,
                                     "shape": [4, 16, 64]}).encode(),
                    headers={"Content-Type": "application/json"})
                jobs[p] = json.load(urllib.request.urlopen(req, timeout=30))
            placed_on_a = [j for j in jobs.values()
                           if j.get("replica_id") == "smoke-a"]
            # Wait until replica a has actually decoded and PARKED its
            # job(s) (bucketed, not yet dispatched), then kill it.
            deadline = time.time() + 120
            while time.time() < deadline:
                health = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{svc_a.port}/healthz", timeout=10))
                if health.get("bucketed_cubes", 0) >= len(placed_on_a) > 0:
                    break
                time.sleep(0.05)
            # One deterministic scrape pass BEFORE the crash: the dead
            # replica's pre-death metrics + flight ring must be in the
            # router's cache for the incident bundle and the stitched
            # trace (the poll loop would usually have done this already).
            router.poll_tick()
            svc_a.stop()    # the "crash": parked jobs stay in its spool
            # Router polls mark a dead and re-route; wait for every job
            # (under its fleet id) to turn terminal through the router.
            deadline = time.time() + 300
            states = {}
            while time.time() < deadline:
                states = {p: json.load(urllib.request.urlopen(
                    f"{base}/jobs/{j['id']}", timeout=10))
                    for p, j in jobs.items()}
                if all(s.get("state") in TERMINAL for s in states.values()):
                    break
                time.sleep(0.1)
            all_done = all(s.get("state") == "done"
                           for s in states.values())
            # Exactly once: the fleet-wide completion count (both
            # replicas share this process's tracing registry) moved by
            # exactly len(paths).
            done_delta = tracing.counters_snapshot().get(
                "service_jobs_done", 0) - before_done
            svc_b.auditor.drain(60)
            health_b = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{svc_b.port}/healthz", timeout=10))
            masks_ok = all_done
            if all_done:
                cfg_np = CleanConfig(backend="numpy")
                for p in paths:
                    want, _rfi = finalize_weights(
                        clean_cube(*preprocess(NpzIO().load(p)),
                                   cfg_np).weights, cfg_np)
                    got = NpzIO().load(states[p]["out_path"])
                    if not np.array_equal(got.weights, want):
                        masks_ok = False
            failovers = router.metrics.counter_total("fleet_failovers_total")
            # --- the fleet observability plane, end to end ---
            # Merged /fleet/metrics: strict grammar (the parse IS the
            # check), merged counters exactly the sum of the per-replica
            # series next to them, and the completion counter moved.
            fleet_text = urllib.request.urlopen(
                f"{base}/fleet/metrics", timeout=10).read().decode()
            fleet_ok = False
            try:
                fams = obs_metrics.parse_exposition(fleet_text)
            except ValueError:
                fams = []
            if fams:
                fleet_ok = (_merged_counters_equal(fams)
                            and router.metrics.counter_value(
                                "fleet_jobs_completed_total",
                                {"state": "done"}) == len(paths))
            # Stitched cross-hop trace: a failed-over job's timeline must
            # carry spans from BOTH replicas under its one trace id.
            trace_ok = False
            for j in jobs.values():
                trace = json.load(urllib.request.urlopen(
                    f"{base}/fleet/trace/{j['trace_id']}", timeout=10))
                span_sources = {s.get("source") for s in trace["spans"]}
                if {"smoke-a", "smoke-b"} <= span_sources:
                    trace_ok = True
                    break
            incidents = json.load(urllib.request.urlopen(
                f"{base}/fleet/incidents", timeout=10))["incidents"]
            # --- the alerting plane, end to end (ISSUE 12) ---
            # The injected rule fired while placements were open; with
            # every job terminal, drive ticks until it resolves (bounded
            # — the background loop may already have).
            deadline = time.time() + 60
            while time.time() < deadline:
                router.poll_tick()
                if not any(a["rule"] == "smoke_open_placements"
                           for a in router.alerts.firing()):
                    break
                time.sleep(0.05)
            alerts_view = json.load(urllib.request.urlopen(
                f"{base}/fleet/alerts", timeout=10))
            cycle = [t["state"] for t in alerts_view["recent"]
                     if t["rule"] == "smoke_open_placements"]
            alert_fired = router.metrics.counter_value(
                "fleet_alerts_total", {"rule": "smoke_open_placements",
                                       "severity": "info"})
            # The counter must be VISIBLE through the federated scrape,
            # and the history endpoint must serve re-renderable ticks.
            alert_text = urllib.request.urlopen(
                f"{base}/fleet/metrics", timeout=10).read().decode()
            counter_visible = False
            try:
                for fam in obs_metrics.parse_exposition(alert_text):
                    if fam.name != "ict_fleet_alerts_total":
                        continue
                    for _n, labels, raw in fam.samples:
                        if (dict(labels).get("rule")
                                == "smoke_open_placements"
                                and obs_metrics.sample_value(raw) >= 1):
                            counter_visible = True
            except ValueError:
                pass
            history_view = json.load(urllib.request.urlopen(
                f"{base}/fleet/metrics/history?ticks=4", timeout=10))
            bundles = fleet_alerts.list_alert_bundles(router.alert_dir)
            alerts_ok = (alert_fired >= 1 and counter_visible
                         and cycle[:2] == ["firing", "resolved"]
                         and not any(a["rule"] == "smoke_open_placements"
                                     for a in alerts_view["firing"])
                         and any(b.get("rule") == "smoke_open_placements"
                                 for b in bundles)
                         and len(history_view["ticks"]) >= 1)
            # --- the throughput tier (ROADMAP item 2): coalescing +
            # fleet-wide content-addressed reuse, end to end ---
            # Two fresh same-shape cubes submitted back to back must
            # share ONE coalesced dispatch on replica b (bucket_cap 1 x
            # coalesce 2), each mask bit-identical to its own oracle.
            def submit(p, extra=None, headers=None):
                req = urllib.request.Request(
                    f"{base}/jobs",
                    data=json.dumps({"path": p, **(extra or {})}).encode(),
                    headers={"Content-Type": "application/json",
                             **(headers or {})})
                return json.load(urllib.request.urlopen(req, timeout=30))

            co_paths = []
            for i in range(2):
                p2 = os.path.join(tmp, f"coalesce{i}.npz")
                NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                          seed=500 + i), p2)
                co_paths.append(p2)
            co_before = tracing.labeled_snapshot()
            co_jobs = {p2: submit(p2, {"shape": [4, 16, 64]})
                       for p2 in co_paths}
            co_states = {}
            deadline = time.time() + 300
            while time.time() < deadline:
                co_states = {p2: json.load(urllib.request.urlopen(
                    f"{base}/jobs/{j['id']}", timeout=10))
                    for p2, j in co_jobs.items()}
                if all(s.get("state") in TERMINAL
                       for s in co_states.values()):
                    break
                time.sleep(0.05)
            co_delta = {
                key: val - co_before.get(key, 0.0)
                for key, val in tracing.labeled_snapshot().items()
                if key[0] == "coalesce_batch_size_total"}
            coalesced_dispatches = sum(
                val for (_fam, labels), val in co_delta.items()
                if int(dict(labels).get("k", "1")) >= 2)
            co_masks_ok = all(s.get("state") == "done"
                              for s in co_states.values())
            if co_masks_ok:
                cfg_np = CleanConfig(backend="numpy")
                for p2 in co_paths:
                    want, _rfi = finalize_weights(
                        clean_cube(*preprocess(NpzIO().load(p2)),
                                   cfg_np).weights, cfg_np)
                    got = NpzIO().load(co_states[p2]["out_path"])
                    if not np.array_equal(got.weights, want):
                        co_masks_ok = False
            coalesce_ok = coalesced_dispatches >= 1 and co_masks_ok
            # A byte-identical resubmission (fresh idempotency key, the
            # original served on ANOTHER placement) must hit the router's
            # fleet-wide result cache: born terminal, byte-identical
            # output, and ZERO replica-side work (service_jobs_done does
            # not move).
            done_before_dup = tracing.counters_snapshot().get(
                "service_jobs_done", 0)
            dup = submit(paths[0])
            fleet_cache_hits = router.metrics.counter_total(
                "fleet_cache_hits_total")
            dup_no_work = (tracing.counters_snapshot().get(
                "service_jobs_done", 0) == done_before_dup)
            dup_masks_ok = False
            if dup.get("state") == "done" and dup.get("out_path"):
                cfg_np = CleanConfig(backend="numpy")
                want, _rfi = finalize_weights(
                    clean_cube(*preprocess(NpzIO().load(paths[0])),
                               cfg_np).weights, cfg_np)
                dup_masks_ok = bool(np.array_equal(
                    NpzIO().load(dup["out_path"]).weights, want))
            cache_ok = (dup.get("served_by") == "fleet-cache"
                        and fleet_cache_hits >= 1 and dup_no_work
                        and dup_masks_ok)
            # --- the campaign lane (ISSUE 16), end to end ---
            # A small survey manifest through POST /campaigns: the
            # orchestrator places every archive through the SAME ranked
            # placement path under campaign-scoped idempotency keys.  A
            # third parked replica joins the fleet at runtime and is
            # killed mid-campaign (the failover story again, now under
            # campaign keys); one manifest entry duplicates an archive
            # the fleet already cleaned, so it must be served
            # born-terminal by the result cache.  Asserted: the campaign
            # reaches "done" with every archive done, the jobs-done
            # ledger moves by the FRESH archive count only (exactly
            # once — duplicates and failovers add nothing), >= 1
            # fleet-cache hit, masks bit-identical to the solo numpy
            # oracle, and the view carries a QA roll-up plus a cost row
            # with real device-seconds and the dedupe dividend.
            svc_c = CleaningService(serve_cfg("c", tmp, deadline_s=3600.0,
                                              bucket_cap=8))
            svc_c.start()
            router.registry.add(f"http://127.0.0.1:{svc_c.port}")
            camp_paths = []
            for i in range(4):
                p3 = os.path.join(tmp, f"survey{i}.npz")
                NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                          seed=620 + i), p3)
                camp_paths.append(p3)
            camp_done_before = tracing.counters_snapshot().get(
                "service_jobs_done", 0)
            camp_cache_before = router.metrics.counter_total(
                "fleet_cache_hits_total")
            camp_req = urllib.request.Request(
                f"{base}/campaigns",
                data=json.dumps({
                    "name": "smoke-survey", "tenant": "smokesurvey",
                    "archives": camp_paths + [paths[0]],
                    "config": {"lane": "serve-fleet --smoke"},
                }).encode(),
                headers={"Content-Type": "application/json"})
            camp_row = json.load(urllib.request.urlopen(camp_req,
                                                        timeout=30))
            camp_id = camp_row["id"]
            # Kill replica c once campaign work is PARKED on it (decoded,
            # bucketed, undispatched — the worst failover case), or once
            # the campaign outran the placement race and finished
            # entirely on b; either way the crash lands while the run is
            # live whenever there is anything on c to fail over.
            camp_view: dict = {}
            deadline = time.time() + 300
            while time.time() < deadline:
                health_c = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{svc_c.port}/healthz", timeout=10))
                camp_view = json.load(urllib.request.urlopen(
                    f"{base}/campaigns/{camp_id}", timeout=10))
                if (health_c.get("bucketed_cubes", 0) >= 1
                        or camp_view.get("state") != "open"):
                    break
                time.sleep(0.05)
            svc_c.stop()    # the mid-campaign crash
            deadline = time.time() + 300
            while time.time() < deadline:
                camp_view = json.load(urllib.request.urlopen(
                    f"{base}/campaigns/{camp_id}", timeout=10))
                if camp_view.get("state") != "open":
                    break
                time.sleep(0.1)
            camp_done_delta = tracing.counters_snapshot().get(
                "service_jobs_done", 0) - camp_done_before
            camp_cache_hits = router.metrics.counter_total(
                "fleet_cache_hits_total") - camp_cache_before
            camp_masks_ok = camp_view.get("state") == "done"
            if camp_masks_ok:
                cfg_np = CleanConfig(backend="numpy")
                for rec in camp_view["archive_records"]:
                    want, _rfi = finalize_weights(
                        clean_cube(*preprocess(NpzIO().load(rec["path"])),
                                   cfg_np).weights, cfg_np)
                    got = NpzIO().load(rec["out_path"])
                    if not np.array_equal(got.weights, want):
                        camp_masks_ok = False
            camp_rollup = camp_view.get("rollup") or {}
            camp_cost = camp_view.get("cost") or {}
            camp_metrics_text = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            campaign_ok = (
                camp_view.get("state") == "done"
                and camp_view.get("archives", {}).get("done", 0)
                == len(camp_paths) + 1
                and camp_done_delta == len(camp_paths)
                and camp_cache_hits >= 1
                and camp_masks_ok
                and camp_rollup.get("jobs") == len(camp_paths) + 1
                and camp_rollup.get("with_quality") == len(camp_paths) + 1
                and camp_cost.get("jobs_costed") == len(camp_paths) + 1
                and camp_cost.get("device_s", 0.0) > 0
                and camp_cost.get("cache_hits", 0) >= 1
                and camp_cost.get("avoided_device_s", 0.0) > 0
                and (f'ict_campaign_device_seconds{{campaign="{camp_id}"}}'
                     in camp_metrics_text))
            # --- the canary/SLO plane (ISSUE 18), end to end ---
            # One synchronous probe round through the router's OWN HTTP
            # surface (the background poll loop keeps driving campaign
            # progress): every journey must come back green with a
            # bit-identical mask verdict, the probes must provably
            # never touch the capacity-demand, admission, or showback
            # planes, and the --slo objectives injected above must have
            # registered their multiwindow burn-rate rules.
            demand_before = router.capacity.demand_total()
            admit_before = router.metrics.counter_value(
                "fleet_tenant_admissions_total",
                {"tenant": SYNTHETIC_TENANT})
            verdicts = {v["journey"]: v
                        for v in router.canary.run_round()}
            router.poll_tick()   # fold verdicts into the SLI gauges
            canary_green = (
                set(verdicts) == set(fleet_slo.CANARY_JOURNEYS)
                and all(v.get("ok") and v.get("correct") is True
                        for v in verdicts.values()))
            canary_costs = json.load(urllib.request.urlopen(
                f"{base}/fleet/costs", timeout=10))
            synthetic_excluded = (
                router.capacity.demand_total() == demand_before
                and router.metrics.counter_value(
                    "fleet_tenant_admissions_total",
                    {"tenant": SYNTHETIC_TENANT}) == admit_before
                and SYNTHETIC_TENANT
                not in (canary_costs.get("tenants") or {}))
            rule_names = {r["name"] for r in router.alerts.rules_table()}
            burn_rules_ok = all(
                f"slo_burn_fast:{j}" in rule_names
                and f"slo_burn_slow:{j}" in rule_names
                for j in fleet_slo.JOURNEYS)
            slo_view = json.load(urllib.request.urlopen(
                f"{base}/fleet/slo", timeout=10))
            slo_report_ok = all(
                (slo_view.get("journeys", {}).get(j, {})
                 .get("availability") == 1.0)
                for j in fleet_slo.CANARY_JOURNEYS)
            canary_ok = (canary_green and synthetic_excluded
                         and burn_rules_ok and slo_report_ok)
            # --- the recorder/explain plane (ISSUE 19), end to end ---
            # Every REAL submission the smoke has made so far (fresh
            # placements and fleet-cache resolutions alike) sits on the
            # flight recorder's open tape, and the synchronous canary
            # round above injected synthetic traffic that must be absent
            # BY CONSTRUCTION.  Seal the production window, check the
            # /fleet/traces inventory, then replay the sealed segment
            # through the SAME ``prove --replay`` entry point operators
            # use: every entry must dedupe one-for-one under its
            # original idempotency key with ZERO new replica work
            # (service_jobs_done unmoved).  Then the explain plane: all
            # seven planes for a completed job on a live replica.
            import contextlib
            import io as io_mod
            from iterative_cleaner_tpu.proving import soak as proving_soak
            from iterative_cleaner_tpu.proving import (
                traces as proving_traces)
            rec_stats = router.recorder.stats()
            seg_path = router.recorder.seal()
            rec_inventory = json.load(urllib.request.urlopen(
                f"{base}/fleet/traces", timeout=10))
            seg_entries = (proving_traces.load_trace(seg_path)
                           if seg_path else [])
            rec_clean = (len(seg_entries) >= 1
                         and rec_stats["excluded_total"] >= 1
                         and not any(e.tenant == SYNTHETIC_TENANT
                                     for e in seg_entries))
            rec_done_before = tracing.counters_snapshot().get(
                "service_jobs_done", 0)
            replay_report: dict = {}
            replay_rc = 1
            if seg_path:
                replay_buf = io_mod.StringIO()
                with contextlib.redirect_stdout(replay_buf):
                    replay_rc = proving_soak.run_replay(
                        seg_path, base, compression=1000.0)
                replay_report = json.loads(
                    replay_buf.getvalue().strip().splitlines()[-1])
            rec_jobs_done_unmoved = (tracing.counters_snapshot().get(
                "service_jobs_done", 0) == rec_done_before)
            recorder_ok = (
                rec_clean and replay_rc == 0
                and replay_report.get("entries") == len(seg_entries)
                and replay_report.get("dedup_delta") == len(seg_entries)
                and rec_jobs_done_unmoved
                and len(rec_inventory.get("segments") or []) >= 1
                and bool(rec_inventory.get("recorder", {})
                         .get("enabled")))
            # Explain: the coalesce jobs finished on live replica b, so
            # the causal report must carry ALL seven planes with the
            # replica-backed ones sourced live.
            exp_job_id = co_jobs[co_paths[0]]["id"]
            exp_view = json.load(urllib.request.urlopen(
                f"{base}/fleet/explain/{exp_job_id}", timeout=10))
            explain_ok = (
                set(exp_view.get("planes") or {})
                == set(fleet_explain.PLANES)
                and exp_view["planes"]["cost"]["source"] == "live"
                and exp_view["planes"]["trace"]["source"]
                in ("live", "spool")
                and exp_view["planes"]["slo"]["source"] == "live"
                and exp_view.get("state") == "done")
            # --- the cost-accounting plane (ISSUE 15), end to end ---
            # A tenant-tagged job burns through the injected tiny
            # budget; the costs lane then asserts (a) attribution
            # CONSERVES — summed per-job device-seconds equal the
            # dispatch-seconds counter within 1%, (b) /fleet/costs
            # carries per-tenant rows, and (c) the tenant_budget_burn
            # rule completes a firing -> resolved cycle (resolution via
            # the replica leaving the fleet — the advisory-budget
            # semantics fleet/costs.py documents).
            cost_path = os.path.join(tmp, "smokecost.npz")
            NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                      seed=700), cost_path)
            cost_job = submit(cost_path, {"shape": [4, 16, 64]},
                              headers={"X-ICT-Tenant": "smokecost"})
            deadline = time.time() + 300
            while time.time() < deadline:
                state = json.load(urllib.request.urlopen(
                    f"{base}/jobs/{cost_job['id']}", timeout=10))
                if state.get("state") in TERMINAL:
                    break
                time.sleep(0.05)
            # Conservation off the replica exposition (both in-process
            # replicas share one registry; the sums on both sides cover
            # both, so the identity still holds exactly).  Bounded
            # retry: a job turns terminal (HTTP-visible) a beat before
            # the worker finalizes its cost record, so one read could
            # catch the window; a PERSISTENT violation still fails.
            cost_sum = dispatch_sum = 0.0
            conservation_ok = False
            deadline = time.time() + 60
            while time.time() < deadline and not conservation_ok:
                cost_text = urllib.request.urlopen(
                    f"http://127.0.0.1:{svc_b.port}/metrics",
                    timeout=10).read().decode()
                cost_sum = dispatch_sum = 0.0
                try:
                    for fam in obs_metrics.parse_exposition(cost_text):
                        for name, _labels, raw in fam.samples:
                            if name == "ict_cost_device_seconds_total":
                                cost_sum += obs_metrics.sample_value(raw)
                            elif name == "ict_service_dispatch_s":
                                dispatch_sum += obs_metrics.sample_value(raw)
                except ValueError:
                    break
                conservation_ok = (dispatch_sum > 0 and abs(
                    cost_sum / dispatch_sum - 1.0)
                    <= fleet_costs.CONSERVATION_TOLERANCE)
                if not conservation_ok:
                    time.sleep(0.1)
            router.poll_tick()   # fold the scrape into /fleet/costs
            costs_view = json.load(urllib.request.urlopen(
                f"{base}/fleet/costs", timeout=10))
            tenant_rows = costs_view.get("tenants", {})
            tenant_rows_ok = (
                "smokecost" in tenant_rows
                and tenant_rows["smokecost"].get("device_s", 0) > 0
                and (tenant_rows["smokecost"].get("budget_used_pct") or 0)
                > 100
                and "default" in tenant_rows)
            burn_rule = "tenant_budget_burn:smokecost"
            deadline = time.time() + 60
            while time.time() < deadline:
                if any(a["rule"] == burn_rule
                       for a in router.alerts.firing()):
                    break
                router.poll_tick()
                time.sleep(0.05)
            budget_fired = any(a["rule"] == burn_rule
                               for a in router.alerts.firing())
            # Resolution: stop replica b — once the registry marks it
            # dead, its per-life usage leaves the budget gauge (rebuilt
            # whole from ALIVE replicas) and the rule must resolve.
            svc_b.stop()
            deadline = time.time() + 60
            while time.time() < deadline:
                router.poll_tick()
                if not any(a["rule"] == burn_rule
                           for a in router.alerts.firing()):
                    break
                time.sleep(0.05)
            burn_cycle = [t["state"] for t in router.alerts.recent()
                          if t["rule"] == burn_rule]
            budget_cycle_ok = (budget_fired
                               and burn_cycle[:1] == ["firing"]
                               and "resolved" in burn_cycle)
            costs_ok = (state.get("state") == "done" and conservation_ok
                        and tenant_rows_ok and budget_cycle_ok)
            # Dead-replica provenance: replica b is gone now, so the
            # cost job's replica-backed planes must degrade to
            # "unavailable" (never stale data) while the report itself
            # still answers with the router-side planes.
            _dead_code, dead_exp = router.fleet_explain_job(
                cost_job["id"])
            explain_dead_ok = (
                _dead_code == 200
                and set(dead_exp.get("planes") or {})
                == set(fleet_explain.PLANES)
                and dead_exp["planes"]["zaps"]["source"] == "unavailable"
                and dead_exp["planes"]["cost"]["source"] == "unavailable")
            # --- the trend lane (ISSUE 20), end to end ---
            # The injected smoke_speed fingerprint watches a synthetic
            # router-registry gauge.  Healthy ticks arm it; a synthetic
            # slowdown must drive sentinel firing -> the
            # perf_regression alert (via the history ring, so one extra
            # tick) -> a trend incident bundle on disk and a live
            # ``GET /fleet/trends`` view of the violation; publishing
            # the healthy figure again must resolve both.
            def _pub_speed(v: float) -> None:
                router.metrics.replace_gauge_family(
                    "fleet_smoke_trend_speed",
                    {(("replica", "smoke-a"),): v})

            def _speed_firing() -> bool:
                return (router.trends is not None
                        and any(f["signal"] == "smoke_speed"
                                for f in router.trends.firing()))

            trend_armed = trend_fired = trend_alert = False
            trend_resolved = trend_view_ok = trend_bundle_ok = False
            if router.trends is not None:
                _pub_speed(10.0)
                deadline = time.time() + 60
                while time.time() < deadline and not trend_armed:
                    router.poll_tick()
                    trend_armed = any(
                        r["signal"] == "smoke_speed" and r["armed"]
                        for r in router.trends.fingerprints_json()
                        ["fingerprints"])
                    time.sleep(0.02)
                _pub_speed(1.0)     # the synthetic slowdown
                deadline = time.time() + 60
                while time.time() < deadline and not (trend_fired
                                                      and trend_alert):
                    router.poll_tick()
                    trend_fired = trend_fired or _speed_firing()
                    trend_alert = any(
                        a["rule"] == "perf_regression"
                        for a in router.alerts.firing())
                    time.sleep(0.02)
                trend_bundle_ok = any(
                    b.get("signal") == "smoke_speed"
                    for b in fleet_trends.list_trend_bundles(
                        router.trends.bundle_dir))
                trends_view = json.load(urllib.request.urlopen(
                    f"{base}/fleet/trends?family=ict_fleet_smoke_trend"
                    f"_speed&resolution=raw", timeout=10))
                trend_view_ok = (
                    trends_view.get("enabled") is not False
                    and any(f["signal"] == "smoke_speed"
                            for f in trends_view.get("firing", []))
                    and len(trends_view.get("series", [])) >= 1)
                _pub_speed(10.0)    # recovery
                deadline = time.time() + 60
                trend_resolved = True
                while time.time() < deadline:
                    router.poll_tick()
                    if not _speed_firing() and not any(
                            a["rule"] == "perf_regression"
                            for a in router.alerts.firing()):
                        break
                    time.sleep(0.02)
                else:
                    trend_resolved = False
            trends_ok = (trend_armed and trend_fired and trend_alert
                         and trend_bundle_ok and trend_view_ok
                         and trend_resolved)
            ok = (all_done and masks_ok and failovers >= 1
                  and done_delta == len(paths)
                  and fleet_ok and trace_ok and len(incidents) >= 1
                  and alerts_ok and coalesce_ok and cache_ok
                  and campaign_ok and canary_ok and costs_ok
                  and recorder_ok and explain_ok and explain_dead_ok
                  and trends_ok
                  and health_b.get("audits_run", 0) >= 1
                  and health_b.get("audit_divergences", 0) == 0)
            result = {
                "smoke": "ok" if ok else "FAIL",
                "jobs": len(paths),
                "jobs_done": sum(1 for s in states.values()
                                 if s.get("state") == "done"),
                "completions": int(done_delta),
                "failovers": int(failovers),
                "mask_identical_to_oracle": bool(masks_ok),
                "fleet_metrics_merged_ok": bool(fleet_ok),
                "stitched_trace_ok": bool(trace_ok),
                "incident_bundles": len(incidents),
                "alerts_lane_ok": bool(alerts_ok),
                "alerts_fired": int(alert_fired),
                "alert_bundles": len(bundles),
                "history_ticks": len(history_view["ticks"]),
                "coalesced_dispatches": int(coalesced_dispatches),
                "coalesce_masks_ok": bool(co_masks_ok),
                "fleet_cache_hits": int(fleet_cache_hits),
                "fleet_cache_hit_ok": bool(cache_ok),
                "campaign_lane_ok": bool(campaign_ok),
                "campaign_state": camp_view.get("state"),
                "campaign_archives_done": int(
                    camp_view.get("archives", {}).get("done", 0)),
                "campaign_jobs_delta": int(camp_done_delta),
                "campaign_cache_hits": int(camp_cache_hits),
                "campaign_masks_ok": bool(camp_masks_ok),
                "campaign_device_s": camp_cost.get("device_s"),
                "canary_lane_ok": bool(canary_ok),
                "canary_verdicts": {
                    j: bool(v.get("ok")) for j, v in verdicts.items()},
                "canary_synthetic_excluded": bool(synthetic_excluded),
                "slo_burn_rules_ok": bool(burn_rules_ok),
                "slo_tick": slo_view.get("tick"),
                "recorder_lane_ok": bool(recorder_ok),
                "recorder_segment_entries": len(seg_entries),
                "recorder_excluded": int(rec_stats["excluded_total"]),
                "recorder_replay_rc": int(replay_rc),
                "recorder_replay_dedup_delta": (
                    replay_report.get("dedup_delta")),
                "recorder_jobs_done_unmoved": bool(rec_jobs_done_unmoved),
                "explain_planes_ok": bool(explain_ok),
                "explain_dead_replica_ok": bool(explain_dead_ok),
                "trends_lane_ok": bool(trends_ok),
                "trend_sentinel_fired": bool(trend_fired),
                "trend_alert_fired": bool(trend_alert),
                "trend_bundle_ok": bool(trend_bundle_ok),
                "trend_view_ok": bool(trend_view_ok),
                "trend_resolved": bool(trend_resolved),
                "costs_lane_ok": bool(costs_ok),
                "cost_conservation_ratio": (
                    round(cost_sum / dispatch_sum, 4)
                    if dispatch_sum > 0 else None),
                "cost_tenant_rows_ok": bool(tenant_rows_ok),
                "budget_burn_cycle_ok": bool(budget_cycle_ok),
                "audits_run": health_b.get("audits_run", 0),
                "audit_divergences": health_b.get("audit_divergences", 0),
                "placements": {
                    rid: int(router.metrics.counter_value(
                        "fleet_placements_total", {"replica": rid}))
                    for rid in ("smoke-a", "smoke-b", "smoke-c")},
            }
            return 0 if ok else 1
        finally:
            print(json.dumps(result))
            router.stop()
            svc_b.stop()
            if svc_c is not None:
                svc_c.stop()    # idempotent if the lane already killed it


def run_autoscale_smoke(cfg: FleetConfig) -> int:
    """Offline autoscale self-check (the ``--smoke --autoscale act`` CI
    lane): ONE in-process jax replica behind a router running the
    capacity model + autoscaler in act mode.  An injected same-bucket
    backlog must drive a scale-up to a second (supervisor-spawned,
    in-process) replica; the post-drain idle must drive a
    drain-then-stop scale-down back to one; every job completes (zero
    lost) with masks bit-identical to the numpy oracle; >= 1 scale
    decision bundle lands on disk; and the merged ``GET /fleet/metrics``
    still passes the exact per-replica-sum equality check.  One JSON
    line, rc 0/1."""
    import tempfile
    import urllib.request

    import numpy as np

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.io.npz import NpzIO
    from iterative_cleaner_tpu.io.synthetic import make_archive
    from iterative_cleaner_tpu.obs import tracing
    from iterative_cleaner_tpu.ops.preprocess import preprocess
    from iterative_cleaner_tpu.parallel.batch import finalize_weights
    from iterative_cleaner_tpu.service.daemon import CleaningService
    from iterative_cleaner_tpu.service.daemon import ServeConfig
    from iterative_cleaner_tpu.service.jobs import TERMINAL

    result = {"smoke": "FAIL"}
    with tempfile.TemporaryDirectory(prefix="ict_autoscale_smoke_") as tmp:
        paths = []
        for i in range(4):
            p = os.path.join(tmp, f"smoke{i}.npz")
            NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                      seed=300 + i), p)
            paths.append(p)

        def serve_cfg(tag: str) -> ServeConfig:
            return ServeConfig(
                spool_dir=os.path.join(tmp, f"spool_{tag}"), port=0,
                replica_id=f"smoke-{tag}", deadline_s=0.2, quiet=True,
                clean=CleanConfig(backend="jax", quiet=True))

        svc = CleaningService(serve_cfg("seed"))
        svc.start()
        factory = fleet_autoscale.InProcessReplicaFactory(
            lambda rid: serve_cfg(rid))
        # Hermetic overrides (the run_smoke idiom): the replica set, the
        # port, the spool, and the poll loop are the smoke's own (ticks
        # are driven BY HAND for determinism); scaling thresholds drop
        # to a snappy cadence when the operator left them at the
        # defaults, and stay honored otherwise.
        router = FleetRouter(FleetConfig(**{
            **cfg.__dict__,
            "replicas": (f"http://127.0.0.1:{svc.port}",),
            "port": 0,
            "poll_interval_s": 999.0,   # manual, deterministic ticks
            "spool_dir": os.path.join(tmp, "router_spool"),
            "min_replicas": 1,
            "max_replicas": 2,
            "scale_up_polls": (
                2 if cfg.scale_up_polls == FleetConfig.scale_up_polls
                else cfg.scale_up_polls),
            "scale_up_eta_s": (
                0.5 if cfg.scale_up_eta_s == FleetConfig.scale_up_eta_s
                else cfg.scale_up_eta_s),
            "scale_down_polls": (
                3 if cfg.scale_down_polls == FleetConfig.scale_down_polls
                else cfg.scale_down_polls),
            "scale_cooldown_s": (
                1.0 if cfg.scale_cooldown_s == FleetConfig.scale_cooldown_s
                else cfg.scale_cooldown_s),
        }), replica_factory=factory)
        router.start()
        jobs = {}
        try:
            base = f"http://{router.cfg.host}:{router.port}"
            before_done = tracing.counters_snapshot().get(
                "service_jobs_done", 0)

            def submit(p):
                req = urllib.request.Request(
                    f"{base}/jobs",
                    data=json.dumps({"path": p,
                                     "shape": [4, 16, 64]}).encode(),
                    headers={"Content-Type": "application/json"})
                return json.load(urllib.request.urlopen(req, timeout=30))

            # Phase 1 — inject a same-bucket backlog (the first jax
            # dispatch compiles, so the queue genuinely sits) and tick
            # until the autoscaler acts: a second replica must join.
            for p in paths:
                jobs[p] = submit(p)
            scaled_up = False
            deadline = time.time() + 300
            while time.time() < deadline:
                router.poll_tick()
                if len(router.registry.snapshot()) >= 2:
                    scaled_up = True
                    break
                time.sleep(0.05)
            # Phase 2 — more traffic lands on the grown fleet; every job
            # must turn terminal through the router.
            extra = []
            for i in range(2):
                p = os.path.join(tmp, f"extra{i}.npz")
                NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                          seed=400 + i), p)
                extra.append(p)
                jobs[p] = submit(p)
            states = {}
            deadline = time.time() + 300
            while time.time() < deadline:
                router.poll_tick()
                states = {p: json.load(urllib.request.urlopen(
                    f"{base}/jobs/{j['id']}", timeout=10))
                    for p, j in jobs.items()}
                if all(s.get("state") in TERMINAL for s in states.values()):
                    break
                time.sleep(0.05)
            all_done = all(s.get("state") == "done"
                           for s in states.values())
            masks_ok = all_done
            if all_done:
                cfg_np = CleanConfig(backend="numpy")
                for p in jobs:
                    want, _rfi = finalize_weights(
                        clean_cube(*preprocess(NpzIO().load(p)),
                                   cfg_np).weights, cfg_np)
                    got = NpzIO().load(states[p]["out_path"])
                    if not np.array_equal(got.weights, want):
                        masks_ok = False
            done_delta = tracing.counters_snapshot().get(
                "service_jobs_done", 0) - before_done
            # Phase 3 — sustained idle: the scaler must drain-then-stop
            # the managed replica (back to the one seed replica).
            scaled_down = False
            deadline = time.time() + 300
            while time.time() < deadline:
                router.poll_tick()
                managed = (router.supervisor.managed()
                           if router.supervisor else {})
                if (managed
                        and all(s == "stopped" for s in managed.values())
                        and len(router.registry.snapshot()) == 1):
                    scaled_down = True
                    break
                time.sleep(0.05)
            up_events = router.metrics.counter_value(
                "fleet_scale_events_total",
                {"direction": "up", "reason": "backlog"})
            down_events = router.metrics.counter_value(
                "fleet_scale_events_total",
                {"direction": "down", "reason": "idle"})
            bundles = [b for b in fleet_obs.list_incidents(
                router.incident_dir)
                if str(b.get("reason", "")).startswith("scale_")]
            # The merged federation view must still hold exactly, and
            # the capacity gauges the decisions are explained by must be
            # on it.
            fleet_text = urllib.request.urlopen(
                f"{base}/fleet/metrics", timeout=10).read().decode()
            fleet_ok = False
            capacity_ok = False
            try:
                fams = obs_metrics.parse_exposition(fleet_text)
            except ValueError:
                fams = []
            if fams:
                fleet_ok = _merged_counters_equal(fams)
                names = {fam.name for fam in fams}
                capacity_ok = (
                    any(n.startswith("ict_fleet_capacity_")
                        for n in names)
                    and "ict_fleet_backlog_eta_seconds" in names
                    and "ict_fleet_scale_events_total" in names)
            ok = (scaled_up and scaled_down and all_done and masks_ok
                  and done_delta == len(jobs)
                  and up_events >= 1 and down_events >= 1
                  and len(bundles) >= 1 and fleet_ok and capacity_ok)
            result = {
                "smoke": "ok" if ok else "FAIL",
                "jobs": len(jobs),
                "jobs_done": sum(1 for s in states.values()
                                 if s.get("state") == "done"),
                "completions": int(done_delta),
                "scaled_up": bool(scaled_up),
                "scaled_down": bool(scaled_down),
                "scale_up_events": int(up_events),
                "scale_down_events": int(down_events),
                "scale_decision_bundles": len(bundles),
                "mask_identical_to_oracle": bool(masks_ok),
                "fleet_metrics_merged_ok": bool(fleet_ok),
                "capacity_gauges_ok": bool(capacity_ok),
            }
            return 0 if ok else 1
        finally:
            print(json.dumps(result))
            router.stop()
            svc.stop()


def fleet_main(argv: list[str] | None = None) -> int:
    args = build_fleet_parser().parse_args(argv)
    try:
        cfg = fleet_config_from_args(args)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.smoke:
        # --smoke --autoscale act runs the elastic-scaling self-check
        # (backlog-driven scale-up, drain-then-stop scale-down); the
        # plain smoke keeps covering placement/failover/federation.
        if cfg.autoscale == "act":
            return run_autoscale_smoke(cfg)
        return run_fleet_smoke(cfg)
    try:
        router = FleetRouter(cfg)
    except (RuntimeError, OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    # SIGTERM/SIGINT dump the router's flight ring before the graceful
    # stop — the same handler shape as serve_main: "what was the router
    # doing when the orchestrator killed it" becomes a file under
    # <spool>/flight instead of a guess (docs/OBSERVABILITY.md "Fleet
    # observability").  Installed BEFORE start(): an orchestrator that
    # signals the moment the startup line appears must hit the handler,
    # not the default disposition (the window used to lose rare races).
    import signal

    def _on_stop_signal(signum, frame):
        name = signal.Signals(signum).name
        path = flight.dump(name, router.flight_dir)
        print(f"ict-fleet: {name} — shutting down (replicas keep their "
              "accepted work; placements resume on restart via replica "
              f"spools{'; flight ring at ' + path if path else ''})",
              file=sys.stderr)
        raise SystemExit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_stop_signal)
        except (ValueError, OSError):  # noqa: PERF203 — non-main-thread embed
            pass
    try:
        router.start()
    except (RuntimeError, OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # Reached only when the SIGINT handler could not be installed (a
        # non-main-thread embed): same graceful stop, same flight dump.
        path = flight.dump("KeyboardInterrupt", router.flight_dir)
        print("ict-fleet: shutting down"
              f"{' (flight ring at ' + path + ')' if path else ''}",
              file=sys.stderr)
    finally:
        router.stop()
    return 0


def console_main() -> int:
    return fleet_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(fleet_main())
