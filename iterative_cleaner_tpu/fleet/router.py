"""The fleet router: one front door for N cleaning-daemon replicas.

Placement policy (docs/SERVING.md "Fleet"):

- **least-loaded-by-bucket** — candidates are ranked by the scalar load
  off their last ``/healthz`` snapshot (open jobs + every queue depth +
  placements routed since that snapshot), minus a **warm-cache affinity
  bonus** when the submission declares its shape bucket (optional
  ``"shape": [nsub, nchan, nbin]`` in the POST body): a replica whose
  warm pool holds the bucket's executables — or that already has cubes
  of that bucket queued — is preferred, because on it the job compiles
  nothing;
- **drain/death eviction** — a draining replica (``/healthz`` says
  ``draining: true``) or a dead one (``dead_after`` consecutive
  unreachable polls) gets no new placements; a dead replica's open
  placements are **re-routed** to surviving replicas carrying the same
  idempotency key, so the job runs at most once per replica and the
  fleet serves it exactly once while the dead replica stays dead;
- **failover retries** — submission-path transport failures walk the
  remaining candidates, then back off with **full jitter**
  (utils/backoff.py; ``ICT_BACKOFF_SEED`` pins schedules in tests) so N
  routers (or one router's N queued failovers) recovering from the same
  incident don't thundering-herd the revived replica;
- **multi-tenant admission** — per-tenant open-placement quotas (429 +
  ``Retry-After`` on breach) and weighted fair queueing over placement
  grants when submissions contend for the ``--max_inflight`` budget
  (fleet/tenants.py; ``X-ICT-Tenant`` header, absent -> "default").

The router is just another stdlib-HTTP daemon — ``serve-fleet`` on the
CLI, ``ThreadingHTTPServer`` + ``urllib`` inside, zero new dependencies
— and it exposes its own ``/metrics`` (placements, failovers, per-tenant
admissions/rejections, per-replica queue-depth gauges) so the obs tower
sees the fleet as one system.  Trace context crosses the hop: the
router forwards ``X-ICT-Trace`` on proxied submissions and emits
``fleet_placement`` / ``fleet_failover`` events into the event log and
the flight ring.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from iterative_cleaner_tpu.fleet.client import (
    ReplicaClient,
    ReplicaRefused,
    ReplicaUnreachable,
)
from iterative_cleaner_tpu.fleet.registry import Replica, ReplicaRegistry
from iterative_cleaner_tpu.fleet.tenants import (
    DEFAULT_TENANT,
    QuotaExceeded,
    TenantAdmission,
    WeightedFairQueue,
)
from iterative_cleaner_tpu.obs import events
from iterative_cleaner_tpu.obs.metrics import _fmt, _labels
from iterative_cleaner_tpu.service.scheduler import bucket_label
from iterative_cleaner_tpu.utils import backoff

#: Placement-score bonus for a replica whose warm pool already holds the
#: submission's shape bucket (it will compile nothing), and the smaller
#: bonus for one that merely has the bucket queued (its compile is paid
#: or in flight).  Units are "queued cubes": a warm replica wins ties
#: and small load deficits, but a deeply-backlogged warm replica still
#: loses to an idle cold one.
AFFINITY_WARM = 2.5
AFFINITY_QUEUED = 1.25

#: Consecutive 404 status polls before an open placement is declared
#: lost (its replica restarted with a cleared spool and genuinely does
#: not know the job) and failed terminally.
MISSING_POLLS_LOST = 3


class FleetBusy(RuntimeError):
    """No replica could take the job right now (all dead, draining, or
    at capacity, or the placement-grant wait timed out) — HTTP 503 with
    Retry-After, the replica admission-cap convention."""


@dataclass
class FleetConfig:
    replicas: tuple = ()             # replica base URLs, e.g. http://h:8750
    host: str = "127.0.0.1"
    port: int = 8790                 # 0 = ephemeral (tests)
    router_id: str = ""              # "" = mint one per process life
    poll_interval_s: float = 1.0     # health-poll + failover-sweep cadence
    dead_after: int = 3              # consecutive unreachable polls -> dead
    replica_timeout_s: float = 10.0  # per router->replica HTTP call
    max_inflight: int = 0            # fleet-wide open-placement budget
                                     # (0 = unbounded); contention beyond it
                                     # is arbitrated by weighted fair queueing
    queue_timeout_s: float = 30.0    # max wait for a placement grant
    failover_retries: int = 2        # extra candidate sweeps per submission
    retry_backoff_s: float = 0.25    # full-jitter base between sweeps
    placement_keep: int = 10000      # terminal placement records kept
    tenant_quotas: dict = field(default_factory=dict)
    tenant_weights: dict = field(default_factory=dict)
    default_quota: int = 0           # per-tenant open-placement cap (0 = off)
    default_weight: float = 1.0
    telemetry: str = ""              # JSON-lines event log (obs/events)
    quiet: bool = False


@dataclass
class Placement:
    """One routed job.  ``job_id`` is the fleet-visible identity — the id
    the FIRST accepting replica minted, which the client holds from its
    202; after a failover the serving replica (and its inner job id)
    change underneath while the fleet id stays stable, and proxied reads
    rewrite the manifest back to it."""

    job_id: str
    tenant: str
    trace_id: str
    payload: dict                   # forwarded verbatim on re-route, with
                                    # the idempotency key inside — the same
                                    # key is what makes re-routes dedupe
    base_url: str
    replica_id: str
    replica_job_id: str
    state: str = "open"             # open -> done | error
    error: str = ""
    attempts: int = 1               # placements incl. failover re-routes
    submitted_s: float = 0.0
    missing_polls: int = 0          # consecutive status polls the serving
                                    # replica answered 404 — a revived
                                    # replica whose spool was cleared has
                                    # genuinely lost the job, and the
                                    # placement must fail terminally
                                    # instead of leaking its slot forever


def new_router_id() -> str:
    return f"fr-{uuid.uuid4().hex[:8]}"


class RouterMetrics:
    """The router's own tiny metric registry, rendered as Prometheus
    text on ``/metrics``.  Deliberately NOT the process-global
    obs.tracing registry: fleet tests run a router and three replicas in
    one process, and the router's counters must not bleed into (or read
    from) the replicas' — each HTTP surface exposes exactly its own
    process role."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (family, ((label, value), ...)) -> float
        self._counters: dict = {}  # ict: guarded-by(self._lock)
        self._gauges: dict = {}  # ict: guarded-by(self._lock)

    @staticmethod
    def _key(family: str, labels: dict | None):
        return (family, tuple(sorted((labels or {}).items())))

    def count(self, family: str, labels: dict | None = None,
              inc: float = 1.0) -> None:
        key = self._key(family, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + inc

    def counter_value(self, family: str, labels: dict | None = None) -> float:
        with self._lock:
            return self._counters.get(self._key(family, labels), 0.0)

    def counter_total(self, family: str) -> float:
        with self._lock:
            return sum(v for (fam, _), v in self._counters.items()
                       if fam == family)

    def set_gauge(self, family: str, labels: dict | None,
                  value: float) -> None:
        with self._lock:
            self._gauges[self._key(family, labels)] = float(value)

    def replace_gauge_family(self, family: str,
                             entries: dict[tuple, float]) -> None:
        """Swap every sample of one gauge family atomically — per-replica
        and per-bucket gauges are rebuilt from each health poll, and a
        bucket that drained (or a replica that left) must drop off the
        exposition rather than freeze at its last value."""
        with self._lock:
            for key in [k for k in self._gauges if k[0] == family]:
                del self._gauges[key]
            for labels, value in entries.items():
                self._gauges[(family, tuple(sorted(labels)))] = float(value)

    def render(self) -> str:
        """Prometheus text exposition; same grammar obs/metrics.py renders
        (pinned by the strict-regex test in tests/test_fleet.py)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        lines: list[str] = []
        for kind, table in (("counter", counters), ("gauge", gauges)):
            seen: set[str] = set()
            for (family, label_pairs) in sorted(table):
                if family not in seen:
                    seen.add(family)
                    lines.append(f"# TYPE ict_{family} {kind}")
                lines.append(f"ict_{family}{_labels(label_pairs)} "
                             f"{_fmt(table[(family, label_pairs)])}")
        return "\n".join(lines) + "\n"


class _Ticket:
    """One submission waiting for a placement grant; written only under
    the router's placement condition lock."""

    __slots__ = ("granted", "abandoned")

    def __init__(self) -> None:
        self.granted = False
        self.abandoned = False


class FleetRouter:
    """Lifecycle + the placement engine.  Thread layout (all daemonic):
    the ThreadingHTTPServer's per-request threads (submissions block in
    the WFQ grant wait; reads are lock-snapshot cheap) and ONE poll
    thread (health refresh, placement-status refresh, failover sweep,
    gauge rebuild).  All shared state sits behind ``self._cond``'s lock
    (placements, inflight budget, WFQ) or the registry's/metrics' own
    locks — acquisition order is always router -> registry/metrics,
    never the reverse."""

    def __init__(self, cfg: FleetConfig) -> None:
        if not cfg.replicas:
            raise ValueError("a fleet needs at least one --replica URL")
        self.cfg = cfg
        self.router_id = cfg.router_id or new_router_id()
        self.started_s = time.time()
        self.client = ReplicaClient(timeout_s=cfg.replica_timeout_s)
        self.registry = ReplicaRegistry(
            [u.rstrip("/") for u in cfg.replicas],
            dead_after=cfg.dead_after)
        self.admission = TenantAdmission(
            quotas=cfg.tenant_quotas, default_quota=cfg.default_quota)
        self.metrics = RouterMetrics()
        # RLock, deliberately: the grant pump (_grant_free_slots) takes it
        # lexically so every _inflight mutation sits under a visible
        # ``with self._lock:`` (the ICT007 discipline), and its callers
        # already hold the lock when pumping after a state change.
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._wfq = WeightedFairQueue(
            weights=cfg.tenant_weights, default_weight=cfg.default_weight)
        self._placements: dict[str, Placement] = {}  # ict: guarded-by(self._lock)
        # idempotency key -> fleet job id ("" while a placement carrying
        # the key is in flight): the ROUTER-side half of the dedupe — a
        # client retry with a pinned key must not run the job again on a
        # DIFFERENT replica (the replica-side map only covers retries
        # that land on the same one).  Trimmed with the placement table.
        self._idem_index: dict[str, str] = {}  # ict: guarded-by(self._lock)
        self._inflight = 0  # ict: guarded-by(self._lock)
        # One shared full-jitter RNG for failover backoff; drawn under its
        # own lock (random.Random is not documented thread-safe, and the
        # ICT_BACKOFF_SEED test hook wants one reproducible stream).
        self._rng_lock = threading.Lock()
        self._backoff_rng = backoff.make_rng()  # ict: guarded-by(self._rng_lock)
        self._stop_evt = threading.Event()
        self._threads: list[threading.Thread] = []
        self._server = None
        self.port = cfg.port

    # --- lifecycle ---

    def start(self) -> None:
        # Same contract as the daemon: telemetry="" must MEAN "honor
        # ICT_TELEMETRY / disabled", never inherit a predecessor's sink.
        events.configure(self.cfg.telemetry or None)
        # Synchronous first poll: replica identities and load snapshots
        # exist before the first placement decision.
        self.registry.poll_once(self.client)
        self._update_replica_gauges()
        th = threading.Thread(target=self._poll_loop, daemon=True,
                              name=f"ict-fleet-poll-{self.router_id}")
        th.start()
        self._threads.append(th)
        self._server = ThreadingHTTPServer(
            (self.cfg.host, self.cfg.port), _RouterHandler)
        self._server.daemon_threads = True
        self._server.router = self
        self.port = self._server.server_address[1]
        th = threading.Thread(target=self._server.serve_forever, daemon=True,
                              name=f"ict-fleet-http-{self.router_id}")
        th.start()
        self._threads.append(th)
        if not self.cfg.quiet:
            alive = sum(1 for r in self.registry.snapshot() if r["alive"])
            print(f"ict-fleet: router {self.router_id} listening on "
                  f"http://{self.cfg.host}:{self.port} "
                  f"({alive}/{len(self.cfg.replicas)} replicas alive)",
                  file=sys.stderr)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        self._stop_evt.set()
        with self._lock:
            self._cond.notify_all()
        for th in self._threads:
            th.join(timeout=10)

    # --- the poll loop: health, status refresh, failover, gauges ---

    def _poll_loop(self) -> None:
        while not self._stop_evt.wait(self.cfg.poll_interval_s):
            self.poll_tick()

    def poll_tick(self) -> None:
        """One maintenance pass; public so tests (and the smoke check)
        can drive the loop deterministically instead of sleeping."""
        newly_dead = self.registry.poll_once(self.client)
        for rep in newly_dead:
            if not self.cfg.quiet:
                print(f"ict-fleet: replica {rep.replica_id or rep.base_url} "
                      f"is dead after {rep.consecutive_failures} failed "
                      "health checks; re-routing its open placements",
                      file=sys.stderr)
        self._refresh_open_placements()
        self._failover_sweep()
        self._update_replica_gauges()
        self._trim_placements()
        # Replica capacity may have freed (placements turned terminal) —
        # wake any submissions parked in the WFQ grant wait.
        self._grant_free_slots()

    def _refresh_open_placements(self) -> None:
        with self._lock:
            open_now = [p for p in self._placements.values()
                        if p.state == "open"]
        # One wedged replica must not stall every placement's refresh for
        # a timeout each: after the first transport failure to a URL this
        # tick, its remaining placements are skipped (the death countdown
        # and the failover sweep own them from here).
        unreachable_now: set[str] = set()
        for p in open_now:
            rep = self.registry.get(p.base_url)
            if (rep is None or not rep.alive
                    or p.base_url in unreachable_now):
                continue   # the failover sweep owns unreachable replicas
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
            except ReplicaRefused as exc:
                if exc.status != 404:
                    continue
                # A 404 right after accept is just spool-visibility lag —
                # but a replica that KEEPS not knowing the job has lost it
                # (restarted with a cleared spool inside the death
                # window): fail the placement terminally instead of
                # leaking its slot and quota forever.
                with self._lock:
                    p.missing_polls += 1
                    gone = p.missing_polls >= MISSING_POLLS_LOST
                if gone:
                    self._mark_terminal(
                        p, "error",
                        error=f"job {p.replica_job_id} vanished from "
                              f"replica {p.replica_id} (restarted with a "
                              "cleared spool?)")
                continue
            except ReplicaUnreachable:
                unreachable_now.add(p.base_url)
                dead = self.registry.note_unreachable(p.base_url)
                if dead is not None and not self.cfg.quiet:
                    print(f"ict-fleet: replica {dead.replica_id} died "
                          "mid-status-poll", file=sys.stderr)
                continue
            with self._lock:
                p.missing_polls = 0
            self._observe_manifest(p, manifest)

    def _failover_sweep(self) -> None:
        """Re-route every open placement whose replica is dead.  Runs on
        the poll thread only; a sweep that cannot place (everyone busy)
        leaves the placement open for the next tick — re-routing is
        idempotent because the replica-side idempotency key rides inside
        the stored payload."""
        with self._lock:
            stranded = [p for p in self._placements.values()
                        if p.state == "open"]
        for p in stranded:
            rep = self.registry.get(p.base_url)
            if rep is not None and rep.alive:
                continue
            from_id = p.replica_id or p.base_url
            try:
                new_rep, body = self._submit_with_failover(
                    p.payload, p.trace_id, exclude={p.base_url})
            except FleetBusy:
                continue           # next tick retries
            except ReplicaRefused as exc:
                # A re-route the fleet *rejected* (e.g. the surviving
                # replicas' --root refuses the path): the job can never
                # complete — surface it as a terminal error instead of
                # sweeping it forever.
                self._mark_terminal(p, "error", error=str(exc))
                continue
            with self._lock:
                p.base_url = new_rep.base_url
                p.replica_id = new_rep.replica_id
                p.replica_job_id = str(body.get("id", p.replica_job_id))
                p.attempts += 1
            self.metrics.count("fleet_failovers_total",
                               {"from_replica": from_id})
            if events.active():
                events.emit("fleet_failover", trace_id=p.trace_id,
                            job_id=p.job_id, from_replica=from_id,
                            to_replica=new_rep.replica_id,
                            tenant=p.tenant, attempts=p.attempts)
            if not self.cfg.quiet:
                print(f"ict-fleet: job {p.job_id} re-routed "
                      f"{from_id} -> {new_rep.replica_id}", file=sys.stderr)

    def _update_replica_gauges(self) -> None:
        snap = self.registry.snapshot()
        states = {"alive": 0, "draining": 0, "dead": 0}
        depth: dict[tuple, float] = {}
        buckets: dict[tuple, float] = {}
        for row in snap:
            rid = row["replica_id"] or row["base_url"]
            if not row["alive"]:
                states["dead"] += 1
            elif row["draining"]:
                states["draining"] += 1
            else:
                states["alive"] += 1
            for queue in ("open_jobs", "load_queue_depth",
                          "dispatch_queue_depth", "bucketed_cubes"):
                depth[(("queue", queue), ("replica", rid))] = float(
                    row.get(queue, 0) or 0)
            for bucket, n in row["bucket_queue_depths"].items():
                buckets[(("bucket", str(bucket)), ("replica", rid))] = float(n)
        self.metrics.replace_gauge_family(
            "fleet_replicas",
            {(("state", s),): float(n) for s, n in states.items()})
        self.metrics.replace_gauge_family("fleet_replica_queue_depth", depth)
        self.metrics.replace_gauge_family(
            "fleet_replica_bucket_queue_depth", buckets)
        with self._lock:
            open_n = sum(1 for p in self._placements.values()
                         if p.state == "open")
            queued = len(self._wfq)
        self.metrics.replace_gauge_family(
            "fleet_open_placements", {(): float(open_n)})
        self.metrics.replace_gauge_family(
            "fleet_queued_submissions", {(): float(queued)})

    def _trim_placements(self) -> None:
        """Bound the placement table by evicting the oldest TERMINAL
        records beyond ``placement_keep`` (job ids are time-sortable, the
        spool-trim rationale) — open placements are never touched."""
        with self._lock:
            terminal = sorted(jid for jid, p in self._placements.items()
                              if p.state != "open")
            for jid in terminal[: max(0, len(terminal)
                                      - self.cfg.placement_keep)]:
                del self._placements[jid]
            # The idempotency index follows the placement table: an entry
            # whose placement was trimmed can no longer dedupe (in-flight
            # "" reservations are owned by their placing thread).
            for key in [k for k, jid in self._idem_index.items()
                        if jid and jid not in self._placements]:
                del self._idem_index[key]

    # --- placement ---

    def place_job(self, payload: dict, tenant: str, trace_id: str) -> dict:
        """Admit + grant + place one submission; returns the 202 body.
        Raises QuotaExceeded (-> 429), FleetBusy (-> 503), ReplicaRefused
        (the replica's own 4xx passes through)."""
        key = str(payload.get("idempotency_key", "") or "")
        known = self._resolve_idem(key)
        if known is not None:
            return known
        try:
            return self._place_fresh(payload, tenant, trace_id, key)
        except BaseException:
            self._drop_idem_reservation(key)
            raise

    def _resolve_idem(self, key: str) -> dict | None:
        """Router-side idempotency: a key this router already placed
        resolves to its existing fleet job (whatever replica serves it
        now) instead of running again — the replica-side map only covers
        retries that happen to land on the same replica.  Returns the
        reply to serve, or None after reserving the key for a fresh
        placement (the caller owns the reservation)."""
        if not key:
            return None
        with self._lock:
            known = self._idem_index.get(key)
            if known is None:
                self._idem_index[key] = ""   # reservation: we place it
                return None
        if known == "":
            # Another handler thread is mid-placement on this key; a 503
            # tells the client to retry into the resolved entry.
            raise FleetBusy(f"a submission with idempotency key {key!r} "
                            "is being placed; retry shortly")
        code, manifest = self.job_manifest(known)
        if code == 200:
            self.metrics.count("fleet_deduped_submissions_total")
            return {**manifest, "router_id": self.router_id}
        # The placement was trimmed from the table: place afresh.
        with self._lock:
            self._idem_index[key] = ""
        return None

    def _drop_idem_reservation(self, key: str) -> None:
        with self._lock:
            if key and self._idem_index.get(key) == "":
                del self._idem_index[key]

    def _place_fresh(self, payload: dict, tenant: str, trace_id: str,
                     key: str) -> dict:
        try:
            self.admission.admit(tenant)
        except QuotaExceeded:
            self.metrics.count("fleet_tenant_rejections_total",
                               {"tenant": tenant})
            raise
        self.metrics.count("fleet_tenant_admissions_total",
                           {"tenant": tenant})
        try:
            self._await_grant(tenant)
        except BaseException:
            self.admission.release(tenant)
            raise
        try:
            rep, body = self._submit_with_failover(payload, trace_id)
        except BaseException:
            self._release_slot()
            self.admission.release(tenant)
            raise
        placement = Placement(
            job_id=str(body.get("id", "")),
            tenant=tenant, trace_id=trace_id, payload=payload,
            base_url=rep.base_url, replica_id=rep.replica_id,
            replica_job_id=str(body.get("id", "")),
            submitted_s=time.time())
        with self._lock:
            existing = self._placements.get(placement.job_id)
            duplicate = existing is not None and existing.state == "open"
            if not duplicate:
                self._placements[placement.job_id] = placement
            if key:
                self._idem_index[key] = placement.job_id
        if duplicate:
            # The replica deduped a client-pinned idempotency key onto a
            # job this router already tracks as OPEN: the original
            # placement keeps the in-flight slot and the quota count, so
            # the retry's admit/grant must be handed back here — silently
            # replacing the record would leak one of each per retry.
            self._release_slot()
            self.admission.release(tenant)
            return {**body, "tenant": tenant, "router_id": self.router_id}
        self.metrics.count("fleet_placements_total",
                           {"replica": rep.replica_id or rep.base_url})
        if events.active():
            events.emit("fleet_placement", trace_id=trace_id,
                        job_id=placement.job_id,
                        replica_id=rep.replica_id, tenant=tenant,
                        bucket=self._bucket_of(payload))
        return {**body, "tenant": tenant, "router_id": self.router_id}

    def _await_grant(self, tenant: str) -> None:
        """Weighted-fair wait for an in-flight slot.  With no budget
        configured the grant is immediate; under contention, grants pop
        in WFQ order as slots free (placements observed terminal)."""
        ticket = _Ticket()
        deadline = time.monotonic() + self.cfg.queue_timeout_s
        with self._lock:
            self._wfq.push(tenant, ticket)
            self._grant_free_slots()
            while not ticket.granted:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop_evt.is_set():
                    ticket.abandoned = True
                    raise FleetBusy(
                        f"no placement slot within "
                        f"{self.cfg.queue_timeout_s:g}s "
                        f"({self._inflight} in flight at the "
                        f"--max_inflight budget); retry later")
                self._cond.wait(remaining)

    def _grant_free_slots(self) -> None:
        """Pop WFQ tickets into free in-flight slots and wake their
        waiters.  Takes the (reentrant) placement lock itself, so every
        call site — callers already holding it included — keeps the
        mutation lexically guarded."""
        with self._lock:
            while len(self._wfq) and (
                    not self.cfg.max_inflight
                    or self._inflight < self.cfg.max_inflight):
                popped = self._wfq.pop()
                if popped is None:
                    break
                _tenant, ticket = popped
                if ticket.abandoned:
                    continue
                ticket.granted = True
                self._inflight += 1
            self._cond.notify_all()

    def _release_slot(self) -> None:
        with self._lock:
            self._inflight -= 1
            self._grant_free_slots()

    @staticmethod
    def _bucket_of(payload: dict) -> str:
        shape = payload.get("shape")
        if (isinstance(shape, (list, tuple)) and len(shape) == 3
                and all(isinstance(v, (int, float)) for v in shape)):
            return bucket_label(shape)
        return ""

    def _ranked_candidates(self, bucket: str,
                           exclude: set[str]) -> list[Replica]:
        cands = [r for r in self.registry.candidates()
                 if r.base_url not in exclude]

        def score(rep: Replica) -> float:
            s = rep.load()
            if bucket:
                if bucket in rep.warm_buckets():
                    s -= AFFINITY_WARM
                if rep.queued_buckets().get(bucket, 0) > 0:
                    s -= AFFINITY_QUEUED
            return s

        # Deterministic tie-break on replica identity, so tests (and two
        # routers sharing one fleet) rank identically from identical
        # snapshots.
        cands.sort(key=lambda r: (score(r), r.replica_id or r.base_url))
        return cands

    def _submit_with_failover(self, payload: dict, trace_id: str,
                              exclude: set[str] | None = None):
        """Walk the ranked candidates; on transport failure note the
        death countdown and move on; on 503 (busy/draining) move on; on
        any other refusal propagate (the client's problem, not the
        fleet's).  Between sweeps, full-jitter backoff."""
        exclude = set(exclude or ())
        bucket = self._bucket_of(payload)
        last_err: Exception | None = None
        for sweep in range(1 + max(self.cfg.failover_retries, 0)):
            if sweep:
                with self._rng_lock:
                    delay = backoff.full_jitter(
                        self.cfg.retry_backoff_s, sweep - 1,
                        rng=self._backoff_rng)
                time.sleep(delay)
            for rep in self._ranked_candidates(bucket, exclude):
                try:
                    body = self.client.submit(rep.base_url, payload,
                                              trace_id=trace_id)
                except ReplicaUnreachable as exc:
                    last_err = exc
                    self.registry.note_unreachable(rep.base_url)
                    continue
                except ReplicaRefused as exc:
                    if exc.status == 503:   # at capacity, or draining
                        last_err = exc
                        continue
                    raise
                self.registry.note_placed(rep.base_url)
                return rep, body
        raise FleetBusy(f"no replica accepted the job: "
                        f"{last_err or 'no live replicas'}")

    # --- reads ---

    def job_manifest(self, job_id: str) -> tuple[int, dict]:
        with self._lock:
            p = self._placements.get(job_id)
        if p is None:
            return 404, {"error": "no such job"}
        rep = self.registry.get(p.base_url)
        if p.state == "open" and (rep is None or rep.alive):
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
            except ReplicaRefused as exc:
                return exc.status, exc.body
            except ReplicaUnreachable:
                self.registry.note_unreachable(p.base_url)
                manifest = None
            if manifest is not None:
                self._observe_manifest(p, manifest)
                return 200, {**manifest, "id": p.job_id,
                             "replica_id": p.replica_id,
                             "tenant": p.tenant}
        if p.state == "open":
            # The replica is unreachable and the failover sweep has not
            # re-placed the job yet: report it still pending so clients
            # keep polling through the hole.
            return 200, {"id": p.job_id, "state": "pending",
                         "replica_id": p.replica_id, "tenant": p.tenant,
                         "trace_id": p.trace_id, "attempts": p.attempts,
                         "detail": "replica unreachable; failover pending"}
        # Terminal and remembered: serve the replica's full manifest when
        # it is KNOWN reachable, the cached summary otherwise — a dead
        # replica (it may stay dead for days) must not cost every read a
        # connection timeout and a pinned handler thread.
        if rep is not None and rep.alive:
            try:
                manifest = self.client.job(p.base_url, p.replica_job_id)
                return 200, {**manifest, "id": p.job_id,
                             "replica_id": p.replica_id, "tenant": p.tenant}
            except ReplicaRefused:
                pass
            except ReplicaUnreachable:
                self.registry.note_unreachable(p.base_url)
        return 200, {"id": p.job_id, "state": p.state,
                     "error": p.error or None,
                     "replica_id": p.replica_id, "tenant": p.tenant,
                     "trace_id": p.trace_id, "attempts": p.attempts}

    def _observe_manifest(self, p: Placement, manifest: dict) -> None:
        state = str(manifest.get("state", ""))
        if state in ("done", "error"):
            self._mark_terminal(p, state,
                                error=str(manifest.get("error") or ""))

    def _mark_terminal(self, p: Placement, state: str,
                       error: str = "") -> None:
        """Idempotent terminal transition: the quota and in-flight slot
        are released exactly once however many readers observe it."""
        with self._lock:
            if p.state != "open":
                return
            p.state = state
            p.error = error
            self._inflight -= 1
            self._grant_free_slots()
        self.admission.release(p.tenant)
        self.metrics.count("fleet_jobs_completed_total", {"state": state})

    def health(self) -> dict:
        snap = self.registry.snapshot()
        with self._lock:
            open_n = sum(1 for p in self._placements.values()
                         if p.state == "open")
            queued = len(self._wfq)
            inflight = self._inflight
        return {
            "status": "ok",
            "router_id": self.router_id,
            "uptime_s": round(time.time() - self.started_s, 3),
            "replicas": snap,
            "replicas_alive": sum(1 for r in snap
                                  if r["alive"] and not r["draining"]),
            "open_placements": open_n,
            "queued_submissions": queued,
            "inflight": inflight,
            "max_inflight": self.cfg.max_inflight,
        }

    def drain_replica(self, replica_id: str, flag: bool) -> tuple[int, dict]:
        rep = self.registry.by_id(replica_id)
        if rep is None:
            return 404, {"error": f"no replica {replica_id!r} in the fleet"}
        try:
            body = self.client.drain(rep.base_url, flag)
        except ReplicaRefused as exc:
            return exc.status, exc.body
        except ReplicaUnreachable as exc:
            return 503, {"error": f"replica unreachable: {exc}"}
        # Reflect the drain in the registry immediately — waiting for the
        # next poll would leave a placement window on a draining replica.
        self.registry.poll_once(self.client)
        return 200, body


class _RouterHandler(BaseHTTPRequestHandler):
    # Bound every socket read (the replica-API rule): a client that
    # under-sends its declared body must time out, not pin this handler
    # thread and its FD forever.
    timeout = 30.0

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        if not self.server.router.cfg.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: dict | None = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if isinstance(payload, dict) and payload.get("trace_id"):
            self.send_header("X-ICT-Trace", str(payload["trace_id"]))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            n = 0
        return self.rfile.read(max(0, min(n, 1 << 20)))

    def do_GET(self) -> None:  # noqa: N802 — stdlib signature
        router = self.server.router
        if self.path == "/healthz":
            self._reply(200, router.health())
        elif self.path == "/metrics":
            body = router.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/replicas":
            self._reply(200, {"replicas": router.registry.snapshot()})
        elif self.path.startswith("/jobs/"):
            jid = self.path[len("/jobs/"):]
            code, payload = router.job_manifest(jid)
            self._reply(code, payload)
        else:
            self._reply(404, {"error": f"no such route {self.path!r}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib signature
        router = self.server.router
        if self.path == "/jobs":
            self._post_job()
            return
        if (self.path.startswith("/replicas/")
                and self.path.endswith("/drain")):
            rid = self.path[len("/replicas/"): -len("/drain")]
            try:
                body = json.loads(self._read_body() or b"{}")
                flag = bool(body.get("drain", True)) \
                    if isinstance(body, dict) else True
            except ValueError:
                flag = True
            code, payload = router.drain_replica(rid, flag)
            self._reply(code, payload)
            return
        self._reply(404, {"error": f"no such route {self.path!r}"})

    def _post_job(self) -> None:
        router = self.server.router
        try:
            body = json.loads(self._read_body() or b"{}")
            path = body["path"]
            payload = {
                "path": str(path),
                "profile": bool(body.get("profile", False)),
                "audit": bool(body.get("audit", False)),
                # The client may pin its own idempotency key (its retry
                # across routers then dedupes too); otherwise the router
                # mints one — it is what makes failover re-routes safe.
                "idempotency_key": str(body.get("idempotency_key", "")
                                       or f"fleet-{uuid.uuid4().hex[:16]}"),
            }
            shape = body.get("shape")
            if shape is not None:
                payload["shape"] = [int(v) for v in shape]
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"error": f"bad request body: {exc!r}; expected "
                                       '{"path": "/abs/archive"}'})
            return
        tenant = str(self.headers.get("X-ICT-Tenant", "")
                     or DEFAULT_TENANT)
        trace_id = str(self.headers.get("X-ICT-Trace", "")
                       or events.new_trace_id())
        try:
            reply = router.place_job(payload, tenant, trace_id)
        except QuotaExceeded as exc:
            self._reply(429, {"error": str(exc)},
                        headers={"Retry-After": "5"})
            return
        except FleetBusy as exc:
            self._reply(503, {"error": str(exc)},
                        headers={"Retry-After": "5"})
            return
        except ReplicaRefused as exc:
            self._reply(exc.status, exc.body)
            return
        except Exception as exc:  # noqa: BLE001 — the client deserves a 500
            self._reply(500, {"error": f"placement failed: {exc}"})
            return
        self._reply(202, reply)


# --- CLI ---

def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ict-serve-fleet",
        description="Fleet router: spreads jobs across N ict-serve "
                    "replicas with shape-bucket affinity, drain/death "
                    "failover, and multi-tenant admission "
                    '(docs/SERVING.md "Fleet")')
    p.add_argument("--replica", action="append", default=[], metavar="URL",
                   help="replica base URL, e.g. http://host:8750 "
                        "(repeatable; at least one unless --smoke)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8790,
                   help="router HTTP port (0 = ephemeral; default 8790)")
    p.add_argument("--router_id", default="", metavar="ID",
                   help="stable router identity on /healthz and event-log "
                        "lines (default: mint one per process life)")
    p.add_argument("--poll_interval_s", type=float, default=1.0, metavar="S",
                   help="health-poll / failover-sweep cadence (default 1.0)")
    p.add_argument("--dead_after", type=int, default=3, metavar="N",
                   help="consecutive unreachable health checks before a "
                        "replica is dead and its open placements re-route "
                        "(default 3)")
    p.add_argument("--max_inflight", type=int, default=0, metavar="N",
                   help="fleet-wide open-placement budget; submissions "
                        "beyond it wait in weighted-fair order "
                        "(0 = unbounded; default 0)")
    p.add_argument("--queue_timeout_s", type=float, default=30.0, metavar="S",
                   help="max wait for a placement slot before 503 "
                        "(default 30)")
    p.add_argument("--failover_retries", type=int, default=2, metavar="N",
                   help="extra full-jitter candidate sweeps per submission "
                        "(default 2)")
    p.add_argument("--retry_backoff_s", type=float, default=0.25, metavar="S",
                   help="full-jitter backoff base between sweeps "
                        "(default 0.25)")
    p.add_argument("--tenant", action="append", default=[],
                   metavar="NAME:QUOTA:WEIGHT",
                   help="per-tenant admission spec (repeatable): QUOTA open "
                        "placements (0 = unbounded) and WFQ WEIGHT, e.g. "
                        "--tenant survey:64:3 --tenant adhoc:8:1")
    p.add_argument("--default_quota", type=int, default=0, metavar="N",
                   help="open-placement quota for undeclared tenants "
                        "(0 = unbounded; default 0)")
    p.add_argument("--default_weight", type=float, default=1.0, metavar="W",
                   help="WFQ weight for undeclared tenants (default 1.0)")
    p.add_argument("--telemetry", default="", metavar="PATH",
                   help="append fleet_placement/fleet_failover events to "
                        "PATH as JSON lines (ICT_TELEMETRY equivalent)")
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="offline self-check: 2 in-process replicas behind "
                        "the router, jobs submitted through it, one replica "
                        "killed mid-queue, every job must complete exactly "
                        "once with oracle-identical masks; one JSON line")
    return p


def parse_tenant_specs(specs: list[str]) -> tuple[dict, dict]:
    quotas: dict[str, int] = {}
    weights: dict[str, float] = {}
    for spec in specs:
        try:
            name, quota, weight = spec.split(":")
            if not name:
                raise ValueError
            quotas[name] = int(quota)
            weights[name] = float(weight)
            if quotas[name] < 0 or weights[name] <= 0:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad --tenant spec {spec!r}; expected NAME:QUOTA:WEIGHT "
                "like survey:64:3 (quota >= 0, weight > 0)") from None
    return quotas, weights


def fleet_config_from_args(args: argparse.Namespace) -> FleetConfig:
    if not args.replica and not args.smoke:
        raise ValueError("at least one --replica URL is required "
                         "(or --smoke for the self-check)")
    if args.dead_after < 1:
        raise ValueError(f"--dead_after must be >= 1, got {args.dead_after}")
    if args.max_inflight < 0:
        raise ValueError(f"--max_inflight must be >= 0 (0 = unbounded), "
                         f"got {args.max_inflight}")
    quotas, weights = parse_tenant_specs(args.tenant)
    return FleetConfig(
        replicas=tuple(args.replica),
        host=args.host,
        port=args.port,
        router_id=args.router_id,
        poll_interval_s=args.poll_interval_s,
        dead_after=args.dead_after,
        max_inflight=args.max_inflight,
        queue_timeout_s=args.queue_timeout_s,
        failover_retries=args.failover_retries,
        retry_backoff_s=args.retry_backoff_s,
        tenant_quotas=quotas,
        tenant_weights=weights,
        default_quota=args.default_quota,
        default_weight=args.default_weight,
        telemetry=args.telemetry,
        quiet=args.quiet,
    )


def run_fleet_smoke(cfg: FleetConfig) -> int:
    """Offline fleet self-check: 2 in-process replicas behind one router;
    several jobs submitted THROUGH the router; the replica holding a
    parked (undispatched) job is killed; every job must complete exactly
    once with masks bit-identical to the numpy oracle and the shadow
    audit clean; at least one failover must be recorded.  One JSON line,
    rc 0/1 — the CI lane next to ``serve --smoke``."""
    import os
    import tempfile
    import urllib.request

    import numpy as np

    from iterative_cleaner_tpu.config import CleanConfig
    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.io.npz import NpzIO
    from iterative_cleaner_tpu.io.synthetic import make_archive
    from iterative_cleaner_tpu.obs import tracing
    from iterative_cleaner_tpu.ops.preprocess import preprocess
    from iterative_cleaner_tpu.parallel.batch import finalize_weights
    from iterative_cleaner_tpu.service.daemon import CleaningService
    from iterative_cleaner_tpu.service.daemon import ServeConfig
    from iterative_cleaner_tpu.service.jobs import TERMINAL

    def serve_cfg(tag: str, tmp: str, deadline_s: float,
                  bucket_cap: int = 0) -> ServeConfig:
        return ServeConfig(
            spool_dir=os.path.join(tmp, f"spool_{tag}"), port=0,
            replica_id=f"smoke-{tag}", deadline_s=deadline_s,
            bucket_cap=bucket_cap,
            quiet=True, clean=CleanConfig(backend="jax", quiet=True))

    result = {"smoke": "FAIL"}
    with tempfile.TemporaryDirectory(prefix="ict_fleet_smoke_") as tmp:
        paths = []
        for i in range(3):
            p = os.path.join(tmp, f"smoke{i}.npz")
            NpzIO().save(make_archive(nsub=4, nchan=16, nbin=64,
                                      seed=200 + i), p)
            paths.append(p)
        # Replica a parks decoded cubes (huge deadline + a wide explicit
        # bucket that never fills): the job placed on it is accepted-but-
        # undispatched when it dies — exactly the failover case the
        # router must cover.  Replica b drains fast.
        svc_a = CleaningService(serve_cfg("a", tmp, deadline_s=3600.0,
                                          bucket_cap=8))
        svc_b = CleaningService(serve_cfg("b", tmp, deadline_s=0.2))
        svc_a.start()
        svc_b.start()
        # Hermetic overrides only (the run_smoke idiom): replicas and the
        # port are the smoke's own; every other operator flag
        # (--dead_after, --poll_interval_s, tenant specs, --telemetry, -q)
        # is honored so the smoke exercises the configured behavior —
        # with a faster-than-default poll/death cadence when the operator
        # left them at the defaults, to keep the CI lane snappy.
        poll_s = (0.2 if cfg.poll_interval_s == FleetConfig.poll_interval_s
                  else cfg.poll_interval_s)
        dead_after = (2 if cfg.dead_after == FleetConfig.dead_after
                      else cfg.dead_after)
        router = FleetRouter(FleetConfig(**{
            **cfg.__dict__,
            "replicas": (f"http://127.0.0.1:{svc_a.port}",
                         f"http://127.0.0.1:{svc_b.port}"),
            "port": 0,
            "poll_interval_s": poll_s,
            "dead_after": dead_after,
        }))
        router.start()
        jobs = {}
        try:
            base = f"http://{router.cfg.host}:{router.port}"
            before_done = tracing.counters_snapshot().get(
                "service_jobs_done", 0)
            for p in paths:
                req = urllib.request.Request(
                    f"{base}/jobs",
                    data=json.dumps({"path": p, "audit": True,
                                     "shape": [4, 16, 64]}).encode(),
                    headers={"Content-Type": "application/json"})
                jobs[p] = json.load(urllib.request.urlopen(req, timeout=30))
            placed_on_a = [j for j in jobs.values()
                           if j.get("replica_id") == "smoke-a"]
            # Wait until replica a has actually decoded and PARKED its
            # job(s) (bucketed, not yet dispatched), then kill it.
            deadline = time.time() + 120
            while time.time() < deadline:
                health = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{svc_a.port}/healthz", timeout=10))
                if health.get("bucketed_cubes", 0) >= len(placed_on_a) > 0:
                    break
                time.sleep(0.05)
            svc_a.stop()    # the "crash": parked jobs stay in its spool
            # Router polls mark a dead and re-route; wait for every job
            # (under its fleet id) to turn terminal through the router.
            deadline = time.time() + 300
            states = {}
            while time.time() < deadline:
                states = {p: json.load(urllib.request.urlopen(
                    f"{base}/jobs/{j['id']}", timeout=10))
                    for p, j in jobs.items()}
                if all(s.get("state") in TERMINAL for s in states.values()):
                    break
                time.sleep(0.1)
            all_done = all(s.get("state") == "done"
                           for s in states.values())
            # Exactly once: the fleet-wide completion count (both
            # replicas share this process's tracing registry) moved by
            # exactly len(paths).
            done_delta = tracing.counters_snapshot().get(
                "service_jobs_done", 0) - before_done
            svc_b.auditor.drain(60)
            health_b = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{svc_b.port}/healthz", timeout=10))
            masks_ok = all_done
            if all_done:
                cfg_np = CleanConfig(backend="numpy")
                for p in paths:
                    want, _rfi = finalize_weights(
                        clean_cube(*preprocess(NpzIO().load(p)),
                                   cfg_np).weights, cfg_np)
                    got = NpzIO().load(states[p]["out_path"])
                    if not np.array_equal(got.weights, want):
                        masks_ok = False
            failovers = router.metrics.counter_total("fleet_failovers_total")
            ok = (all_done and masks_ok and failovers >= 1
                  and done_delta == len(paths)
                  and health_b.get("audits_run", 0) >= 1
                  and health_b.get("audit_divergences", 0) == 0)
            result = {
                "smoke": "ok" if ok else "FAIL",
                "jobs": len(paths),
                "jobs_done": sum(1 for s in states.values()
                                 if s.get("state") == "done"),
                "completions": int(done_delta),
                "failovers": int(failovers),
                "mask_identical_to_oracle": bool(masks_ok),
                "audits_run": health_b.get("audits_run", 0),
                "audit_divergences": health_b.get("audit_divergences", 0),
                "placements": {
                    rid: int(router.metrics.counter_value(
                        "fleet_placements_total", {"replica": rid}))
                    for rid in ("smoke-a", "smoke-b")},
            }
            return 0 if ok else 1
        finally:
            print(json.dumps(result))
            router.stop()
            svc_b.stop()


def fleet_main(argv: list[str] | None = None) -> int:
    args = build_fleet_parser().parse_args(argv)
    try:
        cfg = fleet_config_from_args(args)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.smoke:
        return run_fleet_smoke(cfg)
    try:
        router = FleetRouter(cfg)
        router.start()
    except (RuntimeError, OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    import signal

    def _on_stop_signal(signum, frame):
        name = signal.Signals(signum).name
        print(f"ict-fleet: {name} — shutting down (replicas keep their "
              "accepted work; placements resume on restart via replica "
              "spools)", file=sys.stderr)
        raise SystemExit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_stop_signal)
        except (ValueError, OSError):  # noqa: PERF203 — non-main-thread embed
            pass
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


def console_main() -> int:
    return fleet_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(fleet_main())
