"""Capacity observability: the fleet's demand / utilization / backlog model.

PR 10 made the fleet *visible* (metrics federation, stitched traces,
incidents, SLO/straggler detection); this module makes it *quantified*.
Every poll tick the router hands :class:`CapacityModel` the registry
snapshot and the scrape cache, and the model folds three already-exported
signal families into actionable figures — extending the
Pipeline-Collector aggregation pattern from measuring a distributed
pipeline to steering it (arXiv:1807.05733):

- **queue state** — each replica's ``/healthz`` aggregate and
  per-shape-bucket queue depths (the backlog's *where*);
- **latency / throughput** — the federated ``/metrics`` scrapes already
  in the router's :class:`~.obs.ScrapeCache`: the
  ``ict_service_dispatch_s`` busy-seconds counter (the dispatch thread is
  one thread, so its windowed busy fraction IS the replica's
  utilization), the ``ict_service_jobs_done`` completion counter (the
  service rate), and the ``ict_phase_duration_seconds`` histogram (the
  p50 the straggler detector also watches);
- **cost** — the memoized ``exec_analysis`` figures obs/memory.py
  exports as ``ict_executable_bytes_accessed{shape_bucket=...}`` gauges
  (XLA's static accounting, persisted on job manifests): a queued cube of
  an expensive bucket weighs more than one of a cheap bucket, so the
  backlog-drain ETA is cost-weighted whenever the figures are known.

The model's outputs are rendered (by the router, through the ONE shared
registry renderer) as strict-grammar ``ict_fleet_capacity_*`` /
``ict_fleet_backlog_eta_seconds`` gauges and served as JSON at
``GET /fleet/capacity`` — and they are the ONLY inputs the autoscaler
(fleet/autoscale.py) reads, so every scale decision is reconstructible
from the exported gauges alone (the explainability contract in
docs/OBSERVABILITY.md "Capacity & autoscaling").

Derivations (all rates are windowed over the last ``window`` poll ticks):

- ``utilization(replica)``   = Δ``ict_service_dispatch_s`` / Δwall,
  clamped to [0, 1]; fleet utilization is the mean over live replicas.
- ``service_rate(replica)``  = Δ``ict_service_jobs_done`` / Δwall
  (jobs/s); the fleet rate is the sum.
- ``demand_rate(bucket)``    = placements the router routed for the
  bucket / Δwall (``note_placement`` feeds this; failover re-routes and
  idempotent dedupes are not new demand).
- ``backlog(bucket)``        = Σ over replicas of the bucket's queued
  cubes right now; the fleet backlog adds the un-bucketed load/dispatch
  queue depths on top.
- ``backlog_eta_s``          = cost-weighted backlog / fleet service
  rate: each bucket's depth is scaled by ``bytes_accessed(bucket) /
  mean(bytes_accessed)`` when the exec-analysis gauge is known (1.0
  otherwise).  Zero backlog → 0; backlog with a zero observed rate →
  ``+Inf`` (the renderer emits the grammar-legal ``+Inf``).
"""

from __future__ import annotations

import collections
import threading
import time

from iterative_cleaner_tpu.fleet import obs as fleet_obs
from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.obs.metrics import MetricFamily

#: Poll ticks per sliding window.  Rates over one tick are noisy (a
#: bucket flush completes several jobs at once); eight ticks at the
#: default 1 s cadence is the same horizon the straggler detector uses.
DEFAULT_WINDOW = 8

#: Label value for demand/backlog that arrived without a shape hint —
#: the submission carried no ``"shape"``, so the router cannot attribute
#: it to a bucket (it still counts toward fleet totals).
UNBUCKETED = "unbucketed"


def counter_value(families: list[MetricFamily], name: str) -> float:
    """One flat (unlabeled) counter's value out of a parsed scrape;
    0.0 when the replica has not registered the family yet."""
    for fam in families:
        for sname, labels, raw in fam.samples:
            if sname == name and not labels:
                try:
                    return obs_metrics.sample_value(raw)
                except ValueError:
                    return 0.0
    return 0.0


def labeled_gauge_values(families: list[MetricFamily], family: str,
                         label_key: str) -> dict[str, float]:
    """``{label value -> sample value}`` for one labeled gauge family out
    of a parsed scrape (e.g. ``ict_executable_bytes_accessed`` by
    ``shape_bucket``)."""
    out: dict[str, float] = {}
    for fam in families:
        if fam.name != family:
            continue
        for _sname, labels, raw in fam.samples:
            d = dict(labels)
            if label_key not in d:
                continue
            try:
                out[d[label_key]] = obs_metrics.sample_value(raw)
            except ValueError:
                continue
    return out


class CapacityModel:
    """Windowed capacity/demand accounting, written by the router's poll
    thread (:meth:`update`, once per tick) and its HTTP handler threads
    (:meth:`note_placement` on every fresh placement); read by both
    (:meth:`snapshot`, :meth:`gauge_families`).  Own lock, acquired
    strictly AFTER the router's (the PR 10 lock-order discipline) and
    never while calling out."""

    def __init__(self, window: int = DEFAULT_WINDOW,
                 dispatch_phase: str = "service_dispatch") -> None:
        self.window = max(int(window), 1)
        self.dispatch_phase = dispatch_phase
        self._lock = threading.Lock()
        # Fresh placements since the last update(), keyed by bucket label.
        self._arrivals: dict[str, int] = {}  # ict: guarded-by(self._lock)
        # Sliding windows: wall-seconds per tick, arrivals per tick, and
        # per-replica (busy-seconds delta, jobs-done delta) per tick.
        self._wall_win: collections.deque = collections.deque(maxlen=self.window)  # ict: guarded-by(self._lock)
        self._arrival_win: collections.deque = collections.deque(maxlen=self.window)  # ict: guarded-by(self._lock)
        self._busy_win: dict[str, collections.deque] = {}  # ict: guarded-by(self._lock)
        self._done_win: dict[str, collections.deque] = {}  # ict: guarded-by(self._lock)
        # Previous absolute counter readings per replica (deltas only
        # count NEW work; a replica restart resets its counters, and the
        # max(…, 0) clamp absorbs the negative delta).
        self._busy_prev: dict[str, float] = {}  # ict: guarded-by(self._lock)
        self._done_prev: dict[str, float] = {}  # ict: guarded-by(self._lock)
        self._last_mono: float | None = None  # ict: guarded-by(self._lock)
        self._snapshot: dict = {}  # ict: guarded-by(self._lock)
        # Lifetime demand counter (never windowed away): the synthetic-
        # exclusion proof the canary lane leans on — a probe round with
        # zero movement here provably never entered the demand plane.
        self._noted_total = 0  # ict: guarded-by(self._lock)

    # --- inputs ---

    def note_placement(self, bucket: str) -> None:
        """One fresh placement routed (demand).  Failover re-routes,
        idempotency dedupes, and synthetic canary probes must NOT call
        this — the first two are the same demand arriving twice, the
        probes are not demand at all (fleet/canary.py)."""
        key = bucket or UNBUCKETED
        with self._lock:
            self._arrivals[key] = self._arrivals.get(key, 0) + 1
            self._noted_total += 1

    def demand_total(self) -> int:
        """Cumulative count of placements ever noted (not windowed)."""
        with self._lock:
            return self._noted_total

    # --- the per-tick fold ---

    def update(self, replicas: list[dict],
               scrapes: dict[str, dict]) -> dict:
        """One poll tick: fold the registry snapshot (``replicas``, the
        rows ``ReplicaRegistry.snapshot`` serves) and the scrape cache
        snapshot (``scrapes``, ``ScrapeCache.snapshot``) into the
        capacity figures; returns (and stores) the snapshot dict."""
        now = time.monotonic()
        live = [r for r in replicas if r["alive"]]
        with self._lock:
            dt = (now - self._last_mono) if self._last_mono is not None \
                else 0.0
            self._last_mono = now
            self._wall_win.append(max(dt, 0.0))
            self._arrival_win.append(dict(self._arrivals))
            self._arrivals = {}
            wall = sum(self._wall_win)

            per_replica: dict[str, dict] = {}
            for row in live:
                rid = row["replica_id"] or row["base_url"]
                rec = scrapes.get(rid)
                families = (rec or {}).get("families") or []
                busy = counter_value(
                    families, f"ict_{self.dispatch_phase}_s")
                done = counter_value(families, "ict_service_jobs_done")
                d_busy = max(busy - self._busy_prev.get(rid, busy), 0.0)
                d_done = max(done - self._done_prev.get(rid, done), 0.0)
                self._busy_prev[rid] = busy
                self._done_prev[rid] = done
                bwin = self._busy_win.setdefault(
                    rid, collections.deque(maxlen=self.window))
                dwin = self._done_win.setdefault(
                    rid, collections.deque(maxlen=self.window))
                bwin.append(d_busy)
                dwin.append(d_done)
                util = min(sum(bwin) / wall, 1.0) if wall > 0 else 0.0
                rate = sum(dwin) / wall if wall > 0 else 0.0
                cum = fleet_obs.phase_hist_cum(families,
                                               self.dispatch_phase)
                p50 = obs_metrics.quantile_from_cum(cum, 0.5)
                queued = (float(row.get("bucketed_cubes", 0) or 0)
                          + float(row.get("load_queue_depth", 0) or 0)
                          + float(row.get("dispatch_queue_depth", 0) or 0))
                per_replica[rid] = {
                    "utilization": round(util, 6),
                    "service_rate": round(rate, 6),
                    "p50_s": p50,
                    "queued": queued,
                    "draining": bool(row.get("draining", False)),
                    "bucket_queue_depths": dict(
                        row.get("bucket_queue_depths", {})),
                }
                # Sweep replicas that left the fleet (scale-down, death
                # eviction of a renamed replica) out of the windows.
            gone = ({*self._busy_win} - {rid for rid in per_replica}
                    - {r["replica_id"] or r["base_url"] for r in replicas})
            for rid in gone:
                for table in (self._busy_win, self._done_win,
                              self._busy_prev, self._done_prev):
                    table.pop(rid, None)

            # Per-bucket backlog (fleet-wide) + the exec-analysis cost
            # figures off the same scrapes that fed the rates.
            backlog: dict[str, float] = {}
            for rep in per_replica.values():
                for bucket, n in rep["bucket_queue_depths"].items():
                    backlog[str(bucket)] = (backlog.get(str(bucket), 0.0)
                                            + float(n))
            cost: dict[str, float] = {}
            for rid in per_replica:
                rec = scrapes.get(rid)
                families = (rec or {}).get("families") or []
                for bucket, v in labeled_gauge_values(
                        families, "ict_executable_bytes_accessed",
                        "shape_bucket").items():
                    cost[bucket] = max(cost.get(bucket, 0.0), v)

            # Demand rates over the arrival window.
            demand: dict[str, float] = {}
            for tick in self._arrival_win:
                for bucket, n in tick.items():
                    demand[bucket] = demand.get(bucket, 0.0) + n
            demand = {b: (n / wall if wall > 0 else 0.0)
                      for b, n in demand.items()}

            fleet_rate = sum(r["service_rate"]
                             for r in per_replica.values())
            fleet_util = (sum(r["utilization"]
                              for r in per_replica.values())
                          / len(per_replica)) if per_replica else 0.0
            total_backlog = sum(r["queued"] for r in per_replica.values())
            bucket_backlog_sum = sum(backlog.values())

            # Cost-weighted drain ETA: scale each bucket's depth by its
            # relative bytes-accessed when known; cubes of unknown cost
            # (and the un-bucketed queue residue) weigh 1.0.
            known = [cost[b] for b in backlog if b in cost and cost[b] > 0]
            mean_cost = (sum(known) / len(known)) if known else 0.0
            def weight(bucket: str) -> float:
                if mean_cost > 0 and cost.get(bucket, 0.0) > 0:
                    return cost[bucket] / mean_cost
                return 1.0
            weighted = sum(n * weight(b) for b, n in backlog.items())
            weighted += max(total_backlog - bucket_backlog_sum, 0.0)

            def eta(load: float) -> float:
                if load <= 0:
                    return 0.0
                if fleet_rate <= 0:
                    return float("inf")
                return load / fleet_rate

            buckets = {
                b: {
                    "backlog": backlog.get(b, 0.0),
                    "demand_rate": round(demand.get(b, 0.0), 6),
                    "cost_bytes": cost.get(b),
                    "eta_s": eta(backlog.get(b, 0.0) * weight(b)),
                }
                for b in sorted({*backlog, *demand, *cost})
            }
            snap = {
                "ts": round(time.time(), 3),
                "window_s": round(wall, 3),
                "replicas": per_replica,
                "buckets": buckets,
                "fleet": {
                    "replicas_live": len(per_replica),
                    "utilization": round(fleet_util, 6),
                    "service_rate": round(fleet_rate, 6),
                    "demand_rate": round(sum(demand.values()), 6),
                    "backlog": total_backlog,
                    "backlog_weighted": round(weighted, 6),
                    "backlog_eta_s": eta(weighted),
                },
            }
            self._snapshot = snap
            return snap

    # --- outputs ---

    def snapshot(self) -> dict:
        """The last computed figures (empty before the first update)."""
        with self._lock:
            return dict(self._snapshot)

    def gauge_families(self) -> dict[str, dict[tuple, float]]:
        """The last snapshot rendered as ``{family -> {label pairs ->
        value}}`` for ``RouterMetrics.replace_gauge_family`` — the
        strict-grammar ``ict_fleet_capacity_*`` /
        ``ict_fleet_backlog_eta_seconds`` exposition the explainability
        contract promises.  Families are replaced whole each tick, so a
        drained bucket (or a scaled-down replica) drops off instead of
        freezing at its last value."""
        snap = self.snapshot()
        if not snap:
            return {}
        fleet = snap["fleet"]
        out: dict[str, dict[tuple, float]] = {
            "fleet_capacity_utilization": {(): fleet["utilization"]},
            "fleet_capacity_service_rate": {(): fleet["service_rate"]},
            "fleet_capacity_demand_rate": {(): fleet["demand_rate"]},
            "fleet_capacity_backlog": {(): fleet["backlog"]},
            "fleet_capacity_backlog_weighted": {
                (): fleet["backlog_weighted"]},
            "fleet_backlog_eta_seconds": {(): fleet["backlog_eta_s"]},
            "fleet_capacity_replica_utilization": {
                (("replica", rid),): rep["utilization"]
                for rid, rep in snap["replicas"].items()},
            "fleet_capacity_replica_service_rate": {
                (("replica", rid),): rep["service_rate"]
                for rid, rep in snap["replicas"].items()},
            "fleet_capacity_bucket_backlog": {
                (("bucket", b),): rec["backlog"]
                for b, rec in snap["buckets"].items()},
            "fleet_capacity_bucket_demand_rate": {
                (("bucket", b),): rec["demand_rate"]
                for b, rec in snap["buckets"].items()},
            "fleet_bucket_backlog_eta_seconds": {
                (("bucket", b),): rec["eta_s"]
                for b, rec in snap["buckets"].items()},
            "fleet_capacity_bucket_cost_bytes": {
                (("bucket", b),): rec["cost_bytes"]
                for b, rec in snap["buckets"].items()
                if rec["cost_bytes"] is not None},
        }
        return out
