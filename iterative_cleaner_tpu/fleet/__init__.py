"""ict-fleet: the front-end router + replica-aware serving tier.

One :class:`~.daemon.CleaningService` replica serves one host; survey-scale
real-time RFI mitigation is a *fleet* problem (arXiv:1701.08197), so this
package puts a router process in front of N daemon replicas:

- :mod:`.client`   — stdlib-HTTP client for the replica API (/healthz,
                     /jobs, /drain) with transport-vs-HTTP error split
- :mod:`.registry` — replica records + health polling: liveness, drain
                     flags, per-shape-bucket queue depths, warm shapes
- :mod:`.tenants`  — multi-tenant admission: per-tenant quotas (429 +
                     Retry-After on breach) and weighted fair queueing
                     over placement order under contention
- :mod:`.router`   — the FleetRouter daemon: least-loaded-by-bucket
                     placement with a warm-cache affinity bonus, failover
                     re-routing with idempotency keys (a job never runs
                     twice on one replica), its own Prometheus /metrics,
                     and the ``serve-fleet`` CLI (+ ``--smoke``)
- :mod:`.obs`      — the fleet observability plane: /metrics federation
                     (per-replica re-labeling + exact merged families on
                     ``GET /fleet/metrics``), cross-hop trace assembly
                     (``GET /fleet/trace/<id>``), incident bundles under
                     ``<spool>/fleet-incidents/``, and SLO/straggler
                     detection feeding placement de-prioritization
- :mod:`.capacity` — the capacity model: per-bucket demand rates,
                     per-replica utilization/service rates, and the
                     cost-weighted backlog-drain ETA, rendered as
                     ``ict_fleet_capacity_*`` gauges and served at
                     ``GET /fleet/capacity``
- :mod:`.autoscale`— signal-driven elastic scaling: the
                     ReplicaSupervisor (spawn with full-jitter retries,
                     drain-then-stop scale-down) and the hysteresis +
                     cooldown Autoscaler behind ``--autoscale
                     advise|act``
- :mod:`.history`  — the bounded federated-metrics history ring: one
                     parsed exposition per poll tick (zero new scrape
                     traffic), lossless strict-JSON ticks at
                     ``GET /fleet/metrics/history``, the windowed
                     series the alert predicates evaluate over
- :mod:`.alerts`   — the declarative alerting plane: SLO rules
                     ``(name, severity, selector, predicate,
                     for_ticks)`` over the history, a firing->resolved
                     state machine with per-rule hysteresis, on-disk
                     firing bundles, webhook/command sinks, and the
                     default rule pack behind ``GET /fleet/alerts``

The router is routing, not math: every mask is produced by a replica,
and replicas stay bit-identical to the numpy oracle on every route
(docs/SERVING.md "Fleet").  Zero new dependencies — the router is the
same stdlib ``http.server`` + ``urllib`` stack the replicas use.
"""

from iterative_cleaner_tpu.fleet.client import ReplicaClient
from iterative_cleaner_tpu.fleet.registry import ReplicaRegistry
from iterative_cleaner_tpu.fleet.router import FleetConfig, FleetRouter
from iterative_cleaner_tpu.fleet.tenants import TenantAdmission

__all__ = ["ReplicaClient", "ReplicaRegistry", "FleetConfig", "FleetRouter",
           "TenantAdmission"]
