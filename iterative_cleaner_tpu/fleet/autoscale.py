"""Signal-driven elastic scaling: replica lifecycle + the control loop.

ROADMAP item 2 asked for "autoscaling hooks that use the PR 10
SLO/straggler signals to drive replica spawn/drain instead of only
placement penalties"; the capacity model (fleet/capacity.py) supplies
the missing demand/backlog half.  Two classes, both owned by the router
and driven from its poll tick:

- :class:`ReplicaSupervisor` owns the lifecycle of *managed* replicas —
  the ones the autoscaler created (statically configured ``--replica``
  URLs are never scaled away).  Spawning goes through a pluggable
  factory: :class:`InProcessReplicaFactory` runs
  ``service.daemon.CleaningService`` replicas inside the router process
  (tests and the ``--smoke`` lane), :class:`SubprocessReplicaFactory`
  execs real ``ict-serve`` daemons (deployments).  A failed spawn is
  retried on the utils/backoff.py full-jitter ladder and every failed
  attempt is surfaced to the router's
  ``fleet_scale_events_total{direction="up",reason="spawn_failed"}``
  counter.  Scale-down is **drain-then-stop**: the replica is put in
  drain mode (the existing ``/drain`` + drain-eviction machinery — the
  router stops placing on it, accepted work finishes), and only once its
  ``/healthz`` reports zero open work is the process stopped and the
  replica removed from the registry — zero jobs are ever lost.

- :class:`Autoscaler` turns capacity + SLO/straggler signals into scale
  decisions: scale **up** when the cost-weighted backlog-drain ETA stays
  above ``scale_up_eta_s`` for ``up_polls`` consecutive polls (reason
  ``backlog``), or when SLO burn moved / a straggler is flagged while
  backlog is nonzero (reasons ``slo_burn`` / ``straggler``); scale
  **down** when the fleet sits idle (zero backlog, utilization under
  ``idle_utilization``, zero demand) for ``down_polls`` consecutive
  polls (reason ``idle``).  Hysteresis is those consecutive-poll
  streaks; ``cooldown_s`` after any decision suppresses flapping.  The
  default mode is **advise** — decisions are emitted (events, counters,
  decision bundles) but not executed; ``--autoscale act`` executes them.

Every signal the loop reads is an exported gauge (the capacity families,
``ict_fleet_slo_burn_total``, ``ict_fleet_stragglers``), so each
decision's inputs are reconstructible from ``GET /fleet/metrics`` alone
(docs/OBSERVABILITY.md "Capacity & autoscaling").
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from iterative_cleaner_tpu.utils import backoff


class SpawnFailed(RuntimeError):
    """Every spawn attempt (initial + the full-jitter retries) failed;
    carries the attempt count for the scale-event record."""

    def __init__(self, message: str, attempts: int) -> None:
        super().__init__(message)
        self.attempts = int(attempts)


@dataclass
class ReplicaHandle:
    """One managed replica the supervisor can stop.  ``stop`` must be
    idempotent and never raise (the drain path may race a crash)."""

    replica_id: str
    base_url: str
    stop: callable


class InProcessReplicaFactory:
    """Spawn ``CleaningService`` replicas inside this process — the
    tests/smoke factory (the ReplicaContext refactor is what makes N
    replicas per process possible).  ``make_serve_cfg(replica_id)``
    returns the ``ServeConfig`` for one new replica (port 0, its own
    spool dir)."""

    def __init__(self, make_serve_cfg) -> None:
        self._make_serve_cfg = make_serve_cfg

    def spawn(self, replica_id: str) -> ReplicaHandle:
        from iterative_cleaner_tpu.obs import events
        from iterative_cleaner_tpu.service.daemon import CleaningService

        cfg = self._make_serve_cfg(replica_id)
        if not cfg.telemetry:
            # The daemon's start() (re)configures the process-global
            # event sink from its own ServeConfig; a replica spawned
            # MID-RUN inside the router's process must inherit the
            # router's sink, not silently reset it.
            sink = events.configured_sink()
            if sink:
                cfg = type(cfg)(**{**cfg.__dict__, "telemetry": sink})
        svc = CleaningService(cfg)
        svc.start()
        return ReplicaHandle(
            replica_id=replica_id,
            base_url=f"http://127.0.0.1:{svc.port}",
            stop=svc.stop)


class SubprocessReplicaFactory:
    """Spawn real ``ict-serve`` daemon processes — the deployment
    factory.  Each replica gets its own spool under ``spool_root`` and
    an OS-assigned free port; the spawn blocks until ``/healthz``
    answers (or ``startup_timeout_s`` expires, which kills the child and
    raises).  ``extra_args`` (e.g. ``--backend numpy``) are appended to
    every spawn — the ``--spawn_arg`` CLI knob."""

    def __init__(self, spool_root: str, host: str = "127.0.0.1",
                 extra_args: tuple = (),
                 startup_timeout_s: float = 60.0) -> None:
        self.spool_root = spool_root
        self.host = host
        self.extra_args = tuple(extra_args)
        self.startup_timeout_s = float(startup_timeout_s)

    @staticmethod
    def _free_port(host: str) -> int:
        import socket

        with socket.socket() as sock:
            sock.bind((host, 0))
            return sock.getsockname()[1]

    def spawn(self, replica_id: str) -> ReplicaHandle:
        import urllib.request

        port = self._free_port(self.host)
        spool = os.path.join(self.spool_root, replica_id)
        os.makedirs(spool, exist_ok=True)
        argv = [sys.executable, "-m", "iterative_cleaner_tpu", "serve",
                "--host", self.host, "--port", str(port),
                "--replica_id", replica_id, "--spool", spool, "-q",
                *self.extra_args]
        proc = subprocess.Popen(argv)

        def stop() -> None:
            try:
                proc.terminate()
                proc.wait(timeout=30)
            except Exception:  # noqa: BLE001 — stop never raises
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass

        base_url = f"http://{self.host}:{port}"
        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise SpawnFailed(
                    f"replica {replica_id} exited rc {proc.returncode} "
                    "before serving /healthz", attempts=1)
            try:
                with urllib.request.urlopen(f"{base_url}/healthz",
                                            timeout=2):
                    return ReplicaHandle(replica_id=replica_id,
                                         base_url=base_url, stop=stop)
            except OSError:
                time.sleep(0.2)
        stop()
        raise SpawnFailed(
            f"replica {replica_id} did not serve /healthz within "
            f"{self.startup_timeout_s:g}s", attempts=1)


class ReplicaSupervisor:
    """Lifecycle owner for autoscaler-managed replicas.  Runs entirely on
    the router's poll thread (spawn, drain checks, reaping) plus handler
    threads reading state — one lock, acquired strictly after the
    router's and NEVER held across an HTTP call or a spawn."""

    #: Managed-replica states: spawned and placeable -> draining (the
    #: scale-down decision) -> stopped (reaped once idle).
    UP, DRAINING, STOPPED = "up", "draining", "stopped"

    def __init__(self, factory, registry, client, *,
                 spawn_retries: int = 3, retry_backoff_s: float = 0.25,
                 note_spawn_failure=None, rng=None,
                 quiet: bool = True) -> None:
        self.factory = factory
        self.registry = registry  # ict: guarded-by(none: bound once here; add/remove go through ReplicaRegistry's own lock)
        self.client = client
        self.spawn_retries = max(int(spawn_retries), 0)
        self.retry_backoff_s = float(retry_backoff_s)
        self._note_spawn_failure = note_spawn_failure or (lambda: None)
        self.quiet = quiet
        self._rng_lock = threading.Lock()
        self._rng = rng or backoff.make_rng()  # ict: guarded-by(self._rng_lock)
        self._lock = threading.Lock()
        self._seq = 0  # ict: guarded-by(self._lock)
        # replica_id -> {"handle": ReplicaHandle, "state": str}
        self._managed: dict[str, dict] = {}  # ict: guarded-by(self._lock)

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"as-{self._seq}"

    # --- scale up ---

    def spawn_replica(self) -> ReplicaHandle:
        """Spawn one managed replica, full-jitter retrying failed
        attempts; registers the new base URL with the registry so the
        next poll picks it up.  Raises :class:`SpawnFailed` (with the
        attempt count) after the ladder is exhausted — every failed
        attempt, terminal or not, has already been surfaced through
        ``note_spawn_failure`` (the
        ``fleet_scale_events_total{direction="up",reason="spawn_failed"}``
        counter)."""
        replica_id = self._next_id()
        last: Exception | None = None
        attempts = 0
        for attempt in range(1 + self.spawn_retries):
            if attempt:
                with self._rng_lock:
                    delay = backoff.full_jitter(self.retry_backoff_s,
                                                attempt - 1, rng=self._rng)
                time.sleep(delay)
            attempts += 1
            try:
                handle = self.factory.spawn(replica_id)
            except Exception as exc:  # noqa: BLE001 — every factory
                # failure mode (bind race, exec error, startup timeout)
                # walks the same retry ladder
                last = exc
                self._note_spawn_failure()
                if not self.quiet:
                    print(f"ict-fleet: replica spawn attempt {attempts} "
                          f"failed ({exc}); retrying", file=sys.stderr)
                continue
            with self._lock:
                self._managed[handle.replica_id] = {
                    "handle": handle, "state": self.UP}
            self.registry.add(handle.base_url)
            return handle
        raise SpawnFailed(
            f"replica spawn failed after {attempts} attempts: {last}",
            attempts=attempts)

    # --- scale down: drain, then stop once idle ---

    def begin_drain(self, replica_id: str) -> bool:
        """Put one managed replica in drain mode (the existing ``/drain``
        machinery: the router stops placing, accepted work finishes).
        Returns False when the replica is not managed/up or the drain
        call failed (the decision then retries on a later tick)."""
        with self._lock:
            rec = self._managed.get(replica_id)
            if rec is None or rec["state"] != self.UP:
                return False
            base_url = rec["handle"].base_url
        try:
            self.client.drain(base_url, True)
        except Exception:  # noqa: BLE001 — unreachable or refused: the
            # replica is not cleanly drainable right now; retry later
            return False
        with self._lock:
            rec = self._managed.get(replica_id)
            if rec is not None and rec["state"] == self.UP:
                rec["state"] = self.DRAINING
        return True

    def reap_drained(self) -> list[dict]:
        """Stop every draining managed replica whose ``/healthz`` reports
        zero open work (jobs, queues, buckets, sessions) — the
        drain-then-stop completion.  Returns one record per replica
        stopped this tick: ``{"managed_id", "replica_id", "base_url"}``
        — ``replica_id`` is the id the replica ADVERTISED (the key the
        router's scrape/straggler caches use; it need not equal the
        supervisor's managed id)."""
        with self._lock:
            draining = [(rid, rec["handle"])
                        for rid, rec in self._managed.items()
                        if rec["state"] == self.DRAINING]
        stopped: list[dict] = []
        for rid, handle in draining:
            try:
                health = self.client.health(handle.base_url)
            except Exception:  # noqa: BLE001 — a draining replica that
                # stopped answering is dead; reap it (its accepted work,
                # if any, re-routes through the normal failover path)
                health = None
            if health is not None and (
                    health.get("open_jobs", 0)
                    or health.get("load_queue_depth", 0)
                    or health.get("dispatch_queue_depth", 0)
                    or health.get("bucketed_cubes", 0)
                    or health.get("open_sessions", 0)):
                continue   # still finishing accepted work
            # Resolve the ADVERTISED id before the registry record goes
            # away: the caller's post-mortem caches are keyed by it.
            rep = self.registry.get(handle.base_url)
            reported = ((rep.replica_id if rep is not None else "")
                        or (health or {}).get("replica_id", "")
                        or handle.base_url)
            handle.stop()
            self.registry.remove(handle.base_url)
            with self._lock:
                rec = self._managed.get(rid)
                if rec is not None:
                    rec["state"] = self.STOPPED
            stopped.append({"managed_id": rid, "replica_id": reported,
                            "base_url": handle.base_url})
        return stopped

    # --- reads / shutdown ---

    def managed(self) -> dict[str, str]:
        """``{managed id -> state}`` for every replica ever spawned."""
        with self._lock:
            return {rid: rec["state"] for rid, rec in self._managed.items()}

    def managed_info(self) -> dict[str, dict]:
        """``{managed id -> {"state", "base_url"}}`` — the base URL is
        the stable join key against the registry (a spawned daemon's
        advertised --replica_id is its own business)."""
        with self._lock:
            return {rid: {"state": rec["state"],
                          "base_url": rec["handle"].base_url}
                    for rid, rec in self._managed.items()}

    def up_ids(self) -> list[str]:
        with self._lock:
            return [rid for rid, rec in self._managed.items()
                    if rec["state"] == self.UP]

    def up_urls(self) -> dict[str, str]:
        """``{base_url -> managed id}`` for drainable replicas.  Victim
        selection matches on the URL, never the replica's self-reported
        id — a spawned daemon may advertise any ``--replica_id`` on its
        /healthz, and the supervisor's identity must not depend on it."""
        with self._lock:
            return {rec["handle"].base_url: rid
                    for rid, rec in self._managed.items()
                    if rec["state"] == self.UP}

    def stop_all(self) -> None:
        """Router shutdown: stop every managed replica (their spools keep
        any accepted-but-unfinished work for a restart)."""
        with self._lock:
            handles = [rec["handle"] for rec in self._managed.values()
                       if rec["state"] != self.STOPPED]
            for rec in self._managed.values():
                rec["state"] = self.STOPPED
        for handle in handles:
            handle.stop()


@dataclass
class AutoscaleConfig:
    mode: str = "advise"            # "advise" (default) | "act"
    min_replicas: int = 1           # alive floor (static + managed)
    max_replicas: int = 4           # alive ceiling
    scale_up_eta_s: float = 10.0    # backlog-drain ETA that means "behind"
    up_polls: int = 3               # hysteresis: consecutive slow polls
    down_polls: int = 6             # hysteresis: consecutive idle polls
    idle_utilization: float = 0.05  # fleet utilization under this = idle
    cooldown_s: float = 30.0        # quiet period after any decision


class Autoscaler:
    """The decision half: pure function of the capacity snapshot + the
    SLO/straggler signals, with streak hysteresis and a cooldown.  The
    router executes the decisions (spawn/drain); this class never
    touches lifecycle, so its verdicts are unit-testable from synthetic
    snapshots alone."""

    def __init__(self, cfg: AutoscaleConfig) -> None:
        if cfg.mode not in ("advise", "act"):
            raise ValueError(f"autoscale mode must be advise|act, "
                             f"got {cfg.mode!r}")
        if cfg.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {cfg.min_replicas}")
        if cfg.max_replicas < cfg.min_replicas:
            raise ValueError(f"max_replicas ({cfg.max_replicas}) must be "
                             f">= min_replicas ({cfg.min_replicas})")
        self.cfg = cfg
        self._lock = threading.Lock()
        self._up_streak = 0  # ict: guarded-by(self._lock)
        self._down_streak = 0  # ict: guarded-by(self._lock)
        self._last_decision_mono: float | None = None  # ict: guarded-by(self._lock)
        self._last_decision: dict | None = None  # ict: guarded-by(self._lock)
        self._slo_burn_prev = 0.0  # ict: guarded-by(self._lock)

    def tick(self, snapshot: dict, *, alive: int, managed_up: int,
             slo_burn_total: float, stragglers: int,
             slo_budget_remaining: float | None = None,
             now_mono: float | None = None) -> dict | None:
        """One poll's verdict: None, or a decision dict
        ``{"direction", "reason", "mode", "signals"}``.  ``alive`` is
        live non-draining replicas (the scale bounds); ``managed_up``
        is how many the supervisor could still drain (a fleet of only
        static replicas never scales down).  ``slo_budget_remaining``
        (the minimum error-budget percentage across declared SLO
        objectives, fleet/slo.py) rides the decision's signals so every
        bundle records the budget state it was taken under — the
        router's canary veto is the acting half of that signal."""
        fleet = (snapshot or {}).get("fleet")
        if not fleet:
            return None
        now = time.monotonic() if now_mono is None else now_mono
        with self._lock:
            burn_moved = slo_burn_total > self._slo_burn_prev
            self._slo_burn_prev = slo_burn_total
            backlog = float(fleet.get("backlog", 0.0))
            eta = float(fleet.get("backlog_eta_s", 0.0))
            util = float(fleet.get("utilization", 0.0))
            demand = float(fleet.get("demand_rate", 0.0))
            behind = backlog > 0 and eta > self.cfg.scale_up_eta_s
            pressure = backlog > 0 and (burn_moved or stragglers > 0)
            idle = (backlog <= 0 and demand <= 0
                    and util < self.cfg.idle_utilization)
            self._up_streak = self._up_streak + 1 \
                if (behind or pressure) else 0
            self._down_streak = self._down_streak + 1 if idle else 0
            in_cooldown = (
                self._last_decision_mono is not None
                and now - self._last_decision_mono < self.cfg.cooldown_s)
            decision: dict | None = None
            if (self._up_streak >= self.cfg.up_polls and not in_cooldown
                    and alive < self.cfg.max_replicas):
                reason = ("backlog" if behind
                          else "slo_burn" if burn_moved else "straggler")
                decision = {"direction": "up", "reason": reason}
            elif (self._down_streak >= self.cfg.down_polls
                    and not in_cooldown
                    and alive > self.cfg.min_replicas and managed_up > 0):
                decision = {"direction": "down", "reason": "idle"}
            if decision is not None:
                decision["mode"] = self.cfg.mode
                decision["signals"] = {
                    "backlog": backlog, "backlog_eta_s": eta,
                    "utilization": util, "demand_rate": demand,
                    "slo_burn_total": slo_burn_total,
                    "stragglers": stragglers, "alive": alive,
                    "up_streak": self._up_streak,
                    "down_streak": self._down_streak,
                }
                if slo_budget_remaining is not None:
                    decision["signals"]["slo_budget_remaining_pct"] = (
                        slo_budget_remaining)
                self._last_decision_mono = now
                self._last_decision = dict(decision)
                self._up_streak = 0
                self._down_streak = 0
            return decision

    def state(self, now_mono: float | None = None) -> dict:
        """The /healthz + /fleet/capacity view of the loop."""
        now = time.monotonic() if now_mono is None else now_mono
        with self._lock:
            cooldown_left = 0.0
            if self._last_decision_mono is not None:
                cooldown_left = max(
                    0.0, self.cfg.cooldown_s
                    - (now - self._last_decision_mono))
            return {
                "mode": self.cfg.mode,
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "scale_up_eta_s": self.cfg.scale_up_eta_s,
                "up_polls": self.cfg.up_polls,
                "down_polls": self.cfg.down_polls,
                "cooldown_s": self.cfg.cooldown_s,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "cooldown_remaining_s": round(cooldown_left, 3),
                "last_decision": (dict(self._last_decision)
                                  if self._last_decision else None),
            }
