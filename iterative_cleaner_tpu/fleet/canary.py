"""Black-box canary prober: deterministic synthetic user journeys driven
through the fleet router's PUBLIC HTTP surface (ISSUE 18's measurement
half; the accounting half is fleet/slo.py).

Four journeys, each a real user path end to end:

- ``fresh``    POST /jobs -> status polls -> result.  Every round
  re-stamps the canary archive's ``source`` header with a nonce, so the
  bytes (and the fleet cache digest) are new each round and the journey
  genuinely exercises placement + dispatch.
- ``cache``    byte-identical resubmit of the same round's file — must
  come back ``served_by == "fleet-cache"``, born terminal.
- ``session``  POST /sessions -> per-subint blocks -> finish, through
  the router's session proxy.
- ``campaign`` a 2-entry micro-manifest (one cache-warm path, one
  fresh) through POST /campaigns -> status polls.

Every verdict carries a **bit-identical mask check** against the stored
numpy-oracle answer (computed once per prepare from the same archive
bytes the replicas clean — the repo's parity invariant is what makes
"canary green" mean "users get correct masks"), plus per-hop latency
folded out of the existing trace assembly (fleet/obs.span_hops).

Synthetic traffic is stamped ``synthetic=true`` end-to-end and runs
under the reserved ``_canary`` tenant (fleet/tenants.SYNTHETIC_TENANT):
excluded from capacity demand, tenant quotas, cost showback, and scoped
out of the shared result-cache salt (fleet/router.py) — a probe that
moved the planes it measures would be measuring itself.

Threading: rounds run on a dedicated daemon thread kicked by the
router's poll tick (``maybe_start``); journeys are plain blocking HTTP
against the router, so the poll loop is never blocked and no router
lock is ever held across a probe.  ``run_round`` may also be called
synchronously (tests, the smoke lane).
"""

from __future__ import annotations

import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from dataclasses import replace

from iterative_cleaner_tpu.fleet import obs as fleet_obs
from iterative_cleaner_tpu.fleet.tenants import SYNTHETIC_TENANT

#: Canary archive dims — tiny (one subint block per POST, four blocks a
#: session) but inside the parity floor (nbin >= 3, CLAUDE.md).
CANARY_SHAPE = (4, 16, 64)

#: Deterministic seeds for the two probe archives.
_SEED_A = 1801
_SEED_B = 1802

#: Replica job states that end a status poll.
_TERMINAL = ("done", "error")


class CanaryError(RuntimeError):
    """One journey failed in transit (HTTP error, timeout, bad reply)."""


def _flip_one(weights):
    """The fault-injection seam's single-bit mask flip: toggle the zap
    state of exactly one (subint, channel) cell."""
    flipped = weights.copy()
    flipped.flat[0] = 0.0 if flipped.flat[0] != 0.0 else 1.0
    return flipped


class CanaryProber:
    """Owns the probe corpus (archives + precomputed oracle masks under
    ``<spool>/canary/``) and runs probe rounds against the router's
    public base URL.  One round = all four journeys, sequentially (the
    cache journey NEEDS the fresh journey's entry to be learned)."""

    def __init__(self, spool_dir: str, base_url_fn, clean_cfg=None,
                 timeout_s: float = 120.0, quiet: bool = True) -> None:
        self.dir = os.path.join(spool_dir, "canary")
        os.makedirs(self.dir, exist_ok=True)
        self.base_url_fn = base_url_fn
        self.clean_cfg = clean_cfg
        self.timeout_s = float(timeout_s)
        self.quiet = quiet
        #: The SLO plane verdicts feed (set by the router after both
        #: planes exist) and the mask-mismatch incident hook.
        self.slo = None
        self.on_mask_mismatch = None
        #: Test/drill seam: while True, one bit of every OBSERVED mask is
        #: flipped before the oracle compare — the injected-corruption
        #: path the e2e tests and chaos drills drive (ISSUE 18
        #: acceptance: canary -> correctness SLI -> burn alert ->
        #: incident bundle).
        self.corrupt_mask = False
        self._lock = threading.Lock()
        self._thread = None            # ict: guarded-by(self._lock)
        self._rounds = 0               # ict: guarded-by(self._lock)
        self._prepared = False         # ict: guarded-by(self._lock)
        # Probe corpus, written once by _ensure_prepared under _lock and
        # read-only afterwards.
        self._arch_a = None            # ict: guarded-by(self._lock)
        self._path_b = ""              # ict: guarded-by(self._lock)
        self._oracle_a = None          # ict: guarded-by(self._lock)
        self._oracle_b = None          # ict: guarded-by(self._lock)

    # --- corpus ---

    def _ensure_prepared(self) -> None:
        with self._lock:
            if self._prepared:
                return
            # Lazy heavy imports: the prober only pulls the cleaning
            # stack into the router process when probing is enabled.
            from iterative_cleaner_tpu.config import CleanConfig
            from iterative_cleaner_tpu.io.npz import NpzIO
            from iterative_cleaner_tpu.io.synthetic import make_archive

            if self.clean_cfg is None:
                # The oracle must be computed under the SAME cleaning
                # config the replicas serve (the cache-salt homogeneity
                # assumption); default-config fleets need no knob.
                self.clean_cfg = CleanConfig(backend="numpy", quiet=True,
                                             no_log=True)
            nsub, nchan, nbin = CANARY_SHAPE
            io = NpzIO()
            self._arch_a = make_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                        seed=_SEED_A)
            path_a = os.path.join(self.dir, "canary_a.npz")
            io.save(self._arch_a, path_a)
            self._path_b = os.path.join(self.dir, "canary_b.npz")
            io.save(make_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                 seed=_SEED_B), self._path_b)
            # Oracle masks from the round-tripped bytes (what replicas
            # will actually load), recomputed at every prepare so a
            # config change can never serve a stale stored answer.
            self._oracle_a = self._oracle(path_a)
            self._oracle_b = self._oracle(self._path_b)
            self._prepared = True

    def _oracle(self, path: str):
        """The numpy-oracle mask for one archive file (the test_fleet
        _oracle_weights idiom)."""
        from iterative_cleaner_tpu.core.cleaner import clean_cube
        from iterative_cleaner_tpu.io.npz import NpzIO
        from iterative_cleaner_tpu.ops.preprocess import preprocess
        from iterative_cleaner_tpu.parallel.batch import finalize_weights

        cfg = replace(self.clean_cfg, backend="numpy")
        w, _rfi = finalize_weights(
            clean_cube(*preprocess(NpzIO().load(path)), cfg).weights, cfg)
        return w

    def _fresh_file(self) -> str:
        """Re-stamp the canary archive's source header with a nonce and
        rewrite it: new bytes (new cache digest) every round, identical
        mask (metadata never feeds the cleaner) — the fresh journey
        stays fresh without recomputing the oracle."""
        from iterative_cleaner_tpu.io.npz import NpzIO

        path = os.path.join(self.dir, "canary_fresh.npz")
        stamped = replace(self._arch_a,
                          source=f"CANARY-{uuid.uuid4().hex[:12]}")
        NpzIO().save(stamped, path)
        return path

    # --- HTTP (the router's public surface; stdlib only) ---

    def _base(self) -> str:
        return str(self.base_url_fn()).rstrip("/")

    def _http(self, route: str, data: bytes | None = None,
              content_type: str = "application/json",
              timeout: float | None = None) -> dict:
        import json

        req = urllib.request.Request(
            self._base() + route, data=data,
            headers={"Content-Type": content_type} if data else {})
        try:
            with urllib.request.urlopen(
                    req, timeout=30.0 if timeout is None else timeout
                    ) as resp:
                reply = json.load(resp)
        except urllib.error.HTTPError as exc:
            try:
                detail = json.load(exc)
            except ValueError:
                detail = {"error": exc.reason}
            raise CanaryError(
                f"{route}: HTTP {exc.code}: {detail.get('error', '')!s}"
                ) from exc
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError, ValueError) as exc:
            raise CanaryError(f"{route}: {exc}") from exc
        if not isinstance(reply, dict):
            raise CanaryError(f"{route}: non-object JSON reply")
        return reply

    def _get(self, route: str) -> dict:
        return self._http(route)

    def _post(self, route: str, body: dict,
              timeout: float | None = None) -> dict:
        import json

        return self._http(route, data=json.dumps(body).encode(),
                          timeout=timeout)

    def _await(self, route: str, done, what: str) -> dict:
        deadline = time.monotonic() + self.timeout_s
        while True:
            view = self._get(route)
            if done(view):
                return view
            if time.monotonic() >= deadline:
                raise CanaryError(
                    f"{what} not terminal after {self.timeout_s:g}s "
                    f"(state {view.get('state')!r})")
            time.sleep(0.05)

    # --- verdict plumbing ---

    def _verify_against(self, out_path, oracle) -> bool | None:
        """Bit-identical mask check of one result file against the
        stored oracle answer; None when no result exists to check."""
        import numpy as np

        from iterative_cleaner_tpu.io.npz import NpzIO

        if not out_path or not os.path.exists(str(out_path)):
            return None
        try:
            observed = NpzIO().load(str(out_path)).weights
        except Exception:  # noqa: BLE001 — a torn result file is "wrong"
            return False
        if self.corrupt_mask:
            observed = _flip_one(observed)
        return bool(np.array_equal(observed, oracle))

    def _hops(self, trace_id: str) -> dict | None:
        """Per-hop latency off the assembled trace (best-effort: a probe
        must never fail on forensics)."""
        if not trace_id:
            return None
        try:
            trace = self._get(f"/fleet/trace/{trace_id}")
        except CanaryError:
            return None
        return fleet_obs.span_hops(trace.get("spans") or [])

    def _verdict(self, journey: str, ok: bool, correct, latency_s: float,
                 trace_id: str = "", error: str = "",
                 **extra) -> dict:
        v = {"journey": journey, "ok": bool(ok), "correct": correct,
             "latency_s": round(latency_s, 6), "trace_id": trace_id,
             "error": error, "ts": round(time.time(), 3),
             "hops": self._hops(trace_id)}
        v.update(extra)
        slo = self.slo
        if slo is not None:
            slo.note_verdict(v)
        if correct is False and self.on_mask_mismatch is not None:
            self.on_mask_mismatch(v)
        return v

    # --- the journeys ---

    def _submit_probe(self, path: str, label: str) -> dict:
        return self._post("/jobs", {
            "path": path,
            "shape": list(CANARY_SHAPE),
            "synthetic": True,
            "tenant": SYNTHETIC_TENANT,
            "idempotency_key": f"canary-{label}-{uuid.uuid4().hex[:12]}",
        })

    def _journey_fresh(self, path: str) -> dict:
        t0 = time.monotonic()
        reply = self._submit_probe(path, "fresh")
        man = self._await(f"/jobs/{reply.get('id')}",
                          lambda v: v.get("state") in _TERMINAL,
                          "fresh canary job")
        latency = time.monotonic() - t0
        correct = self._verify_against(man.get("out_path"), self._oracle_a)
        ok = man.get("state") == "done" and correct is True
        return self._verdict(
            "fresh", ok, correct, latency,
            trace_id=str(reply.get("trace_id", "") or ""),
            error=str(man.get("error") or ""),
            job_id=str(reply.get("id", "")))

    def _journey_cache(self, path: str) -> dict:
        t0 = time.monotonic()
        reply = self._submit_probe(path, "cache")
        born_terminal = reply.get("state") in _TERMINAL
        man = (reply if born_terminal else
               self._await(f"/jobs/{reply.get('id')}",
                           lambda v: v.get("state") in _TERMINAL,
                           "cache canary job"))
        latency = time.monotonic() - t0
        correct = self._verify_against(man.get("out_path"), self._oracle_a)
        # The journey's contract is the reuse tier itself: a resubmit
        # that quietly recleans is a broken cache plane even though the
        # mask would come back right.
        hit = (reply.get("served_by") == "fleet-cache") and born_terminal
        ok = hit and man.get("state") == "done" and correct is True
        return self._verdict(
            "cache", ok, correct, latency,
            trace_id=str(reply.get("trace_id", "") or ""),
            error="" if hit else "resubmit missed the fleet cache",
            job_id=str(reply.get("id", "")), cache_hit=hit)

    def _journey_session(self) -> dict:
        from iterative_cleaner_tpu.online.blocks import encode_block
        from iterative_cleaner_tpu.online.state import SessionMeta

        arch = self._arch_a
        t0 = time.monotonic()
        opened = self._post("/sessions",
                            SessionMeta.from_archive(arch).to_dict())
        sid = str(opened.get("id", ""))
        if not sid:
            raise CanaryError("session open returned no id")
        for i in range(arch.data.shape[0]):
            self._http(f"/sessions/{sid}/blocks",
                       data=encode_block(arch.data[i:i + 1],
                                         arch.weights[i:i + 1]),
                       content_type="application/octet-stream")
        # Finish runs the replica's finalize (which may compile under a
        # jax backend) — give it the full round budget, not the default
        # per-call timeout.
        fin = self._post(f"/sessions/{sid}/finish", {},
                         timeout=self.timeout_s)
        latency = time.monotonic() - t0
        correct = self._verify_against(fin.get("out_path"), self._oracle_a)
        ok = fin.get("state") == "done" and correct is True
        return self._verdict(
            "session", ok, correct, latency,
            trace_id=str(opened.get("trace_id", "") or ""),
            session_id=sid, blocks=int(arch.data.shape[0]))

    def _journey_campaign(self, fresh_path: str) -> dict:
        t0 = time.monotonic()
        created = self._post("/campaigns", {
            "name": f"canary-{uuid.uuid4().hex[:8]}",
            "tenant": SYNTHETIC_TENANT,
            "synthetic": True,
            "archives": [fresh_path, self._path_b],
            "max_inflight": 2,
        })
        cid = str(created.get("id", ""))
        if not cid:
            raise CanaryError("campaign create returned no id")
        view = self._await(f"/campaigns/{cid}",
                           lambda v: v.get("state") in
                           ("done", "failed", "cancelled"),
                           f"canary campaign {cid}")
        latency = time.monotonic() - t0
        oracles = {fresh_path: self._oracle_a, self._path_b: self._oracle_b}
        checks = [self._verify_against(
                      rec.get("out_path"), oracles.get(rec.get("path")))
                  for rec in view.get("archive_records") or []]
        correct = (None if not checks or any(c is None for c in checks)
                   else all(checks))
        ok = view.get("state") == "done" and correct is True
        return self._verdict("campaign", ok, correct, latency,
                             campaign_id=cid, archives=len(checks))

    # --- rounds ---

    def run_round(self) -> list[dict]:
        """One full probe round, synchronously: all four journeys in
        order (cache depends on fresh's entry being learned).  A journey
        that raises records a failed verdict and the round continues —
        one broken journey must not blind the other three."""
        self._ensure_prepared()
        with self._lock:
            self._rounds += 1
        fresh_path = self._fresh_file()
        verdicts = []
        for journey, fn in (("fresh",
                             lambda: self._journey_fresh(fresh_path)),
                            ("cache",
                             lambda: self._journey_cache(fresh_path)),
                            ("session", self._journey_session),
                            ("campaign",
                             lambda: self._journey_campaign(fresh_path))):
            t0 = time.monotonic()
            try:
                verdicts.append(fn())
            except Exception as exc:  # noqa: BLE001 — the verdict IS the
                # error report; the prober itself must survive anything
                # the fleet does to it.
                verdicts.append(self._verdict(
                    journey, False, None, time.monotonic() - t0,
                    error=f"{type(exc).__name__}: {exc}"))
        return verdicts

    def maybe_start(self) -> bool:
        """Kick one probe round on the dedicated canary thread unless a
        round is still in flight (a slow fleet gets measured as slow, it
        does not accumulate a thread pileup).  Returns whether a round
        was started.  Called from the router's poll tick — never blocks,
        never holds the router lock."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            t = threading.Thread(target=self._round_guarded,
                                 name="ict-fleet-canary", daemon=True)
            self._thread = t
        t.start()
        return True

    def _round_guarded(self) -> None:
        try:
            self.run_round()
        except Exception:  # noqa: BLE001 — run_round already folds
            # per-journey failures into verdicts; anything else here is
            # corpus preparation, and the next tick retries it.
            pass

    def rounds(self) -> int:
        with self._lock:
            return self._rounds

    def busy(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()
