"""Federated metrics history: the bounded time axis under the fleet view.

``GET /fleet/metrics`` is one instant — the ScrapeCache keeps only each
replica's last good scrape, so "is this figure *rising*" was an operator
holding two terminal scrollbacks.  :class:`MetricsHistory` closes that
gap with zero new scrape traffic: once per poll tick the router parses
the very exposition it already serves (router registry + per-replica
re-labeled series + merged ``ict_fleet_*`` families, all from the ONE
cache snapshot) and appends the parsed families to a bounded ring of
tick records.

Two consumers:

- ``GET /fleet/metrics/history`` serves the ring as strict JSON (sample
  values stay the exposition's raw strings — ``+Inf``/``NaN`` spellings
  included — so the reply is valid JSON *and* each tick re-renders
  byte-exact through ``obs.metrics.render_exposition``, the
  ``/fleet/capacity`` IEEE-specials discipline);
- the alert engine (fleet/alerts.py) evaluates its rule predicates over
  :meth:`series` / :meth:`cum_series` windows — threshold, delta/rate
  over N ticks, absence, histogram quantiles — all off this ring, never
  off a fresh scrape.

Memory is bounded by construction: ``keep`` ticks, each a parsed-family
list the size of one exposition.  Samples are indexed by name at append
time so per-tick rule evaluation is a dict lookup, not a re-scan of the
whole window's text.
"""

from __future__ import annotations

import collections
import threading
import time

from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.obs.metrics import MetricFamily

#: Poll ticks retained by default — at the default 1 s poll cadence,
#: about two minutes of history: enough for every default alert window
#: (<= 8 ticks) with headroom for an operator's `?ticks=` reads.
DEFAULT_KEEP = 128


def family_to_json(fam: MetricFamily) -> dict:
    """One parsed family as strict JSON: raw sample values stay strings
    (``+Inf``/``NaN`` keep their exposition spellings), label pairs stay
    ordered — :func:`family_from_json` inverts losslessly, so a stored
    tick re-renders byte-exact."""
    return {
        "name": fam.name,
        "kind": fam.kind,
        "help": fam.help,
        "samples": [[name, [[k, v] for k, v in labels], raw]
                    for name, labels, raw in fam.samples],
    }


def family_from_json(obj: dict) -> MetricFamily:
    """Inverse of :func:`family_to_json`."""
    fam = MetricFamily(name=str(obj["name"]), kind=obj.get("kind"),
                       help=obj.get("help"))
    fam.samples = [
        (name, tuple((str(k), str(v)) for k, v in labels), raw)
        for name, labels, raw in obj.get("samples", [])]
    return fam


def _index(families: list[MetricFamily]) -> dict[str, list]:
    """``sample name -> [(label_pairs, float value), ...]`` for one tick —
    built once at append time so predicate evaluation never re-walks the
    family lists.  Unparseable values cannot occur in samples that came
    through the strict parser; foreign input (family_from_json on
    operator JSON) is still skipped, not raised."""
    out: dict[str, list] = {}
    for fam in families:
        for name, labels, raw in fam.samples:
            try:
                value = obs_metrics.sample_value(raw)
            except ValueError:
                continue
            out.setdefault(name, []).append((labels, value))
    return out


def _matches(label_pairs: tuple, want: tuple) -> bool:
    """Whether a sample's label pairs contain every selector pair."""
    if not want:
        return True
    d = dict(label_pairs)
    return all(d.get(k) == v for k, v in want)


class MetricsHistory:
    """Bounded ring of per-poll-tick parsed expositions, written by the
    router's poll thread (:meth:`append`, once per tick) and read by its
    HTTP handler threads and the alert engine.  Own lock, acquired
    strictly AFTER the router's RLock (the PR 10 discipline) and never
    while calling out; tick records are immutable once appended, so
    snapshot reads hand out the record dicts themselves."""

    def __init__(self, keep: int = DEFAULT_KEEP) -> None:
        self.keep = max(int(keep), 1)
        self._lock = threading.Lock()
        self._ticks: collections.deque = collections.deque(maxlen=self.keep)  # ict: guarded-by(self._lock)
        self._seq = 0  # ict: guarded-by(self._lock)

    def append(self, families: list[MetricFamily]) -> dict:
        """Record one poll tick's parsed exposition; returns the record.
        The record (families included) is treated as immutable from here
        on — readers receive it without copying."""
        rec = {
            "families": families,
            "by_name": _index(families),
            "ts": round(time.time(), 3),
            "ts_mono": time.monotonic(),
        }
        with self._lock:
            rec["tick"] = self._seq
            self._seq += 1
            self._ticks.append(rec)
        return rec

    def size(self) -> int:
        with self._lock:
            return len(self._ticks)

    def last_tick(self) -> int:
        """Sequence number of the newest record (-1 when empty)."""
        with self._lock:
            return self._ticks[-1]["tick"] if self._ticks else -1

    def window(self, ticks: int | None = None) -> list[dict]:
        """The newest ``ticks`` records oldest-first (all when None;
        empty for ticks <= 0 — a negative slice bound must not invert
        the clip into 'serve everything')."""
        with self._lock:
            recs = list(self._ticks)
        if ticks is not None:
            n = int(ticks)
            recs = recs[-n:] if n > 0 else []
        return recs

    # --- series extraction (the alert predicates' input) ---

    def series(self, family: str, labels: tuple = (),
               window: int | None = None) -> dict[tuple, list[tuple]]:
        """``{full label pairs -> [(tick, ts_mono, value), ...]}`` for
        every sample named ``family`` whose labels contain the selector
        subset, over the newest ``window`` ticks (oldest-first)."""
        out: dict[tuple, list[tuple]] = {}
        for rec in self.window(window):
            for label_pairs, value in rec["by_name"].get(family, ()):
                if _matches(label_pairs, labels):
                    out.setdefault(label_pairs, []).append(
                        (rec["tick"], rec["ts_mono"], value))
        return out

    def cum_series(self, family: str, labels: tuple = (),
                   window: int | None = None) -> dict[tuple, list[tuple]]:
        """Histogram view of :meth:`series`: ``{non-le label pairs ->
        [(tick, ts_mono, {le -> cum count}), ...]}`` for ``family``'s
        ``_bucket`` samples — the shape `obs.metrics.quantile_from_cum`
        consumes after windowed differencing."""
        out: dict[tuple, list[tuple]] = {}
        bucket_name = family + "_bucket"
        for rec in self.window(window):
            per_key: dict[tuple, dict[float, float]] = {}
            for label_pairs, value in rec["by_name"].get(bucket_name, ()):
                if not _matches(label_pairs, labels):
                    continue
                d = dict(label_pairs)
                raw_le = d.pop("le", "+Inf")
                try:
                    le = obs_metrics.sample_value(raw_le)
                except ValueError:
                    continue
                key = tuple(sorted(d.items()))
                per_key.setdefault(key, {})[le] = value
            for key, cum in per_key.items():
                out.setdefault(key, []).append(
                    (rec["tick"], rec["ts_mono"], cum))
        return out

    # --- the HTTP view ---

    def to_json(self, ticks: int | None = None,
                families: tuple = ()) -> dict:
        """The ``GET /fleet/metrics/history`` body: newest ``ticks``
        records oldest-first, each tick's families in the lossless
        strict-JSON shape (:func:`family_to_json`).  ``families`` is an
        optional tuple of family-name PREFIXES (the ``?families=``
        filter): each tick keeps only matching families, in original
        order, so a filtered tick still re-renders byte-exact for the
        families it carries — same grammar, smaller wire cost."""
        recs = self.window(ticks)
        prefixes = tuple(p for p in families if p)

        def keep_fam(fam: MetricFamily) -> bool:
            return (not prefixes
                    or any(fam.name.startswith(p) for p in prefixes))

        return {
            "keep": self.keep,
            "ticks": [{
                "tick": rec["tick"],
                "ts": rec["ts"],
                "families": [family_to_json(f) for f in rec["families"]
                             if keep_fam(f)],
            } for rec in recs],
        }
