"""Double-buffered host->device block staging for the streaming routes.

The chunked (>HBM) backend streams ``(block, nchan, nbin)`` subint slabs
through the device.  Before this module, each pass ran

    load block k -> dispatch kernels on block k -> sync block k-1 -> ...

on ONE thread, so the host-side work of ``load`` (slicing the host cube,
the dtype copy, and the device transfer enqueue -- on a slow link the
transfer itself) serialized in front of every block's compute.  The stager
here moves ``load`` onto a background thread with a credit protocol sized
to the existing residency budget:

- ``depth`` credits (default 2) bound how many device blocks may be live
  at once; the consumer returns a credit only after it has *synced* the
  compute that consumed the oldest block, so at steady state exactly two
  blocks exist on device -- the current one computing and the next one
  uploading -- which is the same 2-slab budget
  ``autoshard.chunk_block_subints`` already sizes blocks for.
- the consumer's only wait is ``queue.get`` on a block whose upload did
  not finish hiding under the previous block's compute; the share of that
  wait NOT absorbed by still-in-flight compute (the critical-path
  ``stall``) is the pipeline's figure of demerit, exported as
  ``ingest_stall`` next to the ``ingest_upload`` busy time so
  ``overlap efficiency = 1 - stall/upload`` is computable from counters.

Determinism: the stager changes WHEN bytes move, never their values or the
order the consumer sees blocks in, so every mask stays bit-identical to the
serial path (pinned by tests/test_ingest.py and the fuzz corpus's
chunked-serial A/B mode).  ``ICT_INGEST_DEPTH=1`` reverts to the serial
in-line path everywhere.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Iterable, Sequence

#: Default staging depth: current block computing + next block uploading.
#: Matches the 2-slab device-residency budget autoshard sizes blocks for;
#: raising it buys nothing until uploads are faster than compute AND the
#: block budget is re-derived.
DEFAULT_DEPTH = 2

_stats_lock = threading.Lock()
_STATS = {
    "blocks": 0,           # blocks staged through any stager
    "serial_blocks": 0,    # of which on the serial (depth=1) path
    "bytes": 0,            # device bytes staged
    "upload_busy_s": 0.0,  # stager-thread time spent loading blocks
    "wait_s": 0.0,         # raw consumer time blocked on a not-yet-ready
                           # block (first-block pipeline fill excluded)
    "stall_s": 0.0,        # the CRITICAL-PATH share of that wait: per
                           # block, the get-wait minus the compute-sync
                           # time that ran anyway right after it (a wait
                           # fully absorbed by an in-flight compute costs
                           # no wall clock); serial loads count entirely
                           # — nothing hides an in-line load
}


def stream_depth() -> int:
    """The staging depth (``ICT_INGEST_DEPTH``, default 2; 1 = serial)."""
    try:
        return max(1, int(os.environ.get("ICT_INGEST_DEPTH", DEFAULT_DEPTH)))
    except ValueError:
        return DEFAULT_DEPTH


def stats_snapshot() -> dict:
    """Cumulative pipeline counters + the derived overlap figures.

    ``overlap_efficiency`` is the fraction of upload busy-time whose cost
    was hidden under device compute: ``1 - stall/upload``, clamped to
    [0, 1], where ``stall_s`` is the critical-path wait (see _STATS).  The
    serial path scores 0 by construction (every in-line load is exposed
    wall clock); a pipeline whose uploads always finished under the
    previous block's compute scores 1."""
    with _stats_lock:
        s = dict(_STATS)
    busy = s["upload_busy_s"]
    s["overlap_efficiency"] = (
        round(max(0.0, min(1.0, 1.0 - s["stall_s"] / busy)), 4)
        if busy > 1e-9 else 0.0)
    s["effective_gbps"] = (
        round(s["bytes"] / 1e9 / busy, 4) if busy > 1e-9 else 0.0)
    s["upload_busy_s"] = round(busy, 4)
    s["wait_s"] = round(s["wait_s"], 4)
    s["stall_s"] = round(s["stall_s"], 4)
    return s


def reset_stats() -> None:
    """Zero the cumulative counters (bench sections measure deltas)."""
    with _stats_lock:
        _STATS.update(blocks=0, serial_blocks=0, bytes=0,
                      upload_busy_s=0.0, wait_s=0.0, stall_s=0.0)


def _note(blocks=0, serial=0, nbytes=0, upload_s=0.0, wait_s=0.0,
          stall_s=0.0) -> None:
    with _stats_lock:
        _STATS["blocks"] += blocks
        _STATS["serial_blocks"] += serial
        _STATS["bytes"] += nbytes
        _STATS["upload_busy_s"] += upload_s
        _STATS["wait_s"] += wait_s
        _STATS["stall_s"] += stall_s


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class BlockStager:
    """Iterate ``((lo, hi), device_block)`` with uploads staged ahead.

    ``load(lo, hi)`` runs on the stager thread and must return the
    device-dispatched block (e.g. ``jnp.asarray(host[lo:hi], dtype)``).
    The CONSUMER drives the credit protocol: after it has synced the
    compute that consumed a block, it calls :meth:`release` to let the
    stager start the next upload.  :func:`stream_map` packages that
    protocol correctly -- prefer it; iterating a stager directly without
    releasing credits stalls the pipeline after ``depth`` blocks.

    Per-instance counters (``upload_busy_s``, ``wait_s``, ``nbytes``,
    ``blocks``) accumulate alongside the module-global ones.
    """

    def __init__(
        self,
        ranges: Iterable[tuple[int, int]],
        load: Callable[[int, int], object],
        depth: int | None = None,
    ) -> None:
        self.ranges: Sequence[tuple[int, int]] = list(ranges)
        self._load = load
        self.depth = stream_depth() if depth is None else max(1, int(depth))
        self.upload_busy_s = 0.0
        self.wait_s = 0.0
        self.stall_s = 0.0
        self.last_wait_s = 0.0  # this block's get-wait, read by stream_map
        self.serial = False     # which path __iter__ took
        self.nbytes = 0
        self.blocks = 0
        self._credits = threading.Semaphore(self.depth)
        self._stop = threading.Event()

    def release(self) -> None:
        """Return one residency credit: the oldest staged block's consumer
        is done (compute synced), so its device buffer is reclaimable and
        the next upload may start."""
        self._credits.release()

    def _account(self, blk, dt: float, serial: bool) -> None:
        nbytes = int(getattr(blk, "nbytes", 0))
        self.upload_busy_s += dt
        self.nbytes += nbytes
        self.blocks += 1
        _note(blocks=1, serial=int(serial), nbytes=nbytes, upload_s=dt)

    def __iter__(self):
        if self.depth == 1 or len(self.ranges) <= 1:
            # Serial fallback: load in-line on the consumer thread --
            # the pre-pipeline behavior, kept reachable for A/B parity
            # (fuzz chunked-serial mode) and for hosts where a background
            # thread is unwanted (ICT_INGEST_DEPTH=1).  Every in-line load
            # is exposed wall clock, so it all counts as stall.
            self.serial = True
            for lo, hi in self.ranges:
                t0 = time.perf_counter()
                blk = self._load(lo, hi)
                dt = time.perf_counter() - t0
                self._account(blk, dt, serial=True)
                self.stall_s += dt
                self.last_wait_s = 0.0
                _note(stall_s=dt)
                yield (lo, hi), blk
            return

        q: queue.Queue = queue.Queue()  # bounded by the credit semaphore

        def run() -> None:
            try:
                for lo, hi in self.ranges:
                    self._credits.acquire()
                    if self._stop.is_set():
                        return
                    t0 = time.perf_counter()
                    blk = self._load(lo, hi)
                    self._account(blk, time.perf_counter() - t0, serial=False)
                    q.put(((lo, hi), blk))
            except BaseException as exc:  # noqa: BLE001 — re-raised consumer-side
                q.put(_Failure(exc))

        th = threading.Thread(target=run, daemon=True, name="ict-ingest-stage")
        th.start()
        try:
            for i in range(len(self.ranges)):
                t0 = time.perf_counter()
                item = q.get()
                dt = time.perf_counter() - t0
                if isinstance(item, _Failure):
                    raise item.exc
                if i:  # the first block's fill has nothing to overlap with
                    self.wait_s += dt
                    self.last_wait_s = dt
                    _note(wait_s=dt)
                else:
                    self.last_wait_s = 0.0
                yield item
        finally:
            # Consumer done or dying mid-stream: unblock the stager thread
            # (it re-checks _stop after every credit) and let it exit.
            self._stop.set()
            self._credits.release()


def stream_map(
    ranges: Iterable[tuple[int, int]],
    load: Callable[[int, int], object],
    compute: Callable[[int, int, object], object],
    sync: Callable[[object], None],
    depth: int | None = None,
) -> list:
    """Run ``compute`` over staged blocks with the full overlap protocol.

    For each range, ``compute(lo, hi, block)`` dispatches the device work
    (asynchronously, as jax does); ``sync(prev_out)`` is called on each
    previous output before the stager is allowed to stage another block --
    that single ordering rule is what bounds device residency to
    ``depth`` blocks while the next upload hides under the current
    compute.  Returns the list of compute outputs, in order.
    """
    from iterative_cleaner_tpu.obs import tracing

    unset = object()  # sentinel: a compute() returning None is still synced
    outs: list = []
    stager = BlockStager(ranges, load, depth=depth)
    prev = unset
    for (lo, hi), blk in stager:
        get_wait = stager.last_wait_s
        out = compute(lo, hi, blk)
        if prev is not unset:
            t0 = time.perf_counter()
            sync(prev)
            sync_s = time.perf_counter() - t0
            stager.release()
            if not stager.serial:
                # Critical-path accounting: this block's get-wait ran while
                # the previous block's compute was still in flight (the
                # sync right after proves how much compute was left); only
                # the surplus beyond that compute cost wall clock.
                stall = max(0.0, get_wait - sync_s)
                if stall:
                    stager.stall_s += stall
                    _note(stall_s=stall)
        outs.append(out)
        prev = out
    if prev is not unset:
        sync(prev)
    # One phase observation per pass (not per block): the daemon /metrics
    # view of the same counters the module-global snapshot feeds.
    tracing.observe_phase("ingest_upload", stager.upload_busy_s)
    if stager.stall_s:
        tracing.observe_phase("ingest_stall", stager.stall_s)
    return outs
