"""Lossless f32 wire codec for the spool/session block path.

The dev tunnel moves ~37 MB/s; the fastest upload is the byte you never
send.  Raw f32 radio data compresses poorly as-is (the mantissa bytes are
noise) but its exponent/sign bytes are highly repetitive, so the codec
byte-shuffles each array -- regrouping byte 0 of every element, then byte
1, ... (the bitshuffle/blosc trick) -- before a general-purpose entropy
coder.  DEFLATE (stdlib zlib) is the floor available everywhere;
``zstandard`` is used automatically when importable (``ICT_WIRE_CODEC``
overrides: ``npz`` | ``shuffle-zlib`` | ``shuffle-zstd``).

The payload is self-describing (magic + JSON header), and the decoder also
accepts the legacy NPZ container (zip magic), so spools written by older
daemons and uploads from older clients keep replaying byte-for-byte through
the same path.  Round-trips are bit-exact for every f32 value including
NaN/inf payloads -- the codec cannot touch mask parity by construction.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import zlib

import numpy as np

#: Wire magic for the shuffled-compressed container ("ICT Wire v1").
MAGIC = b"ICTW1\x00"

#: Legacy container magic (np.savez writes a zip archive).
_ZIP_MAGIC = b"PK\x03\x04"

#: DEFLATE effort: 6 is zlib's default speed/ratio balance; the wire is
#: tens of MB/s, so heavier settings only pay off on even slower links.
ZLIB_LEVEL = 6

#: Decode-side cap on the TOTAL raw bytes a payload's header may declare
#: (callers pass tighter caps — online/blocks.py does).  DEFLATE inflates
#: up to ~1032:1, so without this a 256 MB wire payload could declare and
#: attempt a ~264 GB allocation; with it, memory is bounded by the cap no
#: matter what the header or the streams claim.
MAX_RAW_BYTES = 4 << 30

try:  # gated optional dep: the container image has no zstandard wheel
    import zstandard as _zstd  # type: ignore
except ImportError:  # pragma: no cover - exercised where zstd exists
    _zstd = None

_stats_lock = threading.Lock()
_STATS = {
    "encoded": 0, "raw_bytes_in": 0, "wire_bytes_out": 0,
    "decoded": 0, "wire_bytes_in": 0, "raw_bytes_out": 0,
}


def stats_snapshot() -> dict:
    with _stats_lock:
        s = dict(_STATS)
    s["codec"] = wire_codec_name()
    s["encode_ratio"] = (round(s["wire_bytes_out"] / s["raw_bytes_in"], 4)
                         if s["raw_bytes_in"] else None)
    return s


def reset_stats() -> None:
    with _stats_lock:
        for k in _STATS:
            _STATS[k] = 0


def wire_codec_name() -> str:
    """The codec new payloads are written with (``ICT_WIRE_CODEC``
    override; invalid names fall back to the best available default so a
    typo degrades to a working wire, not a dead daemon)."""
    import os

    name = os.environ.get("ICT_WIRE_CODEC", "")
    if name in ("npz", "shuffle-zlib"):
        return name
    if name == "shuffle-zstd" and _zstd is not None:
        return name
    return "shuffle-zstd" if _zstd is not None else "shuffle-zlib"


def _shuffle(raw: bytes, itemsize: int) -> bytes:
    """Byte-transpose: all byte-0s, then all byte-1s, ...  Same length."""
    u8 = np.frombuffer(raw, np.uint8)
    return np.ascontiguousarray(u8.reshape(-1, itemsize).T).tobytes()


def _unshuffle(raw: bytes, itemsize: int) -> bytes:
    u8 = np.frombuffer(raw, np.uint8)
    return np.ascontiguousarray(u8.reshape(itemsize, -1).T).tobytes()


def _compress(raw: bytes, codec: str) -> bytes:
    if codec == "shuffle-zstd":
        return _zstd.ZstdCompressor().compress(raw)
    return zlib.compress(raw, ZLIB_LEVEL)


def _decompress(raw: bytes, codec: str, n: int) -> bytes:
    """Inflate at most ``n`` bytes (the header-declared array size).

    The bound is enforced DURING decompression, not after: a stream that
    would inflate past its declared size is rejected with at most ``n+1``
    bytes ever materialized, so a crafted stream cannot allocate beyond
    what the header admits to (and the header total is capped before any
    stream is touched — see :func:`_decode_ictw`)."""
    if codec == "shuffle-zstd":
        if _zstd is None:
            raise ValueError(
                "payload compressed with zstd but the zstandard module is "
                "not importable here; re-encode with ICT_WIRE_CODEC="
                "shuffle-zlib or install zstandard")
        # A frame's embedded content size is allocated verbatim by
        # decompress(); reject an over-declared frame before that, and cap
        # unknown-size frames at n.
        try:  # pragma: no cover - exercised where zstd exists
            declared = _zstd.frame_content_size(raw)
        except Exception as exc:  # noqa: BLE001 — malformed frame header
            raise ValueError(f"malformed zstd frame: {exc}") from None
        if declared not in (-1, n):  # pragma: no cover
            raise ValueError(
                f"zstd frame declares {declared} bytes, header admits {n}")
        return _zstd.ZstdDecompressor().decompress(  # pragma: no cover
            raw, max_output_size=max(n, 1))
    out = zlib.decompressobj().decompress(raw, n + 1)
    if len(out) > n:
        raise ValueError(
            f"stream inflates past the {n} bytes its header declares")
    return out


def encode_arrays(arrays: dict[str, np.ndarray],
                  codec: str | None = None) -> bytes:
    """``{name: f32 array} -> wire bytes`` (see the module docstring).

    ``codec=None`` picks :func:`wire_codec_name`; ``"npz"`` writes the
    legacy NPZ container verbatim (the compatibility escape hatch).
    """
    codec = codec or wire_codec_name()
    arrays = {k: np.ascontiguousarray(v, np.float32)
              for k, v in arrays.items()}
    raw_total = sum(a.nbytes for a in arrays.values())
    if codec == "npz":
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        out = buf.getvalue()
    else:
        if codec not in ("shuffle-zlib", "shuffle-zstd"):
            raise ValueError(f"unknown wire codec {codec!r}")
        header = {"codec": codec, "arrays": []}
        streams = []
        for name, a in arrays.items():
            comp = _compress(_shuffle(a.tobytes(), a.itemsize), codec)
            header["arrays"].append({
                "name": name, "shape": list(a.shape),
                "dtype": str(a.dtype), "nbytes": len(comp),
            })
            streams.append(comp)
        head = json.dumps(header, separators=(",", ":")).encode()
        out = b"".join([MAGIC, struct.pack("<I", len(head)), head, *streams])
    with _stats_lock:
        _STATS["encoded"] += 1
        _STATS["raw_bytes_in"] += raw_total
        _STATS["wire_bytes_out"] += len(out)
    return out


def _decode_ictw(payload: bytes,
                 max_raw_bytes: int = MAX_RAW_BYTES) -> dict[str, np.ndarray]:
    off = len(MAGIC)
    if len(payload) < off + 4:
        raise ValueError("truncated ICTW payload (no header length)")
    (hlen,) = struct.unpack_from("<I", payload, off)
    off += 4
    if len(payload) < off + hlen:
        raise ValueError("truncated ICTW payload (header)")
    try:
        header = json.loads(payload[off:off + hlen].decode())
        codec = header["codec"]
        entries = header["arrays"]
    except (ValueError, KeyError, UnicodeDecodeError) as exc:
        raise ValueError(f"malformed ICTW header: {exc}") from None
    off += hlen
    # Parse and size-check EVERY entry before inflating ANY stream: the
    # total the header declares is capped, and each stream's inflation is
    # then bounded to its declared size inside _decompress — so a crafted
    # payload can never allocate past max_raw_bytes.
    parsed = []
    total = 0
    for ent in entries:
        try:
            name, shape = ent["name"], tuple(int(d) for d in ent["shape"])
            dtype, nbytes = np.dtype(ent["dtype"]), int(ent["nbytes"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ValueError(f"malformed ICTW array entry: {exc}") from None
        if any(d < 0 for d in shape) or nbytes < 0:
            raise ValueError(f"malformed ICTW array entry for {name!r}")
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        total += n
        if total > max_raw_bytes:
            raise ValueError(
                f"ICTW header declares > {max_raw_bytes} raw bytes "
                f"({total} and counting at array {name!r}) — rejecting "
                f"before decompression")
        parsed.append((name, shape, dtype, nbytes, n))
    out: dict[str, np.ndarray] = {}
    for name, shape, dtype, nbytes, n in parsed:
        if len(payload) < off + nbytes:
            raise ValueError(f"truncated ICTW stream for array {name!r}")
        raw = _unshuffle(_decompress(payload[off:off + nbytes], codec, n),
                         dtype.itemsize)
        if len(raw) != n:
            raise ValueError(
                f"ICTW array {name!r}: {len(raw)} decompressed bytes, "
                f"expected {n} for shape {shape}")
        out[name] = np.frombuffer(raw, dtype).reshape(shape)
        off += nbytes
    return out


def decode_payload(payload: bytes,
                   max_raw_bytes: int = MAX_RAW_BYTES) -> dict[str, np.ndarray]:
    """Wire bytes -> ``{name: array}``; sniffs the container by magic
    (ICTW vs legacy NPZ/zip) and raises ValueError on anything malformed.
    ICTW payloads cannot inflate past ``max_raw_bytes`` total (nor any
    single stream past the size its header declares) — the bound holds
    during decompression, not after it."""
    if payload.startswith(MAGIC):
        try:
            out = _decode_ictw(payload, max_raw_bytes)
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 — zlib/zstd errors vary
            raise ValueError(f"undecodable ICTW payload: {exc}") from None
    elif payload.startswith(_ZIP_MAGIC):
        try:
            with np.load(io.BytesIO(payload), allow_pickle=False) as z:
                out = {name: np.asarray(z[name]) for name in z.files}
        except Exception as exc:  # noqa: BLE001 — zipfile/format errors vary
            raise ValueError(f"undecodable block payload: {exc}") from None
    else:
        raise ValueError("unrecognized block payload (neither ICTW nor NPZ)")
    with _stats_lock:
        _STATS["decoded"] += 1
        _STATS["wire_bytes_in"] += len(payload)
        _STATS["raw_bytes_out"] += sum(a.nbytes for a in out.values())
    return out
