"""Content addressing for cleaned results (the ingest half of the
throughput tier, ROADMAP item 2).

Reprocessing campaigns resubmit byte-identical archives by the thousand
(DDF-Pipeline-style reruns, arXiv:2509.03075); cleaning is deterministic,
so a resubmission's mask is already known the moment its bytes hash the
same.  This module owns the two hashes that make that reuse safe:

- :func:`cube_key` -- the **canonical content address** of one cleaning
  problem: SHA-256 over the preprocessed cube bytes (``D`` and ``w0``,
  shape/dtype framed so concatenation ambiguity cannot alias two
  problems) plus the :func:`cache_salt`.  Computed at ingest (the loader
  just decoded the cube anyway) and checked replica-side in the dispatch
  worker (service/results_cache.py) -- two different files holding the
  same cube dedupe here.
- :func:`file_digest` -- a plain SHA-256 of the archive file's raw
  bytes, no salt.  The fleet router cannot decode archives at placement
  time, but it can hash the submitted file cheaply; paired with the
  replicas' advertised :func:`cache_salt` it keys the router's
  fleet-wide result index (fleet/cache.py), so byte-identical
  resubmissions return without touching any replica's device.

**Invalidation is the salt.**  :func:`cache_salt` hashes the package
version together with every mask-affecting ``CleanConfig`` field
(thresholds, iteration cap, pulse region, bad-parts policy).  A code
upgrade or a config change yields a different salt, hence different
keys, hence clean misses -- stale entries are never *wrong*, only
unreachable, and the bounded LRU sweeps them out.  Route-selection
fields (``backend``/``fused``/``pallas``/``chunk_block``/...) are
deliberately NOT salted: masks are bit-identical across every execution
mode by the repo's core invariant (docs/PARITY.md), so a result cleaned
on one route answers a resubmission routed anywhere.  ``ICT_CACHE_SALT``
folds an operator-chosen extra salt in (the manual flush knob).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from iterative_cleaner_tpu.obs import tracing

#: CleanConfig fields that can change the served mask (or the served
#: output archive's contents) -- the salt covers exactly these.  The
#: output-policy fields ride along because the cached record is reused
#: to WRITE an output archive: two configs that mask identically but
#: pscrunch differently must not share cache entries.
_SALT_FIELDS = (
    "chanthresh", "subintthresh", "max_iter", "pulse_region",
    "bad_chan", "bad_subint", "pscrunch", "output",
)


def cache_salt(cfg) -> str:
    """Hex salt naming (version, mask-relevant config, operator salt) --
    equal salts mean "a cached mask from there answers here"."""
    from iterative_cleaner_tpu import __version__

    h = hashlib.sha256()
    h.update(__version__.encode())
    for name in _SALT_FIELDS:
        h.update(f"|{name}={getattr(cfg, name)!r}".encode())
    extra = os.environ.get("ICT_CACHE_SALT", "")
    if extra:
        h.update(b"|salt=" + extra.encode())
    return h.hexdigest()[:16]


def _frame(h, arr: np.ndarray) -> None:
    """Hash one array self-describingly: dtype + shape + C-order bytes,
    so (D, w0) pairs of different splits can never collide by
    concatenation."""
    arr = np.ascontiguousarray(arr)
    h.update(f"|{arr.dtype.str}{arr.shape}|".encode())
    h.update(arr.tobytes())


def cube_key(D: np.ndarray, w0: np.ndarray, cfg) -> str:
    """The content address of one cleaning problem: preprocessed cube
    bytes + weights + :func:`cache_salt`."""
    h = hashlib.sha256()
    h.update(cache_salt(cfg).encode())
    _frame(h, D)
    _frame(h, w0)
    return h.hexdigest()


def file_digest(path: str) -> str:
    """Plain SHA-256 of the file's raw bytes (streamed; '' on any read
    error -- content addressing is an optimization, never a failure
    mode)."""
    h = hashlib.sha256()
    try:
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    except OSError:
        return ""
    return h.hexdigest()


def cache_report() -> dict:
    """Cumulative result-cache counters out of the process-global
    registry -- the degraded ``coalesce.cache`` block bench.py's
    error/watchdog paths fall back to (the ingest.stats_report
    pattern)."""
    snap = tracing.counters_snapshot()
    return {
        "hits": int(snap.get("service_result_cache_hits", 0)),
        "misses": int(snap.get("service_result_cache_misses", 0)),
        "bytes_saved": int(snap.get("service_result_cache_bytes_saved", 0)),
    }
