"""Ingest tier: getting cubes onto the device without the device idling.

BENCH_r02 measured the wall this package exists to break: 537x per-iteration
compute next to a 29 s host->device upload at 37 MB/s -- end-to-end the chip
sat idle waiting for bytes.  Two attacks, both pure plumbing (no math, no
mask influence):

- :mod:`.pipeline` -- a double-buffered block-staging pipeline that keeps
  the NEXT block's host->device transfer in flight while the current
  block's kernels run.  Shared by the chunked (>HBM) clean route, the
  streaming ``OnlineSession`` passes, and therefore every daemon worker
  that dispatches either.
- :mod:`.codec` -- a lossless f32 wire codec (byteshuffle + DEFLATE, zstd
  when available) so the spool/session path moves fewer bytes over slow
  links in the first place.

Both layers are value-preserving by construction: the pipeline reorders
*when* bytes move, never what they are, and the codec round-trips bit-exact
-- the repo's bit-identical-mask invariant cannot be touched from here.
"""

from iterative_cleaner_tpu.ingest.codec import (  # noqa: F401
    decode_payload,
    encode_arrays,
    wire_codec_name,
)
from iterative_cleaner_tpu.ingest.pipeline import (  # noqa: F401
    BlockStager,
    stream_depth,
    stream_map,
)


def stats_report() -> dict:
    """One dict with both layers' cumulative counters -- the ``ingest``
    block bench.py promises on every exit path (degraded runs report
    whatever accumulated before the failure).  The headline overlap keys
    are hoisted to the top so the payload contract (tools/perf_gate.py)
    can require them regardless of which path emitted the block."""
    from iterative_cleaner_tpu.ingest import codec, pipeline

    pstats = pipeline.stats_snapshot()
    cstats = codec.stats_snapshot()
    return {
        "overlap_efficiency": pstats["overlap_efficiency"],
        "effective_gbps": pstats["effective_gbps"],
        "codec_ratio": cstats["encode_ratio"],
        "pipeline": pstats,
        "codec": cstats,
    }
