"""Device-mesh construction for the cleaner's parallel axes.

The reference is strictly serial (SURVEY.md §2.4); the TPU framework's
parallelism maps onto three mesh axes:

- ``dp`` — data parallel: one archive per mesh slice (the embarrassingly
  parallel directory-batch axis, BASELINE.md config #4);
- ``sp`` — subint sharding within an archive (the sequence/context-parallel
  analog: per-channel medians become cross-device reductions over ICI);
- ``tp`` — channel sharding (the tensor-parallel analog: per-subint medians
  reduce across it).

XLA GSPMD inserts the collectives (all-gathers for the sharded sorts, psums
for the template reduction); nothing custom rides the wire.  ``make_mesh``
defaults to this process's local devices; the normal multi-host deployment
partitions the archive batch per process (parallel/multihost.py) over
per-host meshes.  A deliberately DCN-spanning mesh (one replicated program
sharding a single giant cube across hosts) requires
``initialize_distributed()`` and an explicit ``devices=jax.devices()``.
"""

from __future__ import annotations


import jax
import numpy as np
from jax.sharding import Mesh


def factor_mesh(n: int) -> tuple[int, int, int]:
    """Split n devices into (dp, sp, tp), favoring dp (archives scale
    embarrassingly), then sp (biggest axis: nsub), then tp."""
    out = [1, 1, 1]
    i = 0
    m = n
    # Peel smallest prime factors, assigning round-robin dp -> sp -> tp.
    while m > 1:
        p = next(p for p in range(2, m + 1) if m % p == 0)
        out[i % 3] *= p
        m //= p
        i += 1
    return tuple(out)


def make_mesh(
    n_devices: int | None = None,
    dp: int | None = None,
    sp: int | None = None,
    tp: int | None = None,
    devices=None,
) -> Mesh:
    """Build a ('dp', 'sp', 'tp') mesh over the first n devices.

    Defaults to this process's *local* devices: in a multi-controller run
    every process partitions the archive batch (parallel/multihost.py) and
    drives its own chips with its own control flow — a global mesh would
    require identical programs on every process, which per-host path slices
    are not.  Pass ``devices=jax.devices()`` explicitly to build a
    DCN-spanning mesh for a single replicated program.
    """
    if devices is None:
        from iterative_cleaner_tpu.utils.device_probe import init_watchdog

        # This is the first in-process device read for every caller that
        # does not bring its own devices (batch dispatch, tools) — the
        # wedged-tunnel hang lands exactly here, so the watchdog turns a
        # silent freeze into a structured warning (the daemon's own wrap
        # in _start_locked is now one of several guarded paths).
        with init_watchdog("make_mesh device discovery"):
            devices = jax.local_devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None and sp is None and tp is None:
        dp, sp, tp = factor_mesh(n_devices)
    dp, sp, tp = dp or 1, sp or 1, tp or 1
    if dp * sp * tp != n_devices:
        raise ValueError(f"dp*sp*tp = {dp * sp * tp} != n_devices = {n_devices}")
    grid = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(grid, ("dp", "sp", "tp"))


def initialize_distributed() -> None:  # pragma: no cover - needs multi-host
    """Multi-host entry: call once per process before any device use;
    afterwards jax.devices() spans all hosts while make_mesh still builds a
    local mesh by default.  To shard one program across hosts over DCN, pass
    ``make_mesh(devices=jax.devices())`` explicitly — and run the identical
    program on every process."""
    jax.distributed.initialize()
