"""Device-mesh construction for the cleaner's parallel axes.

The reference is strictly serial (SURVEY.md §2.4); the TPU framework's
parallelism maps onto three mesh axes:

- ``dp`` — data parallel: one archive per mesh slice (the embarrassingly
  parallel directory-batch axis, BASELINE.md config #4);
- ``sp`` — subint sharding within an archive (the sequence/context-parallel
  analog: per-channel medians become cross-device reductions over ICI);
- ``tp`` — channel sharding (the tensor-parallel analog: per-subint medians
  reduce across it).

XLA GSPMD inserts the collectives (all-gathers for the sharded sorts, psums
for the template reduction); nothing custom rides the wire.  Multi-host
(DCN) extends the same mesh via ``jax.distributed.initialize`` — see
``initialize_distributed``.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh


def factor_mesh(n: int) -> tuple[int, int, int]:
    """Split n devices into (dp, sp, tp), favoring dp (archives scale
    embarrassingly), then sp (biggest axis: nsub), then tp."""
    out = [1, 1, 1]
    i = 0
    m = n
    # Peel smallest prime factors, assigning round-robin dp -> sp -> tp.
    while m > 1:
        p = next(p for p in range(2, m + 1) if m % p == 0)
        out[i % 3] *= p
        m //= p
        i += 1
    return tuple(out)


def make_mesh(
    n_devices: int | None = None,
    dp: int | None = None,
    sp: int | None = None,
    tp: int | None = None,
    devices=None,
) -> Mesh:
    """Build a ('dp', 'sp', 'tp') mesh over the first n devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None and sp is None and tp is None:
        dp, sp, tp = factor_mesh(n_devices)
    dp, sp, tp = dp or 1, sp or 1, tp or 1
    if dp * sp * tp != n_devices:
        raise ValueError(f"dp*sp*tp = {dp * sp * tp} != n_devices = {n_devices}")
    grid = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(grid, ("dp", "sp", "tp"))


def initialize_distributed() -> None:  # pragma: no cover - needs multi-host
    """Multi-host entry: call once per process before building the global
    mesh; afterwards jax.devices() spans all hosts and make_mesh shards over
    ICI within a slice and DCN across slices."""
    jax.distributed.initialize()
