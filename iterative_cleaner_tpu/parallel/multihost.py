"""Multi-host (DCN) batch dispatch.

Archives are embarrassingly parallel (SURVEY.md §2.4 DP row), so the
multi-host story is deliberately thin: every host runs the same CLI over the
same directory, each takes its round-robin slice of the path list, and no
tensor ever crosses DCN — ICI carries the intra-archive collectives of the
sharded kernel, DCN carries nothing but the job launch.  This mirrors how the
reference would be scaled with a job array, but built in.

For a cube too big even for one *host's* chips, the global mesh from
``jax.distributed.initialize`` + ``make_mesh`` spans hosts and the sp/tp
collectives ride DCN; that path works unchanged through
``parallel.sharded`` because GSPMD is topology-agnostic — it is just slower,
and the autoshard router never picks it spontaneously.  Proven end to end by
``tests/test_multihost_resume.py::TestGlobalMeshTwoProcess``: two real
processes, one (sp=4, tp=2) mesh across them, oracle-exact masks on both
hosts (``sharded._to_host`` all-gathers the process-spanning outputs).
"""

from __future__ import annotations

import jax


def process_topology() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) in single-process runs."""
    # Multi-controller entry: callers ran jax.distributed.initialize (an
    # explicit operator action) before partitioning, so backend init here
    # is deliberate, not a stray first touch.
    return jax.process_index(), jax.process_count()  # ict: backend-init-ok(post-distributed-init entry)


def partition_paths(
    paths: list[str],
    process_index: int | None = None,
    process_count: int | None = None,
) -> list[str]:
    """This host's slice of a directory batch (round-robin, so hosts stay
    balanced when archives are listed in size order)."""
    if process_index is None or process_count is None:
        pi, pc = process_topology()
        process_index = pi if process_index is None else process_index
        process_count = pc if process_count is None else process_count
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} not in [0, {process_count})")
    return paths[process_index::process_count]


def local_mesh(**kw):
    """A mesh over this process's addressable devices only — the normal
    multi-host deployment (one mesh per host, archives partitioned by
    partition_paths; nothing crosses DCN).  ``make_mesh`` already defaults
    to local devices; this alias exists so multi-host call sites say what
    they mean."""
    from iterative_cleaner_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.local_devices(), **kw)  # ict: backend-init-ok(post-distributed-init entry)
