"""Automatic sharding for cubes that exceed a single chip's HBM.

The reference holds every archive in host RAM and has no notion of device
memory (SURVEY.md §5 "long-context" row).  On TPU the stress config is a
real constraint: a 1024x4096x1024 f32 cube is ~17 GB against v5e's 16 GB HBM
(BASELINE.md config #5), so the framework must notice before the allocator
does and route the clean through the (sp, tp)-sharded kernel, whose
per-channel/per-subint median reductions become XLA collectives over ICI
(parallel/sharded.py).

The decision is an estimate by design: it errs toward sharding (peak factor
measured generously) because the failure mode of not sharding is an OOM
abort, while the cost of sharding unnecessarily is a few all-gathers.
"""

from __future__ import annotations

import jax

from iterative_cleaner_tpu.obs import memory as obs_memory

# Peak device working set of the fused kernel, in cube-sized units: the cube
# itself, the complex64 rfft of the centred cube (nbin/2+1 bins at 8 bytes
# ~= one cube), the centred/weighted intermediate, and the sort buffers of
# the masked medians (XLA fuses most moment reductions into these).
# History/weights/test arrays are (max_iter, nsub, nchan) — noise by
# comparison.  bench.py validates this constant on hardware every run:
# BENCH_r*.json carries `peak_cube_factor_measured` (the device's
# peak_bytes_in_use / cube bytes at the bench config).
PEAK_CUBE_FACTOR = 3.5

# Fraction of reported device memory treated as usable (XLA reserves some,
# and fragmentation is real).
HBM_USABLE_FRACTION = 0.9


def default_devices():
    """The devices the clean would actually run on: the configured default
    device's platform when one is set (the test harness pins CPU while the
    process also holds a TPU backend; JAX accepts a Device or a platform
    string there), else this process's local devices.  Local, not global:
    the router runs inside one process's control flow, so a DCN-spanning
    mesh here would dispatch collectives the other processes never join
    (multihost.py promises the router 'never picks DCN spontaneously')."""
    from iterative_cleaner_tpu.utils.device_probe import init_watchdog

    # A library embedder's clean_cube() reaches this before any CLI-layer
    # probe ran: first backend init can happen HERE, and a wedged tunnel
    # hangs it process-wide — the watchdog makes that diagnosable.
    with init_watchdog("autoshard device discovery"):
        dev = jax.config.jax_default_device
        if dev is not None:
            return jax.local_devices(
                backend=dev if isinstance(dev, str) else dev.platform)
        return jax.local_devices()


def device_memory_bytes(device=None) -> int | None:
    """Best-effort per-device memory capacity.

    Resolution order: the ``ICT_HBM_BYTES`` env override (tests, and hosts
    where the runtime misreports), the device's ``memory_stats()`` limit
    (TPU), else None (unknown — e.g. CPU backends report no limit).

    Delegates to :mod:`iterative_cleaner_tpu.obs.memory` — the single
    owner of every ``memory_stats()`` read — so the autoshard routing
    decision and the gauges exported on ``/metrics`` can never disagree
    about what a device reported."""
    return obs_memory.device_memory_bytes(
        device, default_device_fn=lambda: default_devices()[0])


def working_set_bytes(shape: tuple[int, ...], itemsize: int = 4) -> int:
    """Estimated peak device bytes for cleaning one cube of ``shape``."""
    n = 1
    for dim in shape:
        n *= int(dim)
    return int(n * itemsize * PEAK_CUBE_FACTOR)


def should_shard(
    shape: tuple[int, ...],
    device=None,
    n_devices: int | None = None,
    itemsize: int = 4,
) -> bool:
    """True when the cube's working set will not fit one device and more
    than one device is available to spread it over.  ``itemsize`` is the
    compute dtype's width — 8 under x64, where an f32-sized estimate would
    undercount by half and wave an OOM through."""
    if n_devices is None:
        n_devices = len(default_devices())
    if n_devices < 2:
        return False
    hbm = device_memory_bytes(device)
    if hbm is None:
        return False
    return working_set_bytes(shape, itemsize) > hbm * HBM_USABLE_FRACTION


def single_archive_mesh(shape: tuple[int, int, int], n_devices: int | None = None):
    """A (dp=1, sp, tp) mesh for one oversized archive: all devices go to
    the intra-archive axes, preferring sp (nsub, the bigger reduction axis)
    and falling back to tp for factors nsub cannot absorb.  Axes that do not
    divide their dimension end up replicated by batch_spec, wasting the
    device — so factor against the actual dims."""
    from iterative_cleaner_tpu.parallel.mesh import make_mesh

    devices = default_devices()
    if n_devices is None:
        n_devices = len(devices)
    nsub, nchan = int(shape[0]), int(shape[1])
    sp = 1
    m = n_devices
    # Peel prime factors into sp while they divide nsub, rest into tp.
    p = 2
    while m > 1 and p <= m:
        if m % p == 0 and nsub % (sp * p) == 0:
            sp *= p
            m //= p
        else:
            p += 1
    tp = 1
    while m > 1:
        p = next(q for q in range(2, m + 1) if m % q == 0)
        if nchan % (tp * p) == 0:
            tp *= p
        m //= p
    used = sp * tp
    # Any devices we could not cleanly use stay out of the mesh entirely.
    return make_mesh(n_devices=used, dp=1, sp=sp, tp=tp, devices=devices)


def chunk_block_subints(shape: tuple[int, ...], cfg) -> int | None:
    """Subint slab size for the single-device streaming backend
    (:class:`.chunked.ChunkedJaxCleaner`), or None when the cube's working
    set fits the device.

    This is the route of last resort behind :func:`maybe_clean_sharded` —
    the answer for an oversized cube when sharding is unavailable (one chip:
    the v5e-1 north-star target vs config #5's 17 GB working set) or
    unsuitable (mesh-indivisible dims, --x64 bit-parity, --unload_res).
    Half the usable budget per slab: consecutive blocks' device buffers
    briefly coexist across the upload/compute boundary.
    """
    itemsize = 8 if cfg.x64 else 4
    hbm = device_memory_bytes()
    if hbm is None:
        return None
    usable = hbm * HBM_USABLE_FRACTION
    if working_set_bytes(shape, itemsize) <= usable:
        return None
    per_sub = working_set_bytes((1, *shape[1:]), itemsize)
    block = int(usable / 2 // per_sub)
    return max(1, min(block, int(shape[0])))


def maybe_clean_sharded(D, w0, cfg, want_residual: bool):
    """The auto-shard router: returns a CleanResult when the cube was
    rerouted through the multi-device sharded kernel, None when the caller
    should run a single-device path (the normal in-memory one, or — if
    :func:`chunk_block_subints` says the cube does not fit — the chunked
    streaming backend; :mod:`..core.cleaner` consults it next).

    Declines to reroute when the caller needs the residual cube (the fused
    sharded kernel does not materialise it), when --x64 is set (the sharded
    kernel would silently drop the f64 bit-parity mode), or when no mesh
    axis divides the cube's dims — in all three cases the chunked backend
    picks the cube up instead.  The reroute and its consequences (no
    per-loop progress, no mask history, pallas falling back to the XLA
    kernel) are announced on stderr — a silent mode switch would make one
    archive in a batch behave inexplicably differently from its neighbors.
    """
    import sys

    from iterative_cleaner_tpu.core.cleaner import CleanResult
    from iterative_cleaner_tpu.parallel.sharded import sharded_clean_single

    itemsize = 8 if cfg.x64 else 4
    if want_residual or cfg.x64 or not should_shard(D.shape, itemsize=itemsize):
        return None
    mesh = single_archive_mesh(D.shape)
    gb = working_set_bytes(D.shape, itemsize) / 1e9
    if mesh.devices.size == 1:
        # No mesh axis divides the cube's dims: decline silently — the
        # chunked route picks it up and prints the one authoritative
        # "chunked clean" announcement (a second note here would just
        # double the routing noise per archive).
        return None
    notes = "no per-loop progress; disable with auto_shard=False"
    if cfg.pallas:
        notes = "pallas unavailable on the sharded route, using the XLA " \
                "kernel; " + notes
    print(
        f"auto-sharding cube {tuple(D.shape)}: ~{gb:.1f} GB working set "
        f"exceeds device memory; cleaning sharded over {mesh.devices.size} "
        f"devices ({notes})",
        file=sys.stderr)
    test, w_final, loops, done = sharded_clean_single(D, w0, cfg, mesh)
    return CleanResult(
        weights=w_final,
        test_results=test,
        loops=loops,
        converged=done,
    )
