from iterative_cleaner_tpu.parallel.mesh import factor_mesh, make_mesh
from iterative_cleaner_tpu.parallel.sharded import sharded_clean
from iterative_cleaner_tpu.parallel.batch import clean_directory_batch

__all__ = ["factor_mesh", "make_mesh", "sharded_clean", "clean_directory_batch"]
