"""Directory-batch driver: bucket archives by shape, clean each bucket on the
mesh, keep per-archive failure isolation.

The reference processes archives strictly sequentially
(iterative_cleaner.py:45); here same-shape archives are stacked and cleaned
in one sharded dispatch (one archive per dp slice).  Archive decode uses a
small thread pool; all cubes for a directory are resident on host during
bucketing (shapes are only known after load), but each bucket's cubes are
released as soon as its dispatch returns.
"""

from __future__ import annotations

from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.core.cleaner import find_bad_parts
from iterative_cleaner_tpu.io.base import Archive, get_io
from iterative_cleaner_tpu.ops.preprocess import preprocess
from iterative_cleaner_tpu.parallel.mesh import make_mesh
from iterative_cleaner_tpu.parallel.sharded import sharded_clean
from iterative_cleaner_tpu.utils.compile_cache import (
    batch_route_key,
    note_compiled_shape,
)


@dataclass
class BatchItem:
    path: str
    archive: Archive | None = None
    weights: np.ndarray | None = None   # final cleaned weights
    test_results: np.ndarray | None = None
    loops: int = 0
    converged: bool = False
    rfi_frac: float = 0.0
    error: str | None = None
    # Convergence forensics (filled only when the dispatcher ran with
    # want_history — the serving daemon's per-job timeline source).
    iterations: list | None = None      # list[IterationInfo]
    termination: str = ""               # "fixed_point" | "cycle" | "max_iter"


def _load_and_preprocess(path: str):
    archive = get_io(path).load(path)
    D, w0 = preprocess(archive)
    return archive, D, w0


def _require_jax_backend(cfg: CleanConfig) -> None:
    if cfg.backend != "jax":
        raise ValueError(
            "clean_directory_batch shards over devices and requires "
            "backend='jax'; use driver.run() for the sequential numpy path")


def finalize_weights(final_w, cfg) -> tuple[np.ndarray, float]:
    """One archive's post-clean finalization policy, in ONE place (shared
    by the bucket dispatcher, the service's oracle fallback, and the serve
    smoke check, so the three can never drift): rfi_frac reports the
    iterative mask BEFORE the bad-parts sweep — identical to the
    sequential driver's ArchiveReport.rfi_frac — and the sweep runs only
    when a flag differs from 1.  Returns (final_weights, rfi_frac)."""
    rfi_frac = float((final_w == 0).mean())
    if cfg.bad_chan != 1 or cfg.bad_subint != 1:
        final_w, _ns, _nc = find_bad_parts(final_w, cfg)
    return final_w, rfi_frac


def _finish_bucket(items, idxs, Db, w0b, cfg, mesh, on_item=None,
                   want_history=False) -> None:
    """Run one stacked bucket on the mesh and write results into its
    BatchItems (shared by the all-at-once and streaming dispatchers).
    ``on_item(i, item)`` fires per finished archive — the streaming driver
    emits outputs there and releases the item's host arrays, which is what
    makes its memory bound real.  ``want_history`` additionally fetches the
    per-archive mask histories and derives each item's per-iteration
    forensics records + termination reason (the serving daemon's
    ``GET /jobs/<id>/trace`` source; off by default — extra host traffic)."""
    from iterative_cleaner_tpu.obs import forensics
    from iterative_cleaner_tpu.obs.tracing import (
        compile_scope,
        shape_bucket_label,
    )

    # The key mirrors batched_fused_clean's static-arg surface; shared with
    # the service warm pool so a pool-warmed batch shape is recognised here
    # (see compile_cache.batch_route_key for the x64 note).
    note_compiled_shape(batch_route_key(Db.shape, cfg))
    with compile_scope(shape_bucket_label(Db.shape)):
        if want_history:
            test_b, w_b, loops_b, done_b, x_b, hist_b = sharded_clean(
                Db, w0b, cfg, mesh, want_history=True)
        else:
            test_b, w_b, loops_b, done_b = sharded_clean(Db, w0b, cfg, mesh)
    for j, i in enumerate(idxs):
        item = items[i]
        final_w, item.rfi_frac = finalize_weights(w_b[j], cfg)
        item.weights = final_w
        item.test_results = test_b[j]
        item.loops = int(loops_b[j])
        item.converged = bool(done_b[j])
        if want_history and hist_b is not None:
            from iterative_cleaner_tpu.core.cleaner import _iteration_info

            hist = hist_b[j][: int(x_b[j]) + 1]
            item.iterations = [
                _iteration_info(k, hist[k - 1], hist[k])
                for k in range(1, len(hist))
            ]
            item.termination = forensics.termination_reason(
                item.converged, hist)
        if on_item is not None:
            on_item(i, item)


def clean_directory_batch(
    paths: list[str],
    cfg: CleanConfig,
    mesh: Mesh | None = None,
) -> list[BatchItem]:
    """Clean many archives; same-shape archives share sharded dispatches.

    A corrupt archive fails alone — it is reported in its BatchItem and never
    takes the bucket down (SURVEY.md §5 failure-detection gap, filled here).
    """
    _require_jax_backend(cfg)
    if mesh is None:
        mesh = make_mesh()
    items = [BatchItem(path=p) for p in paths]

    # Load + preprocess with a small thread pool (archive decode is
    # host-side, independent per file).
    def load(item: BatchItem):
        try:
            item.archive, D, w0 = _load_and_preprocess(item.path)
            return D, w0
        except Exception as exc:  # noqa: BLE001 — isolate the bad archive
            item.error = str(exc)
            return None

    with ThreadPoolExecutor(max_workers=4) as pool:
        loaded = list(pool.map(load, items))

    buckets: dict[tuple, list[int]] = defaultdict(list)
    cubes: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    for i, out in enumerate(loaded):
        if out is None:
            continue
        D, w0 = out
        loaded[i] = None  # `cubes` is now the sole owner -> per-bucket release works
        cubes[i] = (D, w0)
        buckets[D.shape].append(i)

    for _shape, idxs in buckets.items():
        Db = np.stack([cubes[i][0] for i in idxs])
        w0b = np.stack([cubes[i][1] for i in idxs])
        for i in idxs:  # bucket cubes are stacked; release the originals
            del cubes[i]
        _finish_bucket(items, idxs, Db, w0b, cfg, mesh)
    return items


def clean_directory_streaming(
    paths: list[str],
    cfg: CleanConfig,
    mesh: Mesh | None = None,
    bucket_cap: int | None = None,
    n_loaders: int = 4,
    on_item=None,
) -> list[BatchItem]:
    """Streaming variant: archive decode overlaps device compute.

    A loader pool decodes archives concurrently; the consumer dispatches a
    bucket as soon as ``bucket_cap`` same-shape cubes have arrived (default:
    the mesh's dp extent — one full data-parallel slice) while the loaders
    keep reading ahead.  Unlike :func:`clean_directory_batch` this never
    holds the whole directory on host: load submission is throttled, and
    when parked sub-cap buckets (a shape-heterogeneous directory) push total
    decoded-cube residency past ``bucket_cap + n_loaders``, the fullest
    bucket is flushed early.  Same-shape archives split across flushes land
    in separate dispatches — masks are per-archive either way.

    The bound is only real when the caller passes ``on_item(i, item)`` and
    releases each item's ``archive``/``weights``/``test_results`` there
    after emitting outputs (as ``driver.run_sharded_batch`` does) — without
    it every decoded Archive stays resident on its BatchItem.
    """
    from concurrent.futures import FIRST_COMPLETED, wait

    _require_jax_backend(cfg)
    if mesh is None:
        mesh = make_mesh()
    if bucket_cap is None:
        bucket_cap = max(int(mesh.shape["dp"]), 1)
    items = [BatchItem(path=p) for p in paths]

    def load(i: int):
        try:
            items[i].archive, D, w0 = _load_and_preprocess(items[i].path)
            return i, D, w0
        except Exception as exc:  # noqa: BLE001 — isolate the bad archive
            items[i].error = str(exc)
            return i, None, None

    pending: dict[tuple, list[tuple[int, np.ndarray, np.ndarray]]] = defaultdict(list)

    def flush(shape, pow2: bool = False) -> None:
        group = pending.pop(shape)
        if pow2 and len(group) > 1:
            # Early (pressure) flushes trim to a power-of-two batch so the
            # fused kernel sees O(log cap) distinct batch sizes per shape
            # instead of one jit recompile per arbitrary size; the
            # remainder stays parked for a later flush.
            k = 1 << (len(group).bit_length() - 1)
            group, rest = group[:k], group[k:]
            if rest:
                pending[shape] = rest
        idxs = [i for i, _, _ in group]
        Db = np.stack([d for _, d, _ in group])
        w0b = np.stack([w for _, _, w in group])
        del group
        _finish_bucket(items, idxs, Db, w0b, cfg, mesh, on_item=on_item)

    # Submission is throttled to bound host memory: one new load enters the
    # pool only as a finished one is consumed, so a device dispatch slower
    # than decode cannot pile the whole directory into finished futures.
    read_ahead = bucket_cap + n_loaders
    next_idx = iter(range(len(paths)))
    with ThreadPoolExecutor(max_workers=n_loaders) as pool:
        from itertools import islice

        futures = {pool.submit(load, i) for i in islice(next_idx, read_ahead)}
        while futures:
            done, futures = wait(futures, return_when=FIRST_COMPLETED)
            for fut in done:
                i, D, w0 = fut.result()
                if D is not None:
                    pending[D.shape].append((i, D, w0))
                    # Dispatch blocks this (consumer) thread on the device;
                    # the pool threads keep decoding the read-ahead
                    # meanwhile.
                    if len(pending[D.shape]) >= bucket_cap:
                        flush(D.shape)
                    # Parked sub-cap buckets still count against residency:
                    # a many-shapes directory would otherwise accumulate the
                    # whole directory in `pending`.  Flush the fullest
                    # bucket early (a smaller dispatch, same masks).
                    elif sum(len(g) for g in pending.values()) >= read_ahead:
                        flush(max(pending, key=lambda s: len(pending[s])),
                              pow2=True)
                for j in islice(next_idx, 1):
                    futures.add(pool.submit(load, j))
    for shape in list(pending):
        flush(shape)
    return items
