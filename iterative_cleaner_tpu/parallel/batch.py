"""Directory-batch driver: bucket archives by shape, clean each bucket on the
mesh, keep per-archive failure isolation.

The reference processes archives strictly sequentially
(iterative_cleaner.py:45); here same-shape archives are stacked and cleaned
in one sharded dispatch (one archive per dp slice).  Archive decode uses a
small thread pool; all cubes for a directory are resident on host during
bucketing (shapes are only known after load), but each bucket's cubes are
released as soon as its dispatch returns.
"""

from __future__ import annotations

from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.core.cleaner import find_bad_parts
from iterative_cleaner_tpu.io.base import Archive, get_io
from iterative_cleaner_tpu.ops.preprocess import preprocess
from iterative_cleaner_tpu.parallel.mesh import make_mesh
from iterative_cleaner_tpu.parallel.sharded import sharded_clean


@dataclass
class BatchItem:
    path: str
    archive: Archive | None = None
    weights: np.ndarray | None = None   # final cleaned weights
    test_results: np.ndarray | None = None
    loops: int = 0
    converged: bool = False
    rfi_frac: float = 0.0
    error: str | None = None


def _load_and_preprocess(path: str):
    archive = get_io(path).load(path)
    D, w0 = preprocess(archive)
    return archive, D, w0


def clean_directory_batch(
    paths: list[str],
    cfg: CleanConfig,
    mesh: Mesh | None = None,
) -> list[BatchItem]:
    """Clean many archives; same-shape archives share sharded dispatches.

    A corrupt archive fails alone — it is reported in its BatchItem and never
    takes the bucket down (SURVEY.md §5 failure-detection gap, filled here).
    """
    if cfg.backend != "jax":
        raise ValueError(
            "clean_directory_batch shards over devices and requires "
            "backend='jax'; use driver.run() for the sequential numpy path")
    if mesh is None:
        mesh = make_mesh()
    items = [BatchItem(path=p) for p in paths]

    # Load + preprocess with a small thread pool (archive decode is
    # host-side, independent per file).
    def load(item: BatchItem):
        try:
            item.archive, D, w0 = _load_and_preprocess(item.path)
            return D, w0
        except Exception as exc:  # noqa: BLE001 — isolate the bad archive
            item.error = str(exc)
            return None

    with ThreadPoolExecutor(max_workers=4) as pool:
        loaded = list(pool.map(load, items))

    buckets: dict[tuple, list[int]] = defaultdict(list)
    cubes: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    for i, out in enumerate(loaded):
        if out is None:
            continue
        D, w0 = out
        loaded[i] = None  # `cubes` is now the sole owner -> per-bucket release works
        cubes[i] = (D, w0)
        buckets[D.shape].append(i)

    for _shape, idxs in buckets.items():
        Db = np.stack([cubes[i][0] for i in idxs])
        w0b = np.stack([cubes[i][1] for i in idxs])
        for i in idxs:  # bucket cubes are stacked; release the originals
            del cubes[i]
        test_b, w_b, loops_b, done_b = sharded_clean(Db, w0b, cfg, mesh)
        for j, i in enumerate(idxs):
            item = items[i]
            final_w = w_b[j]
            # rfi_frac reports the iterative mask, pre-bad-parts sweep —
            # identical to the sequential driver's ArchiveReport.rfi_frac.
            item.rfi_frac = float((final_w == 0).mean())
            if cfg.bad_chan != 1 or cfg.bad_subint != 1:
                final_w, _ns, _nc = find_bad_parts(final_w, cfg)
            item.weights = final_w
            item.test_results = test_b[j]
            item.loops = int(loops_b[j])
            item.converged = bool(done_b[j])
    return items
