"""Sharded multi-archive execution: vmap over archives, GSPMD over the mesh.

The batched clean is the single-archive kernel vmapped over a leading archive
axis, with inputs laid out on a ('dp', 'sp', 'tp') mesh: archives over dp,
subints over sp, channels over tp.  The cross-profile couplings are exactly
the per-channel / per-subint median reductions (SURVEY.md §2.4 SP/CP row), so
the sharded sorts all-gather their axis over ICI and everything else stays
local; XLA inserts those collectives from the input shardings.

Batching note: archives are bucketed by *exact* shape.  Zero-weight padding
is NOT mask-transparent — padded profiles would still enter the mask-blind
FFT diagnostic's plain medians (§8.L1) and change real archives' masks — so
we never pad.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.backends.jax_backend import clean_step, fused_clean


@partial(jax.jit, static_argnames=("pulse_region",))
def batched_clean_step(Db, w0b, validb, w_prevb, chanthresh, subintthresh, *, pulse_region):
    """One iteration for a batch of archives: (a, s, c, b) cubes."""
    fn = lambda D, w0, v, w: clean_step(
        D, w0, v, w, chanthresh, subintthresh, pulse_region=pulse_region)
    return jax.vmap(fn)(Db, w0b, validb, w_prevb)


@partial(jax.jit, static_argnames=("max_iter", "pulse_region", "use_pallas"))
def batched_fused_clean(Db, w0b, validb, chanthresh, subintthresh, *,
                        max_iter, pulse_region, use_pallas=False):
    """Whole convergence loop for a batch (vmapped lax.while_loop: runs until
    every archive in the batch has converged or hit max_iter).

    ``use_pallas`` routes each archive's stats phase through the fused
    megakernel (pallas_call has a vmap batching rule: the archive axis
    becomes a leading grid dimension).  It stays OFF for mesh-sharded
    dispatches by policy, not oversight: GSPMD cannot partition an opaque
    ``pallas_call`` custom call, so a sharded operand would be all-gathered
    to every device first — re-materialising the full cube is exactly what
    the sharded route exists to avoid (the same static-analysis argument
    that keeps fft_diagnostic custom-partitioned, and why
    ``test_sharded_lowering_never_gathers_the_cube`` would fail).  A
    future shard_map wrapper is the clean unlock; until then the sharded
    route's resolver never turns it on, and CleanConfig still rejects an
    explicit ``pallas=True, sharded_batch=True``.
    """
    fn = lambda D, w0, v: fused_clean(
        D, w0, v, chanthresh, subintthresh,
        max_iter=max_iter, pulse_region=pulse_region, use_pallas=use_pallas)
    return jax.vmap(fn)(Db, w0b, validb)


def batch_spec(shape, mesh: Mesh) -> P:
    """archives->dp, subints->sp, channels->tp, bins replicated — dropping
    any mesh axis that does not divide its array dimension (GSPMD requires
    even sharding; a bucket of 1 archive on a dp=2 mesh just replicates dp)."""
    names = ("dp", "sp", "tp")
    dims = []
    for dim, name in zip(shape[:3], names):
        dims.append(name if dim % mesh.shape[name] == 0 else None)
    dims += [None] * (len(shape) - 3)
    return P(*dims)


def shard_batch(Db, w0b, mesh: Mesh):
    """Lay a stacked batch out on the mesh (see batch_spec).

    The host arrays go straight into the sharded ``device_put`` — a
    ``jnp.asarray`` first would materialise the whole batch on the default
    device, which is exactly what a >HBM cube routed here cannot survive."""
    Db = np.asarray(Db)
    w0b = np.asarray(w0b)
    Db = jax.device_put(Db, NamedSharding(mesh, batch_spec(Db.shape, mesh)))
    w0b = jax.device_put(w0b, NamedSharding(mesh, batch_spec(w0b.shape, mesh)))
    return Db, w0b


def sharded_clean_single(D: np.ndarray, w0: np.ndarray, cfg: CleanConfig, mesh: Mesh):
    """One archive sharded over (sp, tp) — the path for cubes that exceed a
    single chip's HBM (BASELINE.md config #5: the 17 GB stress cube needs
    nsub-sharding on v5e).  Returns (test, weights, loops, converged)."""
    test, w, loops, done = sharded_clean(D[None], w0[None], cfg, mesh)
    return test[0], w[0], int(loops[0]), bool(done[0])


def _to_host(*xs) -> tuple[np.ndarray, ...]:
    """Host values of possibly process-spanning global arrays.

    On a mesh confined to this process a plain fetch works; on a global
    mesh from ``jax.distributed`` (the multi-host DCN path,
    :mod:`.multihost`) the outputs' shards live on other processes'
    devices, so every process all-gathers the global values — each host
    needs the full mask to write its outputs.  One pytree allgather for
    all outputs (they share a mesh, hence addressability), not one
    blocking DCN round per array.
    """
    if all(x.is_fully_addressable for x in xs):
        return tuple(np.asarray(x) for x in xs)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tuple(xs), tiled=True)
    return tuple(np.asarray(g) for g in gathered)


def sharded_clean(
    Db: np.ndarray,
    w0b: np.ndarray,
    cfg: CleanConfig,
    mesh: Mesh,
    want_history: bool = False,
):
    """Clean a same-shape batch of preprocessed cubes on a device mesh.

    Returns host arrays: (test (a,s,c), weights (a,s,c), loops (a,),
    converged (a,)) — plus, with ``want_history`` (the serving daemon's
    convergence-forensics fetch, docs/OBSERVABILITY.md), the per-archive
    iteration counts (a,) and the mask-history ring buffers
    (a, max_iter+1, s, c); rows 0..x[j] of archive j's buffer are populated
    (row 0 = w0).  History is fetched only on request: it is max_iter+1
    masks per archive of extra host traffic the default path must not pay.
    The mesh may span processes (multi-controller SPMD): every
    participating process must call this with the same batch, and each gets
    the full host-side result back.
    """
    Db, w0b = shard_batch(Db, w0b, mesh)
    validb = w0b != 0
    test, w_final, loops, done, x, _r, hist = batched_fused_clean(
        Db,
        w0b,
        validb,
        float(cfg.chanthresh),
        float(cfg.subintthresh),
        max_iter=int(cfg.max_iter),
        pulse_region=tuple(cfg.pulse_region),
    )
    if want_history:
        if all(v.is_fully_addressable
               for v in (test, w_final, loops, done, x, hist)):
            # Fetch only the populated ring-buffer prefix: rows past the
            # batch's largest iteration count are zero padding the host
            # slice (hist_b[j][:x_b[j]+1]) would discard anyway, and at
            # max_iter >> loops they dominate the device->host transfer.
            hist = hist[:, : int(x.max()) + 1]
            return _to_host(test, w_final, loops, done, x, hist)
        # Multi-controller mesh: the history fetch is driven by PER-PROCESS
        # telemetry state (ICT_TELEMETRY/ICT_FORENSICS can differ across
        # hosts), and a process-allgather whose pytree differs between
        # hosts deadlocks every participant — so on a process-spanning
        # mesh the forensics fetch degrades to "no history" rather than
        # extending the same-on-every-process contract to env vars.
        out = _to_host(test, w_final, loops, done)
        return (*out, None, None)
    return _to_host(test, w_final, loops, done)
