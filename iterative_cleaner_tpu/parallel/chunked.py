"""Single-device cleaning of cubes that exceed HBM: stream subint blocks.

The multi-device answer to an oversized cube is the (sp, tp)-sharded kernel
(:mod:`.sharded`); on a lone chip (the BASELINE.md north-star target is TPU
v5e-**1**, where config #5's 17 GB working set beats 16 GB HBM) there is no
second device to spread over, so this backend keeps the cube in host RAM —
exactly where the reference keeps it (iterative_cleaner.py:110) — and streams
``(block, nchan, nbin)`` subint slabs through the device inside each
iteration.

Two passes per iteration, both expressed with the *same* kernels as the
in-memory path so the semantics cannot drift:

1. **template pass** — the weighted profile scrunch
   (:func:`..ops.template.build_template`) is a sum over profiles, so each
   block contributes a partial via the *same* ``build_template`` lowering
   as the in-memory path, accumulated on device.  (Block-wise accumulation
   reorders the f32 sum relative to the monolithic reduction; the masks
   are insensitive to the resulting few-ulp template wobble — per-element
   score drift up to ~5e-5 relative, pinned by ``tests/test_chunked.py`` —
   but bit-identity of intermediate template/score values to the in-memory
   path is not guaranteed for partial blocks.  A single-block stream has no
   reordering and is bit-exact throughout.)
2. **stats pass** — per block: closed-form fit + residual
   (:func:`..ops.template.fit_and_subtract`), weight pre-scaling, and the
   four per-profile diagnostics (:func:`..ops.stats.diagnostics`) — all
   per-profile math, bit-identical to the in-memory path.  Only the tiny
   (nsub, nchan) diagnostic maps stay device-resident.  Under
   ``cfg.pallas`` the fit/weight/centre/moment part of this pass runs the
   fused Pallas kernel per block (one HBM pass over the block —
   :mod:`..ops.pallas_kernels`), exactly as the in-memory ``clean_step``
   does.

The cross-profile couplings (per-channel / per-subint robust scalers) run
once on the assembled maps — three orders of magnitude smaller than the cube.

Every streaming pass runs through the double-buffered upload pipeline
(:mod:`..ingest.pipeline`): block k+1's host slice + dtype copy + device
transfer proceed on a background stager thread while block k's kernels run,
with device residency still bounded to two slabs by the pipeline's credit
protocol (the same budget ``autoshard.chunk_block_subints`` sizes blocks
for).  ``ICT_INGEST_DEPTH=1`` reverts to the serial in-line path; masks are
bit-identical either way (the pipeline moves bytes earlier, never changes
them or the block order).

Cost model: 2 cube uploads for the FIRST iteration; from iteration 2 the
template pass drops out whenever few enough profiles flipped
(``cfg.incremental_template``, on by default): the backend carries the
previous template and adds ``sum (Δw) * profile`` over the flipped profiles
— a host gather of at most ``INCREMENTAL_TEMPLATE_BUDGET`` profiles instead
of re-streaming the cube — so steady-state cost is ~1 cube upload per
iteration (the stats pass re-reads the data for fit/ptp/|rfft|; no moment
trick avoids that).  Any non-finite candidate or over-budget flip count
falls back to the dense streamed template pass (same soundness rule as the
fused kernel's `_incremental_template`).  On a real TPU host the PCIe link
runs at GB/s, so a 17 GB cube costs ~tens of seconds per upload — against
the reference's 4.2 M Python→MINPACK round-trips at the same scale.  Unlike
the sharded reroute this is a stepwise backend, so per-loop progress, mask
history, and the residual archive all keep working.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.ingest.pipeline import stream_map
from iterative_cleaner_tpu.ops.stats import diagnostics, scale_and_combine
from iterative_cleaner_tpu.ops.template import build_template, fit_and_subtract


@partial(jax.jit, donate_argnums=(0,))
def _sparse_template_update(tmpl, dvals, profs):
    """tmpl + sum_k dvals[k] * profs[k] — the flipped-profile correction.
    Inputs are padded host-side to the fixed INCREMENTAL_TEMPLATE_BUDGET
    rows (zero rows contribute nothing) so one executable serves every
    iteration.  ``tmpl`` is donated (registered in
    ``analysis/contracts.ROUTE_DONATIONS``): the carried template is dead
    the moment its successor exists — ``_template_for`` reassigns the
    carry on every call, on both the accept and the dense-fallback branch,
    so the donated buffer is never re-read."""
    return tmpl + jnp.matmul(
        dvals, profs, precision=jax.lax.Precision.HIGHEST)


@jax.jit
def _partial_template(Dblk, wblk):
    """One block's contribution to the weighted profile scrunch — the same
    lowering as the in-memory ``build_template`` so a single-block stream is
    bit-identical to the in-memory path (multi-block accumulation reorders
    the sum either way; ~ulp score wobble, module docstring)."""
    return build_template(Dblk, wblk)


@partial(jax.jit, static_argnames=("pulse_region", "want_resid"))
def _block_stats(Dblk, template, w0blk, validblk, *, pulse_region, want_resid):
    """Fit + subtract + weight + per-profile diagnostics for one block."""
    _amp, resid = fit_and_subtract(Dblk, template, pulse_region)
    weighted = resid * w0blk[..., None]
    d_std, d_mean, d_ptp, d_fft = diagnostics(weighted, validblk)
    if want_resid:
        return d_std, d_mean, d_ptp, d_fft, resid
    return d_std, d_mean, d_ptp, d_fft, None


@partial(jax.jit, static_argnames=("pulse_region", "interpret"))
def _block_stats_pallas(Dblk, template, w0blk, validblk, *, pulse_region,
                        interpret):
    """The Pallas route for one block: the fused fit/weight/centre/moments
    kernel with the numpy.ma valid-fills fused in (one HBM pass over the
    block — ops/pallas_kernels.py), then the XLA FFT diagnostic."""
    from iterative_cleaner_tpu.ops.pallas_kernels import fused_fit_moments
    from iterative_cleaner_tpu.ops.stats import fft_diagnostic

    centred, d_mean, d_std, d_ptp = fused_fit_moments(
        Dblk, template, w0blk, validblk, pulse_region=pulse_region,
        interpret=interpret)
    return d_std, d_mean, d_ptp, fft_diagnostic(centred)


@partial(jax.jit, donate_argnums=(0, 1))
def _finish(d_std, d_mean, d_ptp, d_fft, valid, w0, chanthresh, subintthresh):
    """Robust scalers + combine on the assembled (nsub, nchan) maps, then the
    weight update (zap where test >= 1; NaN never flags, §8.L3).

    ``d_std``/``d_mean`` are donated (ROUTE_DONATIONS ledger): the maps are
    freshly concatenated per step and dead after this call, and both alias
    the equally-shaped f32 outputs (test, new_w) — two fewer (nsub, nchan)
    allocations per iteration.  ``w0``/``valid`` are NOT donated: the
    backend reuses them every step."""
    test = scale_and_combine(
        d_std, d_mean, d_ptp, d_fft, valid, chanthresh, subintthresh)
    return test, jnp.where(test >= 1.0, 0.0, w0)


class ChunkedJaxCleaner:
    """CleanerBackend streaming subint blocks through one device.

    ``block`` is the subint slab size (from
    :func:`..parallel.autoshard.chunk_block_subints` when routed
    automatically).  ``keep_residual`` enables ``residual()`` — the last
    step's residual cube assembled in host RAM (cube-sized *host* memory;
    the whole point is that it does not fit the device) for --unload_res at
    >HBM scale.  It is computed LAZILY on first ``residual()`` call by
    re-running the two passes for the last step's weights: one extra cube
    upload pass once, instead of a cube download on every iteration for a
    value only the final iteration ever uses.
    """

    def __init__(
        self,
        D: np.ndarray,
        w0: np.ndarray,
        cfg: CleanConfig,
        block: int,
        keep_residual: bool = False,
        ingest_depth: int | None = None,
    ) -> None:
        from iterative_cleaner_tpu.backends.jax_backend import _x64_dtype

        self.cfg = cfg
        # Staging depth of the upload pipeline (None → ICT_INGEST_DEPTH,
        # default 2: next block uploads while the current one computes;
        # 1 = the serial pre-pipeline path, kept for A/B parity).
        self._ingest_depth = ingest_depth
        self.block = int(block)
        if self.block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self._dtype = _x64_dtype(cfg)
        # Host-resident by design: never device_put the whole cube.
        self._D = np.asarray(D, dtype=np.float32)
        self._w0 = jax.device_put(jnp.asarray(w0, self._dtype))
        self._valid = self._w0 != 0
        self._keep_residual = keep_residual
        self._resid_w_prev: np.ndarray | None = None  # last step's weights
        self._residual: np.ndarray | None = None      # lazily-filled cache
        self._tmpl: jnp.ndarray | None = None     # carried template …
        self._tmpl_w: np.ndarray | None = None    # … and its weights
        self._tmpl_dense = False                  # built by the streamed
                                                  # pass (not sparse-updated)
        self.template_passes = 0   # observability: full streamed template
                                   # accumulations (cube uploads) so far
        from iterative_cleaner_tpu.ops.pallas_kernels import resolve_use_pallas

        # Tri-state cfg.pallas (None = auto: the megakernel wherever it is a
        # real optimisation); the explicit-True-but-not-viable case keeps its
        # warning + XLA fallback.
        self._use_pallas = resolve_use_pallas(cfg, self._D.shape[-1])
        if self._use_pallas:
            from iterative_cleaner_tpu.ops.pallas_kernels import (
                pallas_route_status,
            )

            self._use_pallas, route_why = pallas_route_status(
                self._D.shape[-1])
            if not self._use_pallas:
                import warnings

                warnings.warn(
                    f"pallas=True but the Pallas route is not viable here "
                    f"({route_why}); the chunked backend uses the XLA "
                    f"route", stacklevel=2)

    def _blocks(self):
        nsub = self._D.shape[0]
        for lo in range(0, nsub, self.block):
            yield lo, min(lo + self.block, nsub)

    def _load(self, lo: int, hi: int):
        """One block, host slab → device dispatch.  Runs on the ingest
        stager's background thread (ingest/pipeline.py) so the host-side
        slice/copy/transfer of block k+1 hides under block k's compute."""
        return jnp.asarray(self._D[lo:hi], self._dtype)

    @staticmethod
    def _sync(x) -> None:
        """Force one block's computation to completion via a tiny fetch.

        JAX dispatch is asynchronous: without a per-block sync the Python
        loop would enqueue every block's compute up front and the device
        would hold most of the cube at once — exactly the residency this
        backend exists to bound.  ``stream_map`` calls this on block k−1's
        output before returning the stager its upload credit, which keeps
        at most two blocks live (the budget autoshard.chunk_block_subints
        assumes) while block k+1's upload hides under block k's compute.
        (A scalar fetch, not ``block_until_ready`` — the latter is
        unreliable on the axon-tunnel platform the bench runs on.)
        """
        np.asarray(x[(0,) * x.ndim])

    def _template(self, w_prev) -> jnp.ndarray:
        """Pass 1: template accumulation (device-resident accumulator),
        streamed through the double-buffered upload pipeline — block k+1
        uploads while block k's partial accumulates.  The accumulation
        order is the sequential block order either way, so the values are
        identical to the serial path."""
        self.template_passes += 1
        acc = [jnp.zeros(self._D.shape[-1], self._dtype)]

        def accumulate(lo, hi, Dblk):
            acc[0] = acc[0] + _partial_template(Dblk, w_prev[lo:hi])
            return acc[0]

        stream_map(self._blocks(), self._load, accumulate, self._sync,
                   depth=self._ingest_depth)
        return acc[0]

    def _template_for(self, w_host: np.ndarray) -> jnp.ndarray:
        """Template for these weights, incrementally when possible.

        From iteration 2, ``template = carried + sum (Δw)·profile`` over the
        flipped profiles — a host gather of ≤ budget rows replacing the full
        streamed template pass (the module docstring's cost model).  Dense
        fallback whenever: no carried template yet, over-budget flip count,
        a non-finite gathered profile, or a non-finite candidate (an inf/NaN
        profile entering or leaving the support makes inf−inf = NaN where a
        dense rebuild is finite — the same soundness rule as the fused
        kernel's ``_incremental_template``)."""
        from iterative_cleaner_tpu.backends.jax_backend import (
            INCREMENTAL_TEMPLATE_BUDGET,
        )

        host_dt = np.float64 if self.cfg.x64 else np.float32  # ict: f64-ok(explicit --x64 opt-in)
        tmpl = None
        dense = False  # provenance of the value we end up carrying
        if self.cfg.incremental_template and self._tmpl_w is not None:
            delta = w_host.astype(host_dt) - self._tmpl_w.astype(host_dt)
            flat = delta.reshape(-1)
            idx = np.nonzero(flat)[0]
            budget = min(INCREMENTAL_TEMPLATE_BUDGET, flat.size)
            if idx.size == 0:
                tmpl = self._tmpl
                dense = self._tmpl_dense  # unchanged carry keeps provenance
            elif idx.size <= budget:
                s, c = np.unravel_index(idx, delta.shape)
                profs = self._D[s, c, :].astype(host_dt)
                if np.isfinite(profs).all():
                    pad = budget - idx.size
                    dvals = np.pad(flat[idx], (0, pad))
                    profs = np.pad(profs, ((0, pad), (0, 0)))
                    cand = _sparse_template_update(
                        self._tmpl,
                        jnp.asarray(dvals, self._dtype),
                        jnp.asarray(profs, self._dtype))
                    # The call above DONATED the carried template; clear
                    # the carry at once so no path (including an exception
                    # in the dense fallback below) can hand the dead
                    # buffer to a later call.
                    self._tmpl = None
                    if bool(np.isfinite(np.asarray(cand)).all()):
                        tmpl = cand
        if tmpl is None:
            tmpl = self._template(jnp.asarray(w_host, self._dtype))
            dense = True
        self._tmpl = tmpl
        self._tmpl_w = w_host.copy()
        # residual() needs the provenance: its bit-exactness claim vs the
        # in-memory path holds only for dense-built templates, so a
        # sparse-updated carry must not be reused there.
        self._tmpl_dense = dense
        return tmpl

    def step(self, w_prev: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        if self._keep_residual:
            # residual() recomputes from these weights on demand — a cube
            # download per iteration for a value only the last iteration
            # uses would be pure waste.
            self._resid_w_prev = np.asarray(w_prev)
            self._residual = None
        w_host = np.asarray(w_prev)
        template = self._template_for(w_host)

        # Pass 2: per-block fit + diagnostics; maps accumulate on device.
        # Streamed through the upload pipeline: block k+1's host→device
        # transfer hides under block k's kernels (ingest/pipeline.py).
        if self._use_pallas:
            from iterative_cleaner_tpu.ops.pallas_kernels import use_interpret

            interp = use_interpret()

        def block_stats(lo, hi, Dblk):
            if self._use_pallas:
                return _block_stats_pallas(
                    Dblk, template, self._w0[lo:hi], self._valid[lo:hi],
                    pulse_region=tuple(self.cfg.pulse_region),
                    interpret=interp,
                )
            return _block_stats(
                Dblk, template, self._w0[lo:hi], self._valid[lo:hi],
                pulse_region=tuple(self.cfg.pulse_region),
                want_resid=False,
            )[:4]

        maps = stream_map(self._blocks(), self._load, block_stats,
                          lambda out: self._sync(out[0]),
                          depth=self._ingest_depth)

        d_std, d_mean, d_ptp, d_fft = (
            jnp.concatenate([m[k] for m in maps], axis=0) for k in range(4))
        test, new_w = _finish(
            d_std, d_mean, d_ptp, d_fft, self._valid, self._w0,
            jnp.asarray(float(self.cfg.chanthresh), self._dtype),
            jnp.asarray(float(self.cfg.subintthresh), self._dtype),
        )
        return np.asarray(test), np.asarray(new_w)

    def residual(self) -> np.ndarray | None:
        """The last step's residual, recomputed lazily (see class docstring).

        Keeps the compute dtype: under --x64 the in-memory JaxCleaner
        returns an f64 residual, and so does this."""
        if not self._keep_residual or self._resid_w_prev is None:
            return None
        if self._residual is None:
            if (self._tmpl is not None and self._tmpl_dense
                    and np.array_equal(self._resid_w_prev, self._tmpl_w)):
                template = self._tmpl  # current AND dense-built: reusable
            else:
                # Dense rebuild even when a sparse-updated carry matches
                # these weights: the residual archive stays bit-exact vs
                # the in-memory path (the sparse template's ulp drift is
                # documented for SCORES only, not output data).
                template = self._template(
                    jnp.asarray(self._resid_w_prev, self._dtype))
            res_dtype = np.float64 if self.cfg.x64 else np.float32  # ict: f64-ok(explicit --x64 opt-in)
            self._residual = np.empty(self._D.shape, res_dtype)

            def fetch_block(lo, hi, Dblk):
                out = _block_stats(
                    Dblk, template, self._w0[lo:hi], self._valid[lo:hi],
                    pulse_region=tuple(self.cfg.pulse_region),
                    want_resid=True,
                )
                # Fetching the cube-sized block synchronises + frees it
                # (so the per-output sync below is a no-op by design — the
                # pipeline still prefetches block k+1 while this download
                # runs).
                self._residual[lo:hi] = np.asarray(out[4], res_dtype)

            stream_map(self._blocks(), self._load, fetch_block,
                       lambda _out: None, depth=self._ingest_depth)
        return self._residual
