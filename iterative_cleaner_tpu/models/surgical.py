"""The flagship model: the surgical RFI cleaner.

High-level archive-in → archive-out API over the core loop, the equivalent of
the reference's ``clean()`` driver behaviors (iterative_cleaner.py:64-177):
preprocessing, the iterative loop, the final weight application with the
pscrunch output policy, the bad-parts sweep, and the residual archive.

The name "surgical" comes from the algorithm's coast_guard ancestry (the
"Surgical Scrub" cleaning strategy, reference :182).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.core.cleaner import CleanResult, ProgressFn, clean_cube, find_bad_parts
from iterative_cleaner_tpu.io.base import Archive, STATE_INTENSITY
from iterative_cleaner_tpu.ops.preprocess import preprocess, pscrunch, redisperse_cube


@dataclass
class SurgicalOutput:
    cleaned: Archive               # original data, cleaned weights
    result: CleanResult
    residual: Archive | None       # reference --unload_res payload
    n_bad_subints: int = 0
    n_bad_channels: int = 0
    audit: dict | None = None      # --audit: the shadow-oracle parity
                                   # record (obs/audit.run_audit; carries
                                   # "bundle" on a divergence)


def apply_output_policy(archive: Archive, weights: np.ndarray, cfg: CleanConfig) -> Archive:
    """Cleaned output archive: original amplitudes + new weights; full-pol
    unless -p (the reference's reload-from-disk dance at :147-149 exists only
    because it mutated its in-memory archive; we never mutate the input)."""
    if cfg.pscrunch and archive.npol > 1:
        out_data = pscrunch(archive.data, archive.state)[:, None]
        out_state = STATE_INTENSITY
    else:
        out_data = archive.data
        out_state = archive.state
    return replace(
        archive,
        data=out_data,
        weights=np.asarray(weights, dtype=np.float32),
        state=out_state,
    )


class SurgicalCleaner:
    """Configured cleaner; ``clean(archive)`` runs the full pipeline."""

    def __init__(self, cfg: CleanConfig | None = None) -> None:
        self.cfg = cfg or CleanConfig()

    def clean(self, archive: Archive, progress: ProgressFn | None = None) -> SurgicalOutput:
        cfg = self.cfg
        warm = None
        if cfg.backend == "jax":
            # The preprocessed-cube shape is known from the header alone,
            # so XLA compilation overlaps the host preprocessing instead of
            # serializing after it (cold-path latency = max, not sum).
            from iterative_cleaner_tpu.backends.jax_backend import (
                start_precompile,
            )

            shape = (archive.data.shape[0], archive.data.shape[2],
                     archive.data.shape[3])
            warm = start_precompile(shape, cfg, want_residual=cfg.unload_res)
        D, w0 = preprocess(archive)
        if warm is not None:
            # A still-compiling warmup must not race a duplicate compile
            # from the real call below.
            warm.join()
        result = clean_cube(D, w0, cfg, progress=progress, want_residual=cfg.unload_res)

        final_w = result.weights
        n_bs = n_bc = 0
        # The reference only runs the sweep when a flag differs from 1
        # (iterative_cleaner.py:155-156).
        if cfg.bad_chan != 1 or cfg.bad_subint != 1:
            final_w, n_bs, n_bc = find_bad_parts(final_w, cfg)

        cleaned = apply_output_policy(archive, final_w, cfg)

        residual = None
        if cfg.unload_res and result.residual is not None:
            # The residual archive lives in the original dispersed frame with
            # the original weights (reference :103-107; SURVEY.md §3.5).
            res_cube = redisperse_cube(archive, result.residual)
            residual = replace(
                archive,
                data=np.asarray(res_cube, np.float32)[:, None],
                weights=w0.copy(),
                state=STATE_INTENSITY,
                dedispersed=archive.dedispersed,
            )

        audit_rec = None
        if cfg.audit and cfg.backend != "numpy":
            # Shadow-oracle parity audit (obs/audit.py): replay the same
            # preprocessed inputs through the numpy oracle and compare the
            # FINAL mask (bad-parts sweep included on both sides).  A
            # divergence writes a self-contained repro bundle; the audit
            # never alters the outputs already computed above.
            from iterative_cleaner_tpu.obs import audit as obs_audit

            route = ("fused" if cfg.fused else
                     "chunked" if cfg.chunk_block else "stepwise")
            audit_rec, oracle_w = obs_audit.run_audit(
                D, w0, cfg, final_w, scores_served=result.test_results,
                route=route)
            if not audit_rec["mask_identical"]:
                audit_rec["bundle"] = obs_audit.write_repro_bundle(
                    obs_audit.default_repro_dir(), D=D, w0=w0, cfg=cfg,
                    reason=f"--audit divergence on the {route} route",
                    weights_served=final_w, weights_oracle=oracle_w,
                    scores_served=result.test_results, route=route,
                    record=audit_rec)
        elif cfg.audit:
            audit_rec = {"skipped": "backend is the numpy oracle"}

        return SurgicalOutput(
            cleaned=cleaned,
            result=result,
            residual=residual,
            n_bad_subints=n_bs,
            n_bad_channels=n_bc,
            audit=audit_rec,
        )
