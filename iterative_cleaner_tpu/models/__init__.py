from iterative_cleaner_tpu.models.surgical import SurgicalCleaner, SurgicalOutput

__all__ = ["SurgicalCleaner", "SurgicalOutput"]
