"""Threshold sweeps: the whole tuning grid in one device dispatch.

Reference users tune -c/--chanthresh and -s/--subintthresh by rerunning the
entire script per setting (the thresholds are read deep inside the stats
kernel, reference iterative_cleaner.py:201-202).  Here the thresholds are
*traced* scalars of the jitted kernel (backends/jax_backend.py), so a sweep
is a ``vmap`` over (chanthresh, subintthresh) pairs: one compile, one cube
upload, every convergence loop of the grid running batched on the chip.

The per-pair outputs (final mask, rfi_frac, loops, converged) are exactly
what a scientist scans to pick thresholds; `--sweep` prints the table and
optionally dumps all masks for offline comparison.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from iterative_cleaner_tpu.config import CleanConfig


@partial(jax.jit, static_argnames=("max_iter", "pulse_region"))
def _sweep_kernel(D, w0, valid, cts, sts, *, max_iter, pulse_region):
    from iterative_cleaner_tpu.backends.jax_backend import fused_clean

    fn = lambda c, s: fused_clean(
        D, w0, valid, c, s, max_iter=max_iter, pulse_region=pulse_region)[:4]
    return jax.vmap(fn)(cts, sts)


_announced_chunkings: set = set()


@dataclass
class SweepPoint:
    chanthresh: float
    subintthresh: float
    rfi_frac: float
    loops: int
    converged: bool
    weights: np.ndarray | None = None  # final mask for this pair


def sweep_thresholds(
    D: np.ndarray,
    w0: np.ndarray,
    cfg: CleanConfig,
    pairs: list[tuple[float, float]],
    keep_masks: bool = True,
) -> list[SweepPoint]:
    """Clean one preprocessed cube under every (chanthresh, subintthresh)
    pair — a single batched dispatch on device.  Each pair runs the full
    convergence loop (same semantics as a solo run with those thresholds;
    pinned by tests/test_sweep.py)."""
    if not pairs:
        return []
    if cfg.backend != "jax":
        raise ValueError("sweep_thresholds runs the batched device kernel "
                         "and requires backend='jax'")
    if cfg.pallas:
        raise ValueError("sweep_thresholds does not support pallas=True "
                         "(vmapped pallas_call is not wired up); drop one")
    from iterative_cleaner_tpu.backends.jax_backend import _x64_dtype

    dtype = _x64_dtype(cfg)  # a sweep must predict the solo runs it guides

    # vmap batches the kernel's cube-sized intermediates over the pairs, so
    # peak HBM is ~n_pairs x a solo run's working set; chunk the grid to
    # what the device can hold (each chunk size is one compilation; at most
    # two distinct sizes occur).  All sizing runs on host SHAPES before any
    # device_put: a cube too big for even one pair must never be uploaded —
    # it reroutes through per-pair solo cleans (below) instead of OOMing.
    from iterative_cleaner_tpu.parallel.autoshard import (
        HBM_USABLE_FRACTION,
        device_memory_bytes,
        working_set_bytes,
    )

    shape = tuple(np.shape(D))
    chunk = len(pairs)
    hbm = device_memory_bytes()
    if hbm is not None:
        per_pair = working_set_bytes(shape, int(jnp.dtype(dtype).itemsize))
        budget = int(hbm * HBM_USABLE_FRACTION)
        if per_pair > budget:
            # Even a single pair exceeds device memory: the batched kernel
            # cannot run at all.  Each pair is exactly a solo clean with
            # those thresholds (pinned by tests/test_sweep.py), so run the
            # grid through clean_cube, whose autoshard/chunked chain
            # handles >HBM cubes — slower (one streamed clean per pair)
            # but correct, instead of an opaque device OOM.
            key = (shape, str(dtype), "solo", len(pairs))
            if key not in _announced_chunkings:
                _announced_chunkings.add(key)
                import sys

                print(
                    f"sweep: cube {shape} exceeds device memory even for a "
                    f"single pair; running {len(pairs)} pairs as solo "
                    "cleans through the >HBM sharded/chunked chain",
                    file=sys.stderr)
            return _sweep_via_solo_cleans(D, w0, cfg, pairs, keep_masks)
        chunk = max(1, min(chunk, budget // per_pair))
        key = (shape, str(dtype), chunk, len(pairs))
        if chunk < len(pairs) and key not in _announced_chunkings:
            # Announce once per distinct decision — a 1000-archive batch
            # sweep must not print 1000 identical lines.
            _announced_chunkings.add(key)
            import sys

            print(
                f"sweep: running {len(pairs)} pairs in chunks of {chunk} "
                "(full grid would exceed device memory)", file=sys.stderr)

    D = jnp.asarray(D, dtype)
    w0 = jnp.asarray(w0, dtype)
    valid = w0 != 0

    points: list[SweepPoint] = []
    for start in range(0, len(pairs), chunk):
        part = pairs[start:start + chunk]
        cts = jnp.asarray([float(c) for c, _ in part], dtype)
        sts = jnp.asarray([float(s) for _, s in part], dtype)
        test, w_final, loops, done = _sweep_kernel(
            D, w0, valid, cts, sts,
            max_iter=int(cfg.max_iter),
            pulse_region=tuple(cfg.pulse_region),
        )
        w_final = np.asarray(w_final)
        loops = np.asarray(loops)
        done = np.asarray(done)
        points.extend(
            SweepPoint(
                chanthresh=float(c),
                subintthresh=float(s),
                rfi_frac=float((w_final[k] == 0).mean()),
                loops=int(loops[k]),
                converged=bool(done[k]),
                weights=w_final[k] if keep_masks else None,
            )
            for k, (c, s) in enumerate(part)
        )
    return points


def _sweep_via_solo_cleans(
    D: np.ndarray,
    w0: np.ndarray,
    cfg: CleanConfig,
    pairs: list[tuple[float, float]],
    keep_masks: bool,
) -> list[SweepPoint]:
    """>HBM fallback: one solo clean per pair via clean_cube, which routes
    oversized cubes through the sharded/chunked chain.  Semantically
    identical to the batched kernel (a sweep pair IS a solo run with those
    thresholds); only the dispatch shape differs."""
    from iterative_cleaner_tpu.core.cleaner import clean_cube

    points: list[SweepPoint] = []
    for c, s in pairs:
        res = clean_cube(
            D, w0,
            cfg.replace(chanthresh=float(c), subintthresh=float(s)))
        points.append(
            SweepPoint(
                chanthresh=float(c),
                subintthresh=float(s),
                rfi_frac=float((res.weights == 0).mean()),
                loops=res.loops,
                converged=res.converged,
                weights=res.weights if keep_masks else None,
            ))
    return points


def grid(chanthreshs, subintthreshs) -> list[tuple[float, float]]:
    """Full Cartesian grid, channel-major (the order the table prints in)."""
    return [(float(c), float(s)) for c in chanthreshs for s in subintthreshs]


def format_table(points: list[SweepPoint]) -> str:
    lines = ["chanthresh  subintthresh  rfi_frac  loops  converged"]
    for p in points:
        lines.append(
            f"{p.chanthresh:10.3g}  {p.subintthresh:12.3g}  "
            f"{p.rfi_frac:8.4f}  {p.loops:5d}  {str(p.converged):>9s}")
    return "\n".join(lines)


def save_sweep(points: list[SweepPoint], path: str) -> None:
    """All sweep masks + metrics in one NPZ (masks stacked in pair order)."""
    payload = dict(
        chanthresh=np.array([p.chanthresh for p in points], np.float32),
        subintthresh=np.array([p.subintthresh for p in points], np.float32),
        rfi_frac=np.array([p.rfi_frac for p in points], np.float32),
        loops=np.array([p.loops for p in points], np.int32),
        converged=np.array([p.converged for p in points], bool),
    )
    if points and points[0].weights is not None:
        payload["weights"] = np.stack([p.weights for p in points])
    np.savez_compressed(path, **payload)
