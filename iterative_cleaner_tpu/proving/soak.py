"""The ``ict-clean prove`` driver: scenario mix + chaos schedule against
an in-process fleet, one JSON verdict.

The soak stands up a hermetic 2-replica fleet (dormant poll loop, driven
by hand — the test_fleet timing discipline), runs a bounded number of
scenario-mix ticks (:mod:`.scenarios`), proves the duplicate-storm CAS
and trace record→replay dedupe observables, runs the chaos schedule
(:mod:`.chaos`), and prints exactly ONE JSON verdict line on stdout on
EVERY exit path, enforcing the invariant triad:

- **zero lost jobs** — the exactly-once ledger conserves: every external
  submission is either a replica completion, a fleet-cache hit, or an
  idempotent dedupe, and every fleet job read back terminal ``done``;
- **bit-identical masks** — sampled shadow-oracle audits per scenario
  class (one job per class per tick re-cleaned on the numpy oracle and
  compared with ``np.array_equal``);
- **cost conservation** — the device-time ledger stays within
  ``fleet/costs.CONSERVATION_TOLERANCE`` (1%) of the dispatch clock.

Exit code 0 iff the triad holds AND every drill's closed loop
(inject → alert → heal → resolve → books balance) closed.  A budget that
cannot fund the proof (``--job_budget 0``) is a FAIL, not a vacuous pass.
Verdict schema: docs/PROVING.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.fleet import costs as fleet_costs
from iterative_cleaner_tpu.fleet.router import FleetConfig, FleetRouter
from iterative_cleaner_tpu.io.npz import NpzIO
from iterative_cleaner_tpu.obs import events
from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.proving import chaos, scenarios, traces
from iterative_cleaner_tpu.service import CleaningService, ServeConfig
from iterative_cleaner_tpu.service.jobs import TERMINAL
from iterative_cleaner_tpu.utils import tracing

#: Alert rules the soak injects into its router — the chaos drills
#: assert full firing -> resolved cycles against these names
#: (chaos.RULE_REPLICA_DEAD / chaos.RULE_SINK_DEGRADED).
PROVE_RULES = (
    {"name": chaos.RULE_REPLICA_DEAD, "severity": "critical",
     "family": "ict_fleet_replicas", "labels": {"state": "dead"},
     "predicate": {"op": "gt", "value": 0}, "for_ticks": 1,
     "description": "proving ground: a fleet replica is dead/unreachable"},
    {"name": chaos.RULE_SINK_DEGRADED, "severity": "warning",
     "family": "ict_prove_event_sink_degraded",
     "predicate": {"op": "gt", "value": 0}, "for_ticks": 1,
     "description": "proving ground: the JSON-lines event sink is "
                    "dropping events (full disk / unwritable path)"},
)


class ProvingFleet:
    """A hermetic in-process fleet plus the helpers the scenario lane and
    chaos drills share.  Single-threaded driver discipline: every method
    is called from the soak's (or the test's) one thread; the router and
    replicas run their own threads behind their own locks."""

    def __init__(self, workdir: str, seed: int = 0, backend: str = "numpy",
                 replicas: int = 2) -> None:
        self.workdir = workdir  # ict: guarded-by(none: soak driver thread only)
        self.seed = int(seed)  # ict: guarded-by(none: soak driver thread only)
        self.backend = backend  # ict: guarded-by(none: soak driver thread only)
        self.services: list = []  # ict: guarded-by(none: soak driver thread only)
        self.scenario_jobs: dict[str, int] = {}  # ict: guarded-by(none: soak driver thread only)
        self.faults_injected: dict[str, int] = {}  # ict: guarded-by(none: soak driver thread only)
        self.faults_healed: dict[str, int] = {}  # ict: guarded-by(none: soak driver thread only)
        self.submitted_total = 0  # ict: guarded-by(none: soak driver thread only)
        self.verdict_code = 0.0  # ict: guarded-by(none: soak driver thread only)
        self._tag_n = 0  # ict: guarded-by(none: soak driver thread only)
        self._oracle_cache: dict[str, object] = {}  # ict: guarded-by(none: soak driver thread only)
        self.telemetry = os.path.join(workdir, "events.jsonl")  # ict: guarded-by(none: soak driver thread only)
        self._prior_sink = events.configured_sink()  # ict: guarded-by(none: set once during construction)
        self._done_at_start = self._global_done()  # ict: guarded-by(none: set once during construction)
        for _ in range(replicas):
            self._start_service(self.next_tag("replica"))
        self._cost_base = self._cost_sums()  # ict: guarded-by(none: set once during construction)
        self.router = FleetRouter(FleetConfig(  # ict: guarded-by(none: set once during construction)
            replicas=tuple(f"http://127.0.0.1:{s.port}"
                           for s in self.services),
            port=0, poll_interval_s=999.0, dead_after=2, quiet=True,
            retry_backoff_s=0.01, queue_timeout_s=10.0,
            spool_dir=os.path.join(workdir, "router_spool"),
            telemetry=self.telemetry, alert_rules=PROVE_RULES))
        self.router.start()
        self.base_url = f"http://127.0.0.1:{self.router.port}"  # ict: guarded-by(none: set once during construction)

    # --- replica lifecycle ---

    def next_tag(self, prefix: str) -> str:
        self._tag_n += 1
        return f"prove-{prefix}-{self._tag_n}"

    def _start_service(self, tag: str, port: int = 0,
                       spool_dir: str | None = None,
                       deadline_s: float = 0.2,
                       bucket_cap: int = 0) -> CleaningService:
        svc = CleaningService(ServeConfig(
            spool_dir=spool_dir or os.path.join(self.workdir,
                                                f"spool_{tag}"),
            port=port, replica_id=tag, deadline_s=deadline_s,
            bucket_cap=bucket_cap, quiet=True, retry_backoff_s=0.01,
            clean=CleanConfig(backend=self.backend, max_iter=3,
                              quiet=True, no_log=True)))
        svc.start()
        self.services.append(svc)
        return svc

    def new_replica(self, tag: str, port: int = 0,
                    spool_dir: str | None = None,
                    deadline_s: float = 0.2,
                    bucket_cap: int = 0) -> CleaningService:
        """Start one more in-process replica and join it to the fleet
        (registry.add = the autoscaler's scale-up path; not alive until
        its first good poll)."""
        svc = self._start_service(tag, port=port, spool_dir=spool_dir,
                                  deadline_s=deadline_s,
                                  bucket_cap=bucket_cap)
        self.router.registry.add(f"http://127.0.0.1:{svc.port}")
        return svc

    def kill(self, svc: CleaningService) -> None:
        """Stop a replica WITHOUT telling the registry — the crash, not
        the drain: the router must discover the death by poll."""
        svc.stop()
        if svc in self.services:
            self.services.remove(svc)

    def close(self) -> None:
        try:
            self.router.stop()
        finally:
            for svc in list(self.services):
                try:
                    svc.stop()
                except Exception:
                    pass
            self.services.clear()
            # Back to honoring ICT_TELEMETRY (the daemon contract).
            events.configure(self._prior_sink)

    # --- the proving tick: publish gauges, then drive the router ---

    def tick(self) -> None:
        """Publish the ``ict_prove_*`` gauge families onto the router's
        registry, THEN run one poll tick — ``_history_alert_tick`` runs
        inside ``poll_tick``, so rules over prove families always see
        this tick's values, never last tick's."""
        m = self.router.metrics
        m.replace_gauge_family(
            "prove_scenario_jobs",
            {(("scenario", k),): float(v)
             for k, v in self.scenario_jobs.items()})
        m.replace_gauge_family(
            "prove_faults_injected",
            {(("fault", k),): float(v)
             for k, v in self.faults_injected.items()})
        m.replace_gauge_family(
            "prove_faults_healed",
            {(("fault", k),): float(v)
             for k, v in self.faults_healed.items()})
        m.set_gauge("prove_soak_verdict", None, float(self.verdict_code))
        m.set_gauge("prove_event_sink_degraded", None,
                    1.0 if events.sink_degraded() else 0.0)
        self.router.poll_tick()

    # --- submission + settlement ---

    def submit(self, sub: scenarios.Submission, timeout_s: float = 30.0,
               count_scenario: bool = True) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}/jobs",
            data=json.dumps(sub.job_body()).encode(),
            headers={"Content-Type": "application/json",
                     "X-ICT-Tenant": sub.tenant})
        reply = json.load(urllib.request.urlopen(req, timeout=timeout_s))
        self.submitted_total += 1
        if count_scenario:
            self.scenario_jobs[sub.scenario] = (
                self.scenario_jobs.get(sub.scenario, 0) + 1)
        return reply

    def job_state(self, job_id: str, timeout_s: float = 30.0) -> dict:
        return json.load(urllib.request.urlopen(
            f"{self.base_url}/jobs/{job_id}", timeout=timeout_s))

    def await_terminal(self, job_ids: list, timeout_s: float = 180.0) -> dict:
        deadline = time.time() + timeout_s
        states: dict = {}
        while time.time() < deadline:
            self.tick()
            states = {jid: self.job_state(jid) for jid in job_ids}
            if all(s.get("state") in TERMINAL for s in states.values()):
                return states
            time.sleep(0.05)
        raise TimeoutError(
            f"jobs not terminal within {timeout_s}s: "
            f"{ {j: s.get('state') for j, s in states.items()} }")

    # --- the invariant triad's measurement helpers ---

    def oracle_weights(self, path: str):
        """The numpy oracle's weights for one cube — the executable
        spec every served mask must match bit for bit.  Cached per path:
        scenario cubes recur across ticks."""
        if path not in self._oracle_cache:
            from iterative_cleaner_tpu.core.cleaner import clean_cube
            from iterative_cleaner_tpu.ops.preprocess import preprocess
            from iterative_cleaner_tpu.parallel.batch import finalize_weights

            cfg = CleanConfig(backend="numpy", max_iter=3, quiet=True,
                              no_log=True)
            w, _rfi = finalize_weights(
                clean_cube(*preprocess(NpzIO().load(path)), cfg).weights,
                cfg)
            self._oracle_cache[path] = w
        return self._oracle_cache[path]

    def load_weights(self, out_path: str):
        return NpzIO().load(out_path).weights

    def audit_ok(self, sub: scenarios.Submission, state: dict) -> bool:
        return (state.get("state") == "done"
                and bool(state.get("out_path"))
                and np.array_equal(self.load_weights(state["out_path"]),
                                   self.oracle_weights(sub.path)))

    def _global_done(self) -> int:
        return int(tracing.counters_snapshot().get("service_jobs_done", 0))

    def jobs_done(self) -> int:
        """Fleet-wide replica completions SINCE this fleet started (the
        tracing counter is process-global; tests may run fleets
        back-to-back in one process)."""
        return self._global_done() - self._done_at_start

    def ledger(self) -> dict:
        m = self.router.metrics
        done = self.jobs_done()
        cache = int(m.counter_total("fleet_cache_hits_total"))
        deduped = int(m.counter_total("fleet_deduped_submissions_total"))
        return {"submitted": self.submitted_total, "completed": done,
                "cache_hits": cache, "deduped": deduped,
                "lost": self.submitted_total - done - cache - deduped}

    def _cost_sums(self) -> tuple[float, float]:
        """(device-seconds total, dispatch-seconds total) off one
        replica's exposition — in-process replicas share one
        process-global metrics registry, so any one covers the fleet."""
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{self.services[0].port}/metrics",
            timeout=10).read().decode()
        cost_sum = dispatch_sum = 0.0
        for fam in obs_metrics.parse_exposition(text):
            for name, _labels, raw in fam.samples:
                if name == "ict_cost_device_seconds_total":
                    cost_sum += obs_metrics.sample_value(raw)
                elif name == "ict_service_dispatch_s":
                    dispatch_sum += obs_metrics.sample_value(raw)
        return cost_sum, dispatch_sum

    def cost_conservation_ok(self, timeout_s: float = 30.0) -> bool:
        """Device-time ledger vs the dispatch clock, as a DELTA since
        this fleet was built: the registry is process-global, so a
        totals check would inherit (and fail on) whatever residue
        earlier fleets in the same process left behind.  Bounded retry:
        a job turns terminal a beat before the worker finalizes its
        cost record."""
        if not self.services:
            return False
        deadline = time.time() + timeout_s
        cost0, dispatch0 = self._cost_base
        while True:
            cost_sum, dispatch_sum = self._cost_sums()
            cost_sum -= cost0
            dispatch_sum -= dispatch0
            if dispatch_sum <= 0.0:
                return True   # nothing dispatched yet: vacuously conserved
            if (abs(cost_sum / dispatch_sum - 1.0)
                    <= fleet_costs.CONSERVATION_TOLERANCE):
                return True
            if time.time() >= deadline:
                return False
            time.sleep(0.1)


class SoakConfig:
    """Bounded budgets + mode for one proving run."""

    def __init__(self, smoke: bool = False, seed: int = 0,
                 ticks: int | None = None, job_budget: int | None = None,
                 wall_budget_s: float | None = None,
                 backend: str = "numpy", workdir: str = "",
                 quiet: bool = False) -> None:
        self.smoke = smoke
        self.seed = int(seed)
        self.ticks = int(ticks if ticks is not None else (1 if smoke else 3))
        self.job_budget = int(job_budget if job_budget is not None
                              else (64 if smoke else 512))
        self.wall_budget_s = float(wall_budget_s if wall_budget_s is not None
                                   else (300.0 if smoke else 1800.0))
        self.backend = backend
        self.workdir = workdir
        self.quiet = quiet


def _scenario_tick(fleet: ProvingFleet, cfg: SoakConfig, tick_i: int,
                   out: dict) -> None:
    """One scenario-mix tick: submit the seeded mix, settle it, audit one
    job per scenario class against the oracle, and prove the
    duplicate-storm echoes land born-terminal on the fleet cache."""
    mix = scenarios.SMOKE_MIX if cfg.smoke else scenarios.FULL_MIX
    subs = scenarios.build_mix(fleet.workdir, cfg.seed + tick_i * 1_000,
                               mix)
    if fleet.submitted_total + len(subs) > cfg.job_budget:
        raise _BudgetExhausted(
            f"job budget {cfg.job_budget} cannot fund scenario tick "
            f"{tick_i} ({len(subs)} submissions on top of "
            f"{fleet.submitted_total})")
    # build_mix orders the stream [first storm copy, ...rest, echoes]:
    # settle the head first so the router's scrape learns the storm
    # cube's result, then the echoes MUST be cache-served born-terminal.
    echoes = [s for s in subs if s.scenario == "duplicate_storm"][1:]
    head = subs[:len(subs) - len(echoes)]
    head_replies = [fleet.submit(s) for s in head]
    states = fleet.await_terminal([r["id"] for r in head_replies])
    audited: dict[str, bool] = {}
    for s, r in zip(head, head_replies):
        if s.scenario not in audited:   # sampled: one per class per tick
            audited[s.scenario] = fleet.audit_ok(s, states[r["id"]])
    out["audits"].append(audited)
    out["audits_ok"] = out["audits_ok"] and all(audited.values())
    if echoes:
        # Wait for the scrape to learn THIS tick's storm cube (probing
        # len(result_index) would pass vacuously from tick 2 on).
        from iterative_cleaner_tpu.fleet import cache as fleet_cache
        from iterative_cleaner_tpu.ingest import cas

        digest = cas.file_digest(echoes[0].path)
        deadline = time.time() + 60
        while time.time() < deadline:
            salt = fleet_cache.unanimous_salt(
                fleet.router.registry.snapshot())
            if salt and fleet.router.result_index.lookup(digest, salt):
                break
            fleet.tick()
            time.sleep(0.05)
        cache0 = fleet.router.metrics.counter_total("fleet_cache_hits_total")
        done0 = fleet.jobs_done()
        echo_replies = [fleet.submit(s) for s in echoes]
        born_terminal = all(r.get("served_by") == "fleet-cache"
                            and r.get("state") == "done"
                            for r in echo_replies)
        cache_moved = (fleet.router.metrics.counter_total(
            "fleet_cache_hits_total") - cache0 == len(echoes))
        storm_ok = (born_terminal and cache_moved
                    and fleet.jobs_done() == done0)
        out["storm_cas_ok"] = out["storm_cas_ok"] and storm_ok
    fleet.tick()


def _trace_lane(fleet: ProvingFleet, cfg: SoakConfig) -> dict:
    """Record a trace from the soak's own event log and replay it at
    high compression: every replayed submission must dedupe (original
    idempotency keys) — zero new replica work, the dedupe counter moving
    one-for-one."""
    trace_path = os.path.join(fleet.workdir, "prove.trace.jsonl")
    recorded = traces.record_trace(fleet.telemetry, trace_path)
    entries = traces.load_trace(trace_path)
    done0 = fleet.jobs_done()
    dedup0 = fleet.router.metrics.counter_total(
        "fleet_deduped_submissions_total")
    report = traces.replay_trace(entries, fleet.base_url,
                                 compression=1000.0)
    fleet.submitted_total += report["submitted"]
    dedup_delta = int(fleet.router.metrics.counter_total(
        "fleet_deduped_submissions_total") - dedup0)
    ok = (recorded > 0 and not report["errors"]
          and report["submitted"] == len(entries)
          and dedup_delta == len(entries)
          and fleet.jobs_done() == done0)
    return {"ok": ok, "recorded": recorded,
            "replayed": report["submitted"],
            "deduped": dedup_delta, "errors": len(report["errors"]),
            "wall_s": report["wall_s"]}


def _chaos_lane(fleet: ProvingFleet, cfg: SoakConfig,
                wall_deadline: float) -> list[dict]:
    names = chaos.SMOKE_DRILLS if cfg.smoke else tuple(chaos.DRILLS)
    reports = []
    for name in names:
        if time.time() >= wall_deadline:
            reports.append({"fault": name, "ok": False,
                            "detail": "wall budget exhausted before drill"})
            continue
        fleet.faults_injected[name] = fleet.faults_injected.get(name, 0) + 1
        rep = chaos.DRILLS[name](fleet)
        if rep.healed:
            fleet.faults_healed[name] = fleet.faults_healed.get(name, 0) + 1
        fleet.tick()
        reports.append(rep.to_json())
    return reports


class _BudgetExhausted(RuntimeError):
    pass


def run_soak(cfg: SoakConfig) -> int:
    """Run the proving ground; prints exactly ONE JSON verdict line on
    stdout on every exit path; returns 0 iff the proof closed."""
    t0 = time.time()
    verdict: dict = {"prove": "fail",
                     "mode": "smoke" if cfg.smoke else "full",
                     "seed": cfg.seed, "backend": cfg.backend}
    rc = 1
    fleet = None
    workdir = cfg.workdir
    try:
        if cfg.job_budget <= 0:
            raise _BudgetExhausted(
                f"job budget {cfg.job_budget} cannot fund any proof")
        if not workdir:
            workdir = tempfile.mkdtemp(prefix="ict_prove_")
        wall_deadline = t0 + cfg.wall_budget_s
        fleet = ProvingFleet(workdir, seed=cfg.seed, backend=cfg.backend)
        scen: dict = {"audits": [], "audits_ok": True, "storm_cas_ok": True}
        ticks_run = 0
        for i in range(cfg.ticks):
            if time.time() >= wall_deadline:
                break
            _scenario_tick(fleet, cfg, i, scen)
            ticks_run += 1
            if not cfg.quiet:
                print(f"[prove] scenario tick {i + 1}/{cfg.ticks}: "
                      f"{fleet.submitted_total} submitted",
                      file=sys.stderr)
        replay = _trace_lane(fleet, cfg)
        drills = _chaos_lane(fleet, cfg, wall_deadline)
        fleet.tick()
        ledger = fleet.ledger()
        cost_ok = fleet.cost_conservation_ok()
        triad = {
            "zero_lost_jobs": ledger["lost"] == 0 and ticks_run > 0,
            "bit_identical_masks": scen["audits_ok"] and ticks_run > 0,
            "cost_conservation": cost_ok,
        }
        drills_ok = bool(drills) and all(d.get("ok") for d in drills)
        # The trend plane's view of the run (fleet/trends.py; ISSUE 20):
        # a CLEAN soak — steady synthetic traffic, no real slowdown —
        # must not trip the regression sentinel; a firing here means
        # the fingerprint bands are mis-learned (or the fleet genuinely
        # destabilized mid-proof), either of which fails the proof.
        trends_plane = fleet.router.trends
        trends_block = {
            "enabled": trends_plane is not None,
            "ticks": (trends_plane.store.ticks()
                      if trends_plane is not None else 0),
            "series": (trends_plane.store.series_count()
                       if trends_plane is not None else 0),
            "regressions_total": (trends_plane.regressions_total()
                                  if trends_plane is not None else 0),
            "firing": (trends_plane.firing()
                       if trends_plane is not None else []),
        }
        trends_ok = (trends_block["regressions_total"] == 0
                     and not trends_block["firing"])
        ok = (all(triad.values()) and drills_ok and replay["ok"]
              and scen["storm_cas_ok"] and trends_ok)
        rc = 0 if ok else 1
        fleet.verdict_code = 1.0 if ok else 2.0
        fleet.tick()   # final verdict visible on /fleet/metrics
        # The SLI plane's view of the run (fleet/slo.py): the proving
        # traffic drives the derived "admission" journey, so a soak that
        # burned grant-waits shows up here even when the triad closed.
        slo_report = fleet.router.slo.report()
        adm = slo_report["journeys"].get("admission", {})
        verdict.update({
            "slo": {
                "tick": slo_report["tick"],
                "failing_journeys": slo_report["failing_journeys"],
                "admission": {k: adm.get(k) for k in
                              ("availability", "good", "bad")},
            }})
        verdict.update({
            "prove": "pass" if ok else "fail",
            "triad": triad, "jobs": ledger,
            "trends": {**trends_block, "ok": trends_ok},
            "scenario_ticks": ticks_run,
            "scenarios": dict(sorted(fleet.scenario_jobs.items())),
            "storm_cas_ok": scen["storm_cas_ok"],
            "audits": scen["audits"],
            "replay": replay, "drills": drills,
        })
    except _BudgetExhausted as exc:
        verdict["error"] = str(exc)
    except Exception as exc:    # the verdict line still prints
        verdict["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        if fleet is not None:
            try:
                verdict.setdefault("jobs", fleet.ledger())
            except Exception:
                pass
            fleet.close()
        verdict["wall_s"] = round(time.time() - t0, 3)
        verdict["rc"] = rc
        print(json.dumps(verdict))
    return rc


def run_replay(trace_path: str, router_url: str,
               compression: float = 10.0) -> int:
    """``ict-clean prove --replay``: re-issue one recorded trace (a
    sealed flight-recorder segment, or a record_trace output) against a
    LIVE router under the original idempotency keys.  One JSON report
    line on stdout on every exit path; rc 0 when every entry was
    submitted and none errored — whether each deduped is visible in the
    report's dedupe delta (a window the fleet already served must come
    back all-dedupe, zero new replica work)."""
    report: dict = {"trace": trace_path, "router": router_url}
    rc = 1
    try:
        entries = traces.load_trace(trace_path)
        report["entries"] = len(entries)

        def _dedup_total() -> float | None:
            try:
                req = urllib.request.urlopen(
                    f"{router_url.rstrip('/')}/metrics", timeout=10)
                text = req.read().decode()
            except (OSError, ValueError):
                return None
            for fam in obs_metrics.parse_exposition(text):
                if fam.name == "ict_fleet_deduped_submissions_total":
                    return sum(obs_metrics.sample_value(raw)
                               for _n, _l, raw in fam.samples)
            return 0.0

        dedup0 = _dedup_total()
        result = traces.replay_trace(entries, router_url,
                                     compression=compression)
        dedup1 = _dedup_total()
        report.update(result)
        report["dedup_delta"] = (
            None if dedup0 is None or dedup1 is None
            else dedup1 - dedup0)
        rc = 0 if (not result["errors"]
                   and result["submitted"] == len(entries)) else 1
    except (OSError, ValueError) as exc:
        report["error"] = str(exc)
    finally:
        report["rc"] = rc
        print(json.dumps(report))
    return rc


def prove_main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="ict-clean prove",
        description="Run the proving ground: scenario mix + chaos drills "
                    "against a hermetic in-process fleet; one JSON "
                    "verdict line on stdout (docs/PROVING.md).")
    p.add_argument("--smoke", action="store_true",
                   help="the bounded CI lane: one scenario-mix tick, the "
                        "trace replay lane, one replica-kill drill")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ticks", type=int, default=None,
                   help="scenario-mix ticks (default: 1 smoke / 3 full)")
    p.add_argument("--job_budget", type=int, default=None,
                   help="max external submissions (default: 64 smoke / "
                        "512 full); a budget that cannot fund the proof "
                        "is a FAIL")
    p.add_argument("--wall_budget_s", type=float, default=None,
                   help="wall-clock budget (default: 300 smoke / 1800 "
                        "full)")
    p.add_argument("--backend", default="numpy",
                   choices=("numpy", "jax"),
                   help="replica clean backend (default numpy: the "
                        "oracle IS the spec; jax exercises the device "
                        "path)")
    p.add_argument("--workdir", default="",
                   help="working directory (default: a fresh tempdir)")
    p.add_argument("--replay", default="", metavar="TRACE",
                   help="replay ONE recorded trace file (a sealed "
                        "flight-recorder segment, or a record_trace "
                        "output) against --router under its original "
                        "idempotency keys, print a JSON report line, "
                        "and exit — a window the fleet already served "
                        "must dedupe one-for-one")
    p.add_argument("--router", default="http://127.0.0.1:8790",
                   metavar="URL",
                   help="fleet router base URL for --replay "
                        "(default http://127.0.0.1:8790)")
    p.add_argument("--compression", type=float, default=10.0,
                   metavar="X",
                   help="--replay time compression: X times faster than "
                        "recorded (default 10.0)")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    if args.replay:
        return run_replay(args.replay, args.router,
                          compression=args.compression)
    return run_soak(SoakConfig(
        smoke=args.smoke, seed=args.seed, ticks=args.ticks,
        job_budget=args.job_budget, wall_budget_s=args.wall_budget_s,
        backend=args.backend, workdir=args.workdir, quiet=args.quiet))


if __name__ == "__main__":
    sys.exit(prove_main())
