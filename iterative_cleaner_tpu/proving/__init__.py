"""ict-prove: the million-job proving ground (ROADMAP item 6).

The fleet stack measures, alerts, autoscales, dedupes, accounts, and runs
campaigns — this package is what *demonstrates* those control loops
closing under realistic load and injected faults, instead of leaving each
one to its own hand-built smoke:

- :mod:`.traces` — record a replayable submission trace from the
  JSON-lines event log, and re-issue it against a live router at N× time
  compression under the original idempotency keys;
- :mod:`.scenarios` — named, seeded, deterministic synthetic workload
  generators (small-cube floods, big-cube walls, byte-identical duplicate
  storms, mixed-tenant contention, pathological all-RFI archives)
  composable into one mixed stream;
- :mod:`.chaos` — scheduled fault injection with explicit heal
  assertions: every injected fault must surface as a firing alert, heal
  autonomously (failover / traffic re-route / restart-recover), and
  reconcile in the cost ledger;
- :mod:`.soak` — the ``ict-clean prove`` driver: scenario mix + chaos
  schedule against an in-process fleet for a bounded budget, one JSON
  verdict enforcing the invariant triad (zero lost jobs, bit-identical
  masks, cost conservation).

Full docs: ``docs/PROVING.md``.
"""
