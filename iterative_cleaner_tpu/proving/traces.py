"""Submission-trace record/replay (the proving ground's traffic lane).

A *trace* is the replayable distillation of one production window: every
submission that entered the fleet — through a replica's ``POST /jobs``,
through the router (placements AND born-terminal cache hits), or through
the batch CLI — reduced to the fields a re-issue needs.  The recorder
derives it from the JSON-lines event log (``--telemetry`` /
``ICT_TELEMETRY``): since the replay-completeness fix that landed with
this module, every ``job_submitted`` / ``fleet_cache_hit`` event carries
the arrival timestamp, tenant, idempotency key, declared shape + bucket,
and the serving replica's config salt, at all three entry points.

Trace file grammar (JSON lines, one object per line):

- line 1, the header::

    {"kind": "ict-trace", "version": 1, "t0": <abs ts of first entry>,
     "source": "<event log path>", "entries": N}

- lines 2..N+1, one entry each, ordered by arrival time::

    {"t": <seconds since t0>, "path": "...", "tenant": "...",
     "idem_key": "...", "shape": [nsub, nchan, nbin] | [],
     "bucket": "...", "salt": "...", "trace_id": "...",
     "entry": "service" | "cli" | "cache"}

The replayer re-issues the trace against a live router at 1×/N× time
compression **under the original idempotency keys**, so replaying a
window the fleet already served must dedupe end to end (the
``fleet_deduped_submissions_total`` counter moves; ``service_jobs_done``
does not) — the record→replay round-trip regression tests/test_proving.py
pins.  Entries recorded without a key (CLI runs) get a deterministic
``replay:``-prefixed key derived from the trace position, so repeated
replays of one trace file still dedupe against each other.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from dataclasses import asdict, dataclass

TRACE_KIND = "ict-trace"
TRACE_VERSION = 1

#: Events a submission trace is derived from.  ``job_submitted`` is the
#: replica-side acceptance record (CLI runs emit it too, entry="cli");
#: ``fleet_cache_hit`` is the ONLY record of a born-terminal cache-served
#: submission, which never reaches a replica's job_submitted.
_SOURCE_EVENTS = ("job_submitted", "fleet_cache_hit")


@dataclass(frozen=True)
class TraceEntry:
    """One recorded submission, relative to the trace's t0."""

    t: float
    path: str
    tenant: str = ""
    idem_key: str = ""
    shape: tuple = ()
    bucket: str = ""
    salt: str = ""
    trace_id: str = ""
    entry: str = "service"

    def to_json(self) -> dict:
        d = asdict(self)
        d["t"] = round(float(self.t), 6)
        d["shape"] = [int(v) for v in self.shape]
        return d


def _entry_from_event(rec: dict, t0: float) -> TraceEntry | None:
    path = str(rec.get("path", "") or "")
    if not path:
        return None
    shape = rec.get("shape") or []
    if not (isinstance(shape, list)
            and all(isinstance(v, int) for v in shape)):
        shape = []
    return TraceEntry(
        t=max(float(rec.get("ts", t0)) - t0, 0.0),
        path=path,
        tenant=str(rec.get("tenant", "") or ""),
        idem_key=str(rec.get("idem_key", "") or ""),
        shape=tuple(shape),
        bucket=str(rec.get("bucket", "") or ""),
        salt=str(rec.get("cache_salt", "") or ""),
        trace_id=str(rec.get("trace_id", "") or ""),
        entry=("cache" if rec.get("event") == "fleet_cache_hit"
               else str(rec.get("entry", "service") or "service")),
    )


def _event_lines(event_log: str):
    """Yield parsed event dicts from the log, rotated generation first
    (``<path>.1`` precedes ``<path>`` in time — obs/events.py rotation).
    Malformed lines are skipped: the log is append-only JSON lines, and a
    line torn by a crash must not lose the window around it."""
    import os

    for p in (event_log + ".1", event_log):
        if not os.path.exists(p):
            continue
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def record_trace(event_log: str, out_path: str) -> int:
    """Derive a replayable trace from an event log; returns how many
    entries were written.  Submissions are deduplicated by idempotency
    key (a failover re-submits the SAME job to a second replica, which
    emits a second ``job_submitted`` under the same key — one arrival,
    one trace entry) and ordered by arrival timestamp."""
    picked: dict[str, dict] = {}
    anon: list[dict] = []
    for rec in _event_lines(event_log):
        if rec.get("event") not in _SOURCE_EVENTS:
            continue
        if not rec.get("path"):
            continue
        key = str(rec.get("idem_key", "") or "") or str(
            rec.get("job_id", "") or "")
        if key:
            picked.setdefault(key, rec)
        else:
            anon.append(rec)   # CLI runs: no key, every arrival distinct
    events = sorted([*picked.values(), *anon],
                    key=lambda r: float(r.get("ts", 0.0)))
    if not events:
        with open(out_path, "w") as fh:
            fh.write(json.dumps({"kind": TRACE_KIND,
                                 "version": TRACE_VERSION, "t0": 0.0,
                                 "source": event_log, "entries": 0}) + "\n")
        return 0
    t0 = float(events[0].get("ts", 0.0))
    entries = [e for e in (_entry_from_event(rec, t0) for rec in events)
               if e is not None]
    with open(out_path, "w") as fh:
        fh.write(json.dumps({"kind": TRACE_KIND, "version": TRACE_VERSION,
                             "t0": round(t0, 6), "source": event_log,
                             "entries": len(entries)}) + "\n")
        for e in entries:
            fh.write(json.dumps(e.to_json()) + "\n")
    return len(entries)


def load_trace(path: str) -> list[TraceEntry]:
    """Parse + validate a trace file; raises ValueError on anything
    outside the grammar (the trace is an operator-supplied artifact — a
    stale or hand-edited file must fail loudly, not replay garbage)."""
    with open(path) as fh:
        lines = [ln for ln in (raw.strip() for raw in fh) if ln]
    if not lines:
        raise ValueError(f"trace {path!r} is empty (want a header line)")
    try:
        header = json.loads(lines[0])
    except ValueError as exc:
        raise ValueError(f"trace {path!r} header is not JSON: {exc}") from None
    if not isinstance(header, dict) or header.get("kind") != TRACE_KIND:
        raise ValueError(f"trace {path!r} header lacks kind={TRACE_KIND!r}")
    if header.get("version") != TRACE_VERSION:
        raise ValueError(f"trace {path!r} is version "
                         f"{header.get('version')!r}; this reader speaks "
                         f"{TRACE_VERSION}")
    declared = header.get("entries")
    entries: list[TraceEntry] = []
    last_t = 0.0
    for i, line in enumerate(lines[1:], start=2):
        try:
            rec = json.loads(line)
        except ValueError as exc:
            raise ValueError(f"trace {path!r} line {i}: not JSON "
                             f"({exc})") from None
        if not isinstance(rec, dict):
            raise ValueError(f"trace {path!r} line {i}: want an object")
        if not isinstance(rec.get("path"), str) or not rec["path"]:
            raise ValueError(f"trace {path!r} line {i}: missing 'path'")
        t = rec.get("t")
        if not isinstance(t, (int, float)) or t < 0:
            raise ValueError(f"trace {path!r} line {i}: bad 't' {t!r}")
        if float(t) < last_t:
            raise ValueError(f"trace {path!r} line {i}: out of order "
                             f"(t={t} after t={last_t})")
        last_t = float(t)
        shape = rec.get("shape", [])
        if not (isinstance(shape, list)
                and all(isinstance(v, int) and v > 0 for v in shape)):
            raise ValueError(f"trace {path!r} line {i}: bad 'shape' "
                             f"{shape!r}")
        entry = rec.get("entry", "service")
        if entry not in ("service", "cli", "cache"):
            raise ValueError(f"trace {path!r} line {i}: bad 'entry' "
                             f"{entry!r}")
        entries.append(TraceEntry(
            t=float(t), path=rec["path"],
            tenant=str(rec.get("tenant", "") or ""),
            idem_key=str(rec.get("idem_key", "") or ""),
            shape=tuple(shape),
            bucket=str(rec.get("bucket", "") or ""),
            salt=str(rec.get("salt", "") or ""),
            trace_id=str(rec.get("trace_id", "") or ""),
            entry=entry))
    if isinstance(declared, int) and declared != len(entries):
        raise ValueError(f"trace {path!r}: header declares {declared} "
                         f"entries, file has {len(entries)}")
    return entries


def replay_key(e: TraceEntry, index: int) -> str:
    """The idempotency key a replay submits under: the ORIGINAL key when
    one was recorded (the whole point — replaying a served window must
    dedupe), else a deterministic per-position key so repeated replays of
    one trace still dedupe against each other."""
    return e.idem_key or f"replay:{e.trace_id or 'anon'}:{index}"


def replay_trace(entries: list[TraceEntry], base_url: str,
                 compression: float = 1.0, timeout_s: float = 30.0) -> dict:
    """Re-issue a trace against a live router at ``compression``× speed
    (10.0 = ten times faster than recorded).  Returns a report dict:
    submissions attempted/succeeded, per-entry job ids, and collected
    errors (a replay is a measurement run — one refused submission is a
    data point, not an abort)."""
    base = base_url.rstrip("/")
    speed = max(float(compression), 1e-9)
    t_start = time.monotonic()
    job_ids: list[str] = []
    errors: list[str] = []
    submitted = 0
    for i, e in enumerate(entries):
        delay = e.t / speed - (time.monotonic() - t_start)
        if delay > 0:
            time.sleep(delay)
        body = {"path": e.path, "idempotency_key": replay_key(e, i)}
        if len(e.shape) == 3:
            body["shape"] = [int(v) for v in e.shape]
        headers = {"Content-Type": "application/json"}
        if e.tenant:
            headers["X-ICT-Tenant"] = e.tenant
        req = urllib.request.Request(f"{base}/jobs",
                                     data=json.dumps(body).encode(),
                                     headers=headers)
        try:
            row = json.load(urllib.request.urlopen(req, timeout=timeout_s))
            submitted += 1
            jid = str(row.get("id", "") or "")
            if jid:
                job_ids.append(jid)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            errors.append(f"entry {i} ({e.path}): {exc}")
    return {"entries": len(entries), "submitted": submitted,
            "job_ids": job_ids, "errors": errors,
            "compression": speed,
            "wall_s": round(time.monotonic() - t_start, 3)}
