"""Scheduled fault injection with explicit heal assertions.

Every drill follows the same closed-loop contract the fleet claims for
itself (docs/PROVING.md carries the fault → alert → heal → ledger table):

1. **inject** a fault into a live in-process fleet;
2. the fault must surface as a FIRING alert on the router's alert plane;
3. the fleet must heal autonomously (failover re-route, traffic flowing
   around the wedge, restart-recover, sink restore) and the alert must
   RESOLVE;
4. the books must balance afterwards: every submitted job terminal
   exactly once with oracle-identical masks, and the cost ledger still
   conserving against the dispatch clock.

Drills are functions over the duck-typed fleet handle built in
:mod:`.soak` (``ProvingFleet``): a router with a dormant poll loop the
drill drives by hand (``fleet.tick()``), 2+ in-process replicas, and
helpers for submission / terminal-wait / oracle audit / ledger reads.
Each returns a :class:`DrillReport`; ``report.ok`` is the whole contract.
"""

from __future__ import annotations

import glob
import os
import socket
import threading
import time
from dataclasses import dataclass

import numpy as np

from iterative_cleaner_tpu.obs import events
from iterative_cleaner_tpu.proving import scenarios

#: Alert rule names the drills assert against — injected into the
#: router's rule set by soak.PROVE_RULES (names must match there).
RULE_REPLICA_DEAD = "prove_replica_dead"
RULE_SINK_DEGRADED = "prove_event_sink_degraded"


@dataclass
class DrillReport:
    """One drill's closed-loop scorecard."""

    fault: str
    injected: bool = False        # the fault observably took hold
    alert_fired: bool = False     # surfaced on the router's alert plane
    healed: bool = False          # service restored (jobs flow/complete)
    alert_resolved: bool = False  # the alert plane saw the heal too
    masks_ok: bool = False        # mid-drill jobs match the numpy oracle
    ledger_ok: bool = False       # exactly-once completion count held
    cost_ok: bool = False         # cost ledger still conserves post-drill
    detail: str = ""

    @property
    def ok(self) -> bool:
        return (self.injected and self.alert_fired and self.healed
                and self.alert_resolved and self.masks_ok
                and self.ledger_ok and self.cost_ok)

    def to_json(self) -> dict:
        return {"fault": self.fault, "ok": self.ok,
                "injected": self.injected, "alert_fired": self.alert_fired,
                "healed": self.healed,
                "alert_resolved": self.alert_resolved,
                "masks_ok": self.masks_ok, "ledger_ok": self.ledger_ok,
                "cost_ok": self.cost_ok, "detail": self.detail}


def _drill_subs(fleet, tag: str, count: int,
                offset: int) -> list[scenarios.Submission]:
    """Drill-private submissions: cube seeds live in a 900k+ band so no
    drill cube is ever byte-identical to a scenario cube (byte identity
    would let the fleet CAS serve it born-terminal and the drill's job
    would never reach a replica)."""
    out = []
    for i in range(count):
        seed = 900_000 + offset * 1_000 + fleet.seed * 13 + i
        path = scenarios._cube(
            fleet.workdir, f"drill_{tag}_{fleet.seed}_{i}.npz",
            scenarios.SMALL_SHAPE, seed)
        out.append(scenarios.Submission(
            path=path, tenant="chaos",
            idem_key=f"drill:{tag}:{fleet.seed}:{i}",
            shape=scenarios.SMALL_SHAPE, scenario=f"drill_{tag}"))
    return out


def _await_alert(fleet, rule: str, state: str, baseline: int,
                 max_ticks: int = 12, sleep_s: float = 0.05) -> bool:
    """Drive poll ticks until the alert plane records a ``rule`` → state
    transition NEWER than ``baseline`` (a recent()-length snapshot taken
    before injection, so stale transitions from earlier drills never
    satisfy a later one)."""
    for _ in range(max_ticks):
        fleet.tick()
        for rec in fleet.router.alerts.recent()[baseline:]:
            if rec.get("rule") == rule and rec.get("state") == state:
                return True
        time.sleep(sleep_s)
    return False


def _park_on(fleet, victim, victim_tag: str, subs) -> tuple[list, list]:
    """Submit until least-loaded placement has used the victim, then wait
    for the victim to decode and PARK its share (accepted, undispatched —
    the mid-queue death window)."""
    replies = [fleet.submit(s) for s in subs]
    on_victim = [r for r in replies if r.get("replica_id") == victim_tag]
    deadline = time.time() + 60
    while (victim.scheduler.pending_count() < len(on_victim)
           and time.time() < deadline):
        time.sleep(0.02)
    return replies, on_victim


def _settle(fleet, subs, replies, done_before: int) -> tuple[bool, bool]:
    """The post-heal bookkeeping every drill ends with: all jobs terminal
    ``done`` with oracle-identical masks, and the fleet-wide completion
    counter moved by exactly len(subs)."""
    states = fleet.await_terminal([r["id"] for r in replies])
    masks_ok = all(s.get("state") == "done" for s in states.values())
    if masks_ok:
        for sub, r in zip(subs, replies):
            got = states[r["id"]]
            masks_ok = masks_ok and np.array_equal(
                fleet.load_weights(got["out_path"]),
                fleet.oracle_weights(sub.path))
    ledger_ok = (fleet.jobs_done() - done_before == len(subs)
                 and all(s.get("state") == "done"
                         for s in states.values()))
    return masks_ok, ledger_ok


def drill_replica_kill(fleet) -> DrillReport:
    """Kill a replica with jobs parked mid-queue; assert the dead-replica
    alert fires, failover re-routes the parked placements under their
    original idempotency keys, a replacement replica joins, the alert
    resolves, and every job completes exactly once, oracle-identical."""
    rep = DrillReport(fault="replica_kill")
    baseline = len(fleet.router.alerts.recent())
    done0 = fleet.jobs_done()
    tag = fleet.next_tag("victim")
    victim = fleet.new_replica(tag, deadline_s=3600.0, bucket_cap=8)
    fleet.tick()   # first good poll marks the victim alive
    subs = _drill_subs(fleet, "kill", 4, offset=1)
    replies, on_victim = _park_on(fleet, victim, tag, subs)
    victim_url = f"http://127.0.0.1:{victim.port}"
    fleet.tick()   # pre-death scrape: router sees the parked placements
    fleet.kill(victim)
    rep.injected = bool(on_victim)
    rep.alert_fired = _await_alert(
        fleet, RULE_REPLICA_DEAD, "firing", baseline)
    # Heal: a replacement joins on a fresh spool; the dead row leaves the
    # registry (the autoscaler's scale-down path), so the dead gauge
    # returns to 0 and the alert resolves.  NOT the old spool: its parked
    # jobs were already re-routed, and replaying them would double-run.
    fleet.new_replica(fleet.next_tag("heal"))
    fleet.router.registry.remove(victim_url)
    rep.alert_resolved = _await_alert(
        fleet, RULE_REPLICA_DEAD, "resolved", baseline)
    rep.masks_ok, rep.ledger_ok = _settle(fleet, subs, replies, done0)
    rep.healed = rep.ledger_ok and rep.alert_resolved
    rep.cost_ok = fleet.cost_conservation_ok()
    rep.detail = (f"{len(on_victim)}/{len(subs)} jobs parked on the "
                  f"victim at kill time; failovers="
                  f"{fleet.router.metrics.counter_total('fleet_failovers_total')}")
    return rep


class _WedgedBackend:
    """A replica-shaped black hole: the socket ACCEPTS (so the failure
    mode is 'process up, HTTP dead' — a wedged backend, not a down host)
    but every connection is closed before a byte of response, so the
    router's health poll fails instantly instead of burning its
    per-call timeout on every tick."""

    def __init__(self) -> None:
        self._sock = socket.socket()  # ict: guarded-by(none: bound once here; accept loop is the only user after start)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()  # ict: guarded-by(none: threading.Event is internally locked)
        self._thread = threading.Thread(  # ict: guarded-by(none: set once during construction)
            target=self._run, name="ict-prove-wedge", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
                conn.close()
            except OSError:
                return

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def drill_wedged_backend(fleet) -> DrillReport:
    """Join a wedged backend (TCP up, HTTP never answers) to the fleet;
    assert the dead-replica alert fires, traffic keeps flowing around it
    (a never-alive row is never a placement candidate), and scaling the
    wedge out resolves the alert."""
    rep = DrillReport(fault="wedged_backend")
    baseline = len(fleet.router.alerts.recent())
    done0 = fleet.jobs_done()
    wedge = _WedgedBackend()
    url = f"http://127.0.0.1:{wedge.port}"
    try:
        fleet.router.registry.add(url)
        rep.injected = True
        # registry.add = not alive until a good poll it will never give,
        # so the dead gauge goes positive on the next tick.
        rep.alert_fired = _await_alert(
            fleet, RULE_REPLICA_DEAD, "firing", baseline)
        # Service continues mid-fault: one job end to end.
        subs = _drill_subs(fleet, "wedge", 1, offset=2)
        replies = [fleet.submit(s) for s in subs]
        rep.masks_ok, rep.ledger_ok = _settle(fleet, subs, replies, done0)
        # Heal = scale the wedge out (the operator/autoscaler move for a
        # backend that accepts but never serves).
        fleet.router.registry.remove(url)
        rep.alert_resolved = _await_alert(
            fleet, RULE_REPLICA_DEAD, "resolved", baseline)
        rep.healed = rep.ledger_ok and rep.alert_resolved
        rep.cost_ok = fleet.cost_conservation_ok()
        rep.detail = f"wedge at {url} joined, alerted, drained out"
    finally:
        wedge.close()
    return rep


def drill_corrupt_spool(fleet) -> DrillReport:
    """Crash a replica with parked jobs, corrupt EVERY manifest in its
    spool, and restart on the same spool+port; assert the dead window
    fired the alert and re-routed the parked placements, the revived
    replica's recover() skips the corrupt manifests instead of replaying
    them (no double-completion), and the alert resolves on revival."""
    rep = DrillReport(fault="corrupt_spool")
    baseline = len(fleet.router.alerts.recent())
    done0 = fleet.jobs_done()
    tag = fleet.next_tag("spool")
    victim = fleet.new_replica(tag, deadline_s=3600.0, bucket_cap=8)
    fleet.tick()
    subs = _drill_subs(fleet, "spool", 4, offset=3)
    replies, on_victim = _park_on(fleet, victim, tag, subs)
    victim_port = victim.port
    spool_dir = victim.serve_cfg.spool_dir
    fleet.tick()   # pre-death scrape
    fleet.kill(victim)
    manifests = glob.glob(os.path.join(spool_dir, "*.json"))
    for path in manifests:
        with open(path, "w") as fh:
            fh.write("{torn mid-write: not json")
    rep.injected = bool(on_victim) and bool(manifests)
    # >= dead_after ticks while down: the alert fires and the failover
    # sweep re-routes the parked placements under their pinned idem keys.
    rep.alert_fired = _await_alert(
        fleet, RULE_REPLICA_DEAD, "firing", baseline)
    # Heal: revive on the SAME spool and port.  JobSpool.get() treats a
    # garbage manifest as "not a job" (returns None), so recover() skips
    # every corrupted entry — the re-routed copies are the only live ones.
    fleet.new_replica(fleet.next_tag("revived"), port=victim_port,
                      spool_dir=spool_dir)
    rep.alert_resolved = _await_alert(
        fleet, RULE_REPLICA_DEAD, "resolved", baseline)
    rep.masks_ok, rep.ledger_ok = _settle(fleet, subs, replies, done0)
    rep.healed = rep.ledger_ok and rep.alert_resolved
    rep.cost_ok = fleet.cost_conservation_ok()
    rep.detail = (f"corrupted {len(manifests)} manifests; "
                  f"{len(on_victim)}/{len(subs)} parked at crash")
    return rep


def drill_event_sink_full_disk(fleet) -> DrillReport:
    """Break the JSON-lines event sink (the full-disk class: writes to
    the telemetry path start failing); assert the degradation is visible
    as a firing alert via the ``ict_prove_event_sink_degraded`` gauge,
    jobs keep completing losslessly mid-fault (emit never raises — the
    flight ring still mirrors), and restoring the sink resolves it."""
    rep = DrillReport(fault="event_sink_full_disk")
    baseline = len(fleet.router.alerts.recent())
    done0 = fleet.jobs_done()
    good = events.configured_sink()
    blocker = os.path.join(fleet.workdir, "sink_blocker")
    with open(blocker, "w") as fh:
        fh.write("a regular file where a directory must be\n")
    try:
        # Writes now fail with ENOTDIR — same observable as ENOSPC: the
        # sink enters its drop window and sink_degraded() goes true.
        events.configure(os.path.join(blocker, "events.jsonl"))
        events.emit("prove_sink_probe", drill="event_sink_full_disk")
        rep.injected = events.sink_degraded()
        rep.alert_fired = _await_alert(
            fleet, RULE_SINK_DEGRADED, "firing", baseline)
        # Zero loss mid-fault: one job end to end while events drop.
        subs = _drill_subs(fleet, "sink", 1, offset=4)
        replies = [fleet.submit(s) for s in subs]
        rep.masks_ok, rep.ledger_ok = _settle(fleet, subs, replies, done0)
    finally:
        events.configure(good)   # heal: restore the sink
    rep.alert_resolved = _await_alert(
        fleet, RULE_SINK_DEGRADED, "resolved", baseline)
    rep.healed = (not events.sink_degraded()) and rep.alert_resolved
    rep.cost_ok = fleet.cost_conservation_ok()
    rep.detail = "sink wedged via ENOTDIR stand-in for ENOSPC, restored"
    return rep


#: The drill catalog: name -> drill(fleet) -> DrillReport.
DRILLS = {
    "replica_kill": drill_replica_kill,
    "wedged_backend": drill_wedged_backend,
    "corrupt_spool": drill_corrupt_spool,
    "event_sink_full_disk": drill_event_sink_full_disk,
}

#: The CI smoke lane runs exactly one drill (the ~90 s budget).
SMOKE_DRILLS = ("replica_kill",)
