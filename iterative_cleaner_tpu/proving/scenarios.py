"""Named, seeded, deterministic synthetic workload generators.

Each scenario is a generator over :mod:`io/synthetic`'s fully-seeded
archive builder: the same ``(workdir-relative name, seed)`` always yields
byte-identical ``.npz`` cubes and identical submission metadata, so a
proving run is reproducible end to end (the determinism test pins
``same seed → byte-identical cube stream``).  Scenarios compose into one
mixed stream via :func:`build_mix`, which interleaves them with a seeded
shuffle — the arrival ORDER is part of the workload and must reproduce
too.

The catalog (docs/PROVING.md carries the full table):

- ``small_flood`` — many distinct small cubes (the campaign-of-small-jobs
  class the coalescing tier exists for);
- ``big_wall`` — fewer, larger cubes (a different shape bucket, so the
  scheduler's bucketing and the capacity model's per-bucket rows are both
  exercised);
- ``duplicate_storm`` — ONE cube submitted N times under N distinct
  idempotency keys: copies after the first must be served born-terminal
  by the fleet's content-addressed result cache, with the exactly-once
  completion ledger unmoved;
- ``tenant_mix`` — distinct cubes alternating across two tenants (quota /
  weighted-fair-queueing contention under the router's admission plane);
- ``all_rfi`` — pathologically contaminated archives (every injection
  morphology cranked up): the cleaner must converge and the masks must
  still match the numpy oracle bit-for-bit.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

from iterative_cleaner_tpu.io.npz import NpzIO
from iterative_cleaner_tpu.io.synthetic import RFISpec, make_archive

#: The smoke/test small-cube class (the bench/coalesce shape).
SMALL_SHAPE = (4, 16, 64)
#: The big-wall class: a different scheduler bucket, still CI-sized.
BIG_SHAPE = (8, 32, 128)

#: Every injection morphology cranked well past the default mix —
#: the pathological all-RFI class.  Amplitude stays finite so the
#: synthetic pulse is still in there to find.
ALL_RFI_SPEC = RFISpec(n_profile_spikes=24, n_dc_profiles=12,
                       n_bad_channels=5, n_bad_subints=2,
                       n_prezapped=8, amplitude=120.0)


@dataclass(frozen=True)
class Submission:
    """One scenario arrival: everything a fleet submission needs."""

    path: str
    tenant: str
    idem_key: str
    shape: tuple
    scenario: str

    def job_body(self) -> dict:
        return {"path": self.path, "idempotency_key": self.idem_key,
                "shape": list(self.shape)}


def _cube(workdir: str, name: str, shape: tuple, seed: int,
          rfi: RFISpec | None = None) -> str:
    import os

    nsub, nchan, nbin = shape
    path = os.path.join(workdir, name)
    if not os.path.exists(path):   # generators are re-runnable in place
        kw = {"rfi": rfi} if rfi is not None else {}
        NpzIO().save(make_archive(nsub=nsub, nchan=nchan, nbin=nbin,
                                  seed=seed, **kw), path)
    return path


def gen_small_flood(workdir: str, seed: int, count: int) -> list[Submission]:
    return [Submission(
        path=_cube(workdir, f"flood_{seed}_{i}.npz", SMALL_SHAPE, seed + i),
        tenant="flood", idem_key=f"flood:{seed}:{i}",
        shape=SMALL_SHAPE, scenario="small_flood") for i in range(count)]


def gen_big_wall(workdir: str, seed: int, count: int) -> list[Submission]:
    return [Submission(
        path=_cube(workdir, f"wall_{seed}_{i}.npz", BIG_SHAPE,
                   10_000 + seed + i),
        tenant="wall", idem_key=f"wall:{seed}:{i}",
        shape=BIG_SHAPE, scenario="big_wall") for i in range(count)]


def gen_duplicate_storm(workdir: str, seed: int,
                        count: int) -> list[Submission]:
    """ONE cube, ``count`` submissions under DISTINCT idempotency keys:
    the replica-side idempotency map cannot dedupe these — only the
    fleet's content-addressed result cache can, which is the point."""
    path = _cube(workdir, f"storm_{seed}.npz", SMALL_SHAPE, 20_000 + seed)
    return [Submission(
        path=path, tenant="storm", idem_key=f"storm:{seed}:{i}",
        shape=SMALL_SHAPE, scenario="duplicate_storm")
        for i in range(count)]


def gen_tenant_mix(workdir: str, seed: int, count: int) -> list[Submission]:
    return [Submission(
        path=_cube(workdir, f"mix_{seed}_{i}.npz", SMALL_SHAPE,
                   30_000 + seed + i),
        tenant=("mix-a" if i % 2 == 0 else "mix-b"),
        idem_key=f"mix:{seed}:{i}",
        shape=SMALL_SHAPE, scenario="tenant_mix") for i in range(count)]


def gen_all_rfi(workdir: str, seed: int, count: int) -> list[Submission]:
    return [Submission(
        path=_cube(workdir, f"rfi_{seed}_{i}.npz", SMALL_SHAPE,
                   40_000 + seed + i, rfi=ALL_RFI_SPEC),
        tenant="rfi", idem_key=f"rfi:{seed}:{i}",
        shape=SMALL_SHAPE, scenario="all_rfi") for i in range(count)]


#: The scenario catalog: name -> generator(workdir, seed, count).
SCENARIOS = {
    "small_flood": gen_small_flood,
    "big_wall": gen_big_wall,
    "duplicate_storm": gen_duplicate_storm,
    "tenant_mix": gen_tenant_mix,
    "all_rfi": gen_all_rfi,
}

#: One tick of the CI smoke lane: every scenario class represented, the
#: whole mix small enough for the ~90 s budget alongside one chaos drill.
SMOKE_MIX = {"small_flood": 2, "big_wall": 1, "duplicate_storm": 3,
             "tenant_mix": 2, "all_rfi": 1}

#: The full-soak default mix per tick.
FULL_MIX = {"small_flood": 4, "big_wall": 2, "duplicate_storm": 4,
            "tenant_mix": 4, "all_rfi": 2}


def build_mix(workdir: str, seed: int,
              counts: dict[str, int]) -> list[Submission]:
    """Generate each named scenario and interleave them with a seeded
    shuffle — deterministic for a (seed, counts) pair, including arrival
    order.  Unknown scenario names raise (a typo'd mix must not silently
    prove less than it claims)."""
    unknown = sorted(set(counts) - set(SCENARIOS))
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; catalog: "
                         f"{sorted(SCENARIOS)}")
    subs: list[Submission] = []
    for name in sorted(counts):
        n = int(counts[name])
        if n > 0:
            subs.extend(SCENARIOS[name](workdir, seed, n))
    # Seeded interleave; duplicate-storm copies keep their relative order
    # (stable sort on a seeded draw) so "first copy, then the echoes"
    # remains a meaningful phase for the CAS assertion.
    rng = random.Random(seed)
    draws = {id(s): rng.random() for s in subs}
    subs.sort(key=lambda s: (draws[id(s)], s.idem_key))
    storm = [s for s in subs if s.scenario == "duplicate_storm"]
    if storm:
        rest = [s for s in subs if s.scenario != "duplicate_storm"]
        first = min(storm, key=lambda s: s.idem_key)
        subs = [first, *rest, *[s for s in storm if s is not first]]
    return subs


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def mix_digest(subs: list[Submission]) -> str:
    """One hex digest over the whole stream — cube bytes AND submission
    metadata in arrival order — the determinism test's single figure."""
    h = hashlib.sha256()
    for s in subs:
        h.update(f"{s.scenario}|{s.tenant}|{s.idem_key}|{s.shape}|"
                 f"{file_digest(s.path)}\n".encode())
    return h.hexdigest()


def campaign_manifest(subs: list[Submission], name: str,
                      tenant: str = "prove-survey") -> dict:
    """A ``POST /campaigns`` body over a scenario stream: campaigns as a
    workload source (the orchestrator pins its own per-archive
    idempotency keys, so the stream's keys are not carried over)."""
    return {"name": name, "tenant": tenant,
            "archives": [s.path for s in subs],
            "config": {"lane": "ict-clean prove"}}
