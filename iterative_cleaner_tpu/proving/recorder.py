"""Production flight recorder: every *real* submission, always on tape.

The proving ground can already replay a trace (proving/traces.py), but
until this module the only traces were the soak's own — ROADMAP item 6
left "replaying *production* traces" open.  The recorder closes it from
the router side: every submission the router places (fresh placements
AND born-terminal fleet-cache hits) is appended, as it happens, to a
bounded, rotated set of **segments** in the exact PR-17 versioned trace
grammar, so any production window is a replayable artifact the moment
its segment seals — ``ict-clean prove --replay <segment>`` re-issues it
under the original idempotency keys and must dedupe one-for-one with
zero new replica work.

Discipline (mirrors obs/events.py rotation + fleet/obs.py bundles):

- **synthetic traffic is excluded by construction** — canary probes and
  soak-synthetic submissions arrive with ``synthetic: true`` / the
  ``_canary`` tenant (place_job normalizes both into each other), and
  :meth:`FlightRecorder.record` refuses them before any byte is written
  (counted on ``ict_recorder_excluded_total``).  A sealed segment can
  never contain a probe.
- **durable open segment** — entries append to ``open.trace.part`` (one
  JSON line each, absolute timestamps) so a crash loses at most the
  torn last line; a restarted recorder re-adopts the part file and the
  window survives the process.
- **size-capped rotation, atomic sealing** — when the open segment
  crosses ``max_segment_kb`` it seals: the final grammar file (header
  line + time-relative entries, loadable by ``traces.load_trace``
  unchanged) is written to a ``.part`` sibling and ``os.replace``d into
  ``seg-NNNNNN.trace.jsonl``; readers never see a half segment.
- **bounded keep** — beyond ``keep`` sealed segments the oldest are
  swept (the incident-bundle MAX_INCIDENTS_KEPT idiom): the recorder is
  a flight recorder, not an archive.
- **never in the serving path's way** — a failed append is counted
  (``ict_recorder_dropped_total``) and swallowed; recording must never
  turn a placement into a 500.

The recorder owns ONE lock, acquired strictly after the router's (the
router -> subsystem order); it performs only local file appends under
it, never HTTP.
"""

from __future__ import annotations

import json
import os
import threading
import time

from iterative_cleaner_tpu.proving import traces

#: Open-segment journal (absolute-timestamp JSON lines) and the sealed
#: segment name grammar.  The ``.part`` suffix keeps the open journal
#: (and the seal-in-progress temp file) invisible to the inventory.
OPEN_PART = "open.trace.part"
SEGMENT_FMT = "seg-{seq:06d}.trace.jsonl"
SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".trace.jsonl"


def _is_segment(name: str) -> bool:
    return (name.startswith(SEGMENT_PREFIX)
            and name.endswith(SEGMENT_SUFFIX))


class FlightRecorder:
    """Router-side production submission recorder (one per router)."""

    def __init__(self, out_dir: str, max_segment_kb: int = 256,
                 keep: int = 16, enabled: bool = True,
                 quiet: bool = True) -> None:
        self.out_dir = out_dir
        self.max_segment_bytes = max(int(max_segment_kb), 1) * 1024
        self.keep = max(int(keep), 1)
        self.enabled = bool(enabled)
        self.quiet = quiet
        self._lock = threading.Lock()
        # The open segment's entries, in arrival order: dicts carrying
        # the absolute ``ts`` plus every TraceEntry field — relativized
        # against the segment's t0 only at seal time.
        self._open: list[dict] = []  # ict: guarded-by(self._lock)
        self._open_bytes = 0  # ict: guarded-by(self._lock)
        self._seq = 0  # next sealed-segment sequence number  # ict: guarded-by(self._lock)
        self._entries_total = 0  # ict: guarded-by(self._lock)
        self._excluded_total = 0  # ict: guarded-by(self._lock)
        self._dropped_total = 0  # ict: guarded-by(self._lock)
        self._sealed_total = 0  # ict: guarded-by(self._lock)
        if self.enabled:
            os.makedirs(self.out_dir, exist_ok=True)
            self._adopt_existing()

    # --- init recovery ------------------------------------------------

    def _adopt_existing(self) -> None:
        """Resume a predecessor's state: continue the sealed sequence
        past the highest existing segment and re-adopt its open-segment
        journal (the crash-durability half of the ``.part`` append).
        The directory scan and journal read run unlocked (init-only, no
        concurrency yet); the state commit takes the lock."""
        try:
            names = sorted(n for n in os.listdir(self.out_dir)
                           if _is_segment(n))
        except OSError:
            names = []
        next_seq = 0
        for name in names:
            try:
                next_seq = max(
                    next_seq,
                    int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]) + 1)
            except ValueError:
                continue
        part = os.path.join(self.out_dir, OPEN_PART)
        adopted: list[dict] = []
        part_bytes = 0
        try:
            if os.path.exists(part):
                with open(part) as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue  # the torn last line of a crash
                        if isinstance(rec, dict) and rec.get("path"):
                            adopted.append(rec)
                part_bytes = os.path.getsize(part)
        except OSError:
            adopted = []
            part_bytes = 0
        with self._lock:
            self._seq = max(self._seq, next_seq)
            self._open.extend(adopted)
            self._open_bytes = part_bytes

    # --- the hot path -------------------------------------------------

    def record(self, *, path: str, tenant: str = "", idem_key: str = "",
               shape=(), bucket: str = "", salt: str = "",
               trace_id: str = "", entry: str = "service",
               synthetic: bool = False, ts: float | None = None) -> bool:
        """Append one real submission to the open segment.  Returns True
        when the entry landed on tape; synthetic traffic is refused here
        (excluded by construction — not filtered at seal time), and any
        failure is counted and swallowed, never raised into the
        placement path."""
        if synthetic or tenant == "_canary":
            with self._lock:
                self._excluded_total += 1
            return False
        if not self.enabled:
            with self._lock:
                self._dropped_total += 1
            return False
        rec = {
            "ts": round(float(time.time() if ts is None else ts), 6),
            "path": str(path), "tenant": str(tenant or ""),
            "idem_key": str(idem_key or ""),
            "shape": [int(v) for v in (shape or ())],
            "bucket": str(bucket or ""), "salt": str(salt or ""),
            "trace_id": str(trace_id or ""),
            "entry": entry if entry in ("service", "cli", "cache")
            else "service",
        }
        line = json.dumps(rec) + "\n"
        with self._lock:
            try:
                with open(os.path.join(self.out_dir, OPEN_PART), "a") as fh:
                    fh.write(line)
            except OSError:
                self._dropped_total += 1
                return False
            self._open.append(rec)
            self._open_bytes += len(line)
            self._entries_total += 1
            roll = self._open_bytes >= self.max_segment_bytes
        if roll:
            self.seal()
        return True

    # --- rotation -----------------------------------------------------

    def seal(self) -> str | None:
        """Seal the open segment into the next ``seg-NNNNNN`` grammar
        file (atomic ``.part`` -> ``os.replace``); returns its path, or
        None when there was nothing to seal.  Public so the smoke (and
        an operator export) can close a window on demand."""
        with self._lock:
            if not self.enabled or not self._open:
                return None
            pending = self._open
            self._open = []
            self._open_bytes = 0
            seq = self._seq
            self._seq += 1
        t0 = float(pending[0]["ts"])
        final = os.path.join(self.out_dir, SEGMENT_FMT.format(seq=seq))
        tmp = final + ".part"
        try:
            with open(tmp, "w") as fh:
                fh.write(json.dumps({
                    "kind": traces.TRACE_KIND,
                    "version": traces.TRACE_VERSION,
                    "t0": round(t0, 6), "source": "fleet-recorder",
                    "entries": len(pending)}) + "\n")
                last_t = 0.0
                for rec in pending:
                    e = traces.TraceEntry(
                        # Clamp monotone: wall clocks can step backward
                        # and load_trace requires ordered t.
                        t=max(float(rec["ts"]) - t0, last_t),
                        path=rec["path"], tenant=rec.get("tenant", ""),
                        idem_key=rec.get("idem_key", ""),
                        shape=tuple(rec.get("shape") or ()),
                        bucket=rec.get("bucket", ""),
                        salt=rec.get("salt", ""),
                        trace_id=rec.get("trace_id", ""),
                        entry=rec.get("entry", "service"))
                    last_t = e.t
                    fh.write(json.dumps(e.to_json()) + "\n")
            os.replace(tmp, final)
            try:
                os.remove(os.path.join(self.out_dir, OPEN_PART))
            except OSError:
                pass
        except OSError:
            # The window stays on the open journal; next seal retries.
            with self._lock:
                self._dropped_total += len(pending)
            return None
        with self._lock:
            self._sealed_total += 1
        self._sweep()
        return final

    def _sweep(self) -> None:
        """Drop the oldest sealed segments beyond ``keep`` (sequence
        numbers ARE age: the name sort is the time sort)."""
        try:
            names = sorted(n for n in os.listdir(self.out_dir)
                           if _is_segment(n))
        except OSError:
            return
        for name in names[:-self.keep] if len(names) > self.keep else []:
            try:
                os.remove(os.path.join(self.out_dir, name))
            except OSError:
                pass

    # --- read side ----------------------------------------------------

    def segments(self) -> list[dict]:
        """Inventory of sealed segments, oldest first: name/path/bytes
        plus the header's t0 and entry count (each file is read for its
        header line only)."""
        if not self.enabled and not os.path.isdir(self.out_dir):
            return []
        try:
            names = sorted(n for n in os.listdir(self.out_dir)
                           if _is_segment(n))
        except OSError:
            return []
        rows = []
        for name in names:
            path = os.path.join(self.out_dir, name)
            row = {"name": name, "path": path, "bytes": 0,
                   "t0": 0.0, "entries": 0}
            try:
                row["bytes"] = os.path.getsize(path)
                with open(path) as fh:
                    header = json.loads(fh.readline())
                row["t0"] = float(header.get("t0", 0.0))
                row["entries"] = int(header.get("entries", 0))
            except (OSError, ValueError, TypeError):
                continue  # a segment mid-replace; the next scrape sees it
            rows.append(row)
        return rows

    def export(self, segment: str = "", t_start: float | None = None,
               t_end: float | None = None) -> list[dict]:
        """A replayable trace document as a list of JSON-line objects
        (header first) — written one ``json.dumps`` per element, the
        result IS a valid trace file for ``traces.load_trace``.

        ``segment`` names one sealed segment verbatim; otherwise every
        sealed entry whose ABSOLUTE arrival time falls in
        ``[t_start, t_end]`` (open bounds when None) is merged, in
        order, under a fresh header.  Raises KeyError for an unknown
        segment name."""
        if segment:
            if not _is_segment(segment) or os.sep in segment:
                raise KeyError(segment)
            path = os.path.join(self.out_dir, segment)
            if not os.path.exists(path):
                raise KeyError(segment)
            with open(path) as fh:
                return [json.loads(ln) for ln in fh if ln.strip()]
        picked: list[tuple[float, dict]] = []
        for row in self.segments():
            try:
                entries = traces.load_trace(row["path"])
            except (OSError, ValueError):
                continue
            for e in entries:
                abs_t = row["t0"] + e.t
                if t_start is not None and abs_t < t_start:
                    continue
                if t_end is not None and abs_t > t_end:
                    continue
                picked.append((abs_t, e.to_json()))
        picked.sort(key=lambda p: p[0])
        t0 = picked[0][0] if picked else 0.0
        out = [{"kind": traces.TRACE_KIND,
                "version": traces.TRACE_VERSION, "t0": round(t0, 6),
                "source": "fleet-recorder-window",
                "entries": len(picked)}]
        last_t = 0.0
        for abs_t, rec in picked:
            rec = dict(rec)
            rec["t"] = round(max(abs_t - t0, last_t), 6)
            last_t = rec["t"]
            out.append(rec)
        return out

    def stats(self) -> dict:
        """One snapshot for gauges, /fleet/traces, and fleet_top."""
        rows = self.segments()
        with self._lock:
            return {
                "enabled": self.enabled,
                "segments": len(rows),
                "segment_bytes": sum(r["bytes"] for r in rows),
                "open_entries": len(self._open),
                "open_bytes": self._open_bytes,
                "entries_total": self._entries_total,
                "excluded_total": self._excluded_total,
                "dropped_total": self._dropped_total,
                "sealed_total": self._sealed_total,
            }
