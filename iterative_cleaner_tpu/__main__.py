from iterative_cleaner_tpu.cli import main

raise SystemExit(main())
