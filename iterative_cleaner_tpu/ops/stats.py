"""The comprehensive-stats kernel in JAX — hot loop #2, TPU-resident.

Re-designs the reference's ``comprehensive_stats`` + per-row/column scaler
loops (iterative_cleaner.py:180-255; SURVEY.md §3.4) as fused array ops: the
O(nchan + nsub) Python loop bodies become two batched sort-based masked
medians, and the four diagnostics become reductions + one batched rfft along
the bin axis (XLA FFT on the TPU).

The numpy.ma landmines are reproduced with explicit value+validity
arithmetic; the exact scaled-value rules (verified empirically against
numpy 2.0.2, tests/test_landmines.py + tests/test_equivalence.py):

masked diagnostics (std / mean / ptp — "type A" scaling):
  valid entry, MAD != 0 : |x − med| / MAD / thresh
  valid entry, MAD == 0 : |x − med|          (masked division leaves the
                                              numerator; abs still applies;
                                              the /thresh skips masked data)
  masked entry          : |x|                (raw garbage data: 0.0 for
                                              std/mean, 1e20 for ptp — the
                                              MaskedArray fill value)
plain diagnostic (max |rfft| — "type B", mask-blind per §8.L1):
  IEEE throughout: (x − med)/MAD with MAD == 0 gives ±inf / NaN.

Downstream of the scalers the masks are gone (mask-drop at the max step,
§8.L2): element-wise max of the channel/subint scalings, then a NaN-
propagating median across the four diagnostics.  NaN ≥ 1 is False, so
fully-masked profiles are never flagged (§8.L3).
"""

from __future__ import annotations

import jax.numpy as jnp

from iterative_cleaner_tpu.ops.masked import masked_median, nan_propagating_median

# numpy.ma's default float fill value — the raw data np.ma.ptp leaves at
# fully-masked positions (only reachable for already-zapped profiles).
MA_FILL = 1e20


def diagnostics(weighted: jnp.ndarray, valid: jnp.ndarray):
    """The four per-profile outlier diagnostics along the bin axis.

    weighted: (nsub, nchan, nbin) residuals pre-scaled by the original
    weights; valid: (nsub, nchan) = w0 != 0.  Profiles are entirely valid or
    entirely masked (the mask comes from per-profile weights), so the masked
    reductions collapse to plain reductions + a fill at masked profiles.
    """
    mean = jnp.mean(weighted, axis=-1)
    centred = weighted - mean[..., None]
    std = jnp.sqrt(jnp.mean(centred * centred, axis=-1))
    ptp = jnp.max(weighted, axis=-1) - jnp.min(weighted, axis=-1)
    # Mask-blind FFT diagnostic (§8.L1): masked profiles were pre-zeroed by
    # the weight scaling, and the masked mean's raw data is 0.0, so they
    # contribute exactly |rfft(0)| = 0.
    fft_diag = fft_diagnostic(centred)
    d_mean, d_std, d_ptp = fill_moments(mean, std, ptp, valid)
    return d_std, d_mean, d_ptp, fft_diag


def fill_moments(mean, std, ptp, valid):
    """numpy.ma raw-data fills at fully-masked profiles: 0.0 for std/mean
    (masked reductions), 1e20 for ptp (the MaskedArray fill value).
    Returns in argument order: (mean, std, ptp)."""
    return (jnp.where(valid, mean, 0.0), jnp.where(valid, std, 0.0),
            jnp.where(valid, ptp, MA_FILL))


def comprehensive_stats_from_moments(
    centred, mean, std, ptp, valid, chanthresh: float, subintthresh: float
) -> jnp.ndarray:
    """The stats tail for the Pallas-fused path: the kernel already produced
    the centred cube and raw moments (ops/pallas_kernels.py); only the XLA
    FFT diagnostic, the fills, and the robust scalers remain."""
    d_mean, d_std, d_ptp = fill_moments(mean, std, ptp, valid)
    return scale_and_combine(
        d_std, d_mean, d_ptp, fft_diagnostic(centred), valid,
        chanthresh, subintthresh)


def scale_masked(diag: jnp.ndarray, valid: jnp.ndarray, axis: int, thresh: float):
    """Type-A robust scaling along ``axis`` with numpy.ma leak semantics.

    Returns the final |scaled|/thresh *data* (plain array — the caller is
    downstream of the mask-drop).
    """
    med, n = masked_median(diag, valid, axis=axis)
    has = n > 0
    med_b = jnp.expand_dims(med, axis)
    has_b = jnp.expand_dims(has, axis)
    r = diag - med_b
    mad, _ = masked_median(jnp.abs(r), valid, axis=axis)
    mad_ok = has & (mad != 0) & ~jnp.isnan(mad)
    mad_ok_b = jnp.expand_dims(mad_ok, axis)
    mad_b = jnp.expand_dims(jnp.where(mad_ok, mad, 1.0), axis)
    # Two-division op order matches the reference: (r/MAD), abs, /thresh.
    scaled_ok = jnp.abs(r / mad_b) / thresh
    scaled_valid = jnp.where(mad_ok_b, scaled_ok, jnp.abs(r))
    return jnp.where(valid & has_b, scaled_valid, jnp.abs(diag))


def scale_plain(diag: jnp.ndarray, axis: int, thresh: float):
    """Type-B scaling: plain IEEE arithmetic, no mask anywhere (§8.L1)."""
    med = nan_propagating_median(diag, axis=axis)
    r = diag - jnp.expand_dims(med, axis)
    mad = nan_propagating_median(jnp.abs(r), axis=axis)
    return jnp.abs(r / jnp.expand_dims(mad, axis)) / thresh


def comprehensive_stats(
    weighted: jnp.ndarray,
    valid: jnp.ndarray,
    chanthresh: float,
    subintthresh: float,
) -> jnp.ndarray:
    """weighted residual cube → per-profile outlier score (plain array).

    axis=0 scaling compares a profile against others in the same *channel*
    (across subints, / chanthresh); axis=1 against the same *subint* (across
    channels, / subintthresh) — reference iterative_cleaner.py:221-223.
    """
    d_std, d_mean, d_ptp, d_fft = diagnostics(weighted, valid)
    return scale_and_combine(
        d_std, d_mean, d_ptp, d_fft, valid, chanthresh, subintthresh)


def fft_diagnostic(centred: jnp.ndarray) -> jnp.ndarray:
    """max |rfft| over the bin axis of the centred residuals — the mask-blind
    diagnostic #4 (§8.L1); shared by the XLA and Pallas-fused paths."""
    return jnp.max(jnp.abs(jnp.fft.rfft(centred, axis=-1)), axis=-1)


def scale_and_combine(
    d_std, d_mean, d_ptp, d_fft, valid, chanthresh: float, subintthresh: float
) -> jnp.ndarray:
    """Robust-scale the four diagnostics and combine (reference :220-224).

    The three type-A diagnostics are stacked so each axis needs ONE sort of a
    (3, nsub, nchan) array instead of three separate sorts — r03 phase
    telemetry put the scalers at ~44% of the device step, dominated by sort
    launches.  Rows sort independently, so the batched medians are
    bit-identical to the per-diagnostic ones.
    """
    stacked = jnp.stack((d_std, d_mean, d_ptp), axis=0)
    valid3 = jnp.broadcast_to(valid, stacked.shape)
    # 2-D axis=0 (across subints, /chanthresh) == stacked axis=1; 2-D axis=1
    # (across channels, /subintthresh) == stacked axis=2.
    per_chan = scale_masked(stacked, valid3, axis=1, thresh=chanthresh)
    per_subint = scale_masked(stacked, valid3, axis=2, thresh=subintthresh)
    combined = jnp.maximum(per_chan, per_subint)  # mask-drop (§8.L2)
    fft_combined = jnp.maximum(
        scale_plain(d_fft, axis=0, thresh=chanthresh),
        scale_plain(d_fft, axis=1, thresh=subintthresh),
    )
    return nan_propagating_median(
        jnp.concatenate((combined, fft_combined[None]), axis=0), axis=0)
