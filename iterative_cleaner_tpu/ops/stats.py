"""The comprehensive-stats kernel in JAX — hot loop #2, TPU-resident.

Re-designs the reference's ``comprehensive_stats`` + per-row/column scaler
loops (iterative_cleaner.py:180-255; SURVEY.md §3.4) as fused array ops: the
O(nchan + nsub) Python loop bodies become two batched sort-based masked
medians, and the four diagnostics become reductions + one batched rfft along
the bin axis (XLA FFT on the TPU).

The numpy.ma landmines are reproduced with explicit value+validity
arithmetic; the exact scaled-value rules (verified empirically against
numpy 2.0.2, tests/test_landmines.py + tests/test_equivalence.py):

masked diagnostics (std / mean / ptp — "type A" scaling):
  valid entry, MAD != 0 : |x − med| / MAD / thresh
  valid entry, MAD == 0 : |x − med|          (masked division leaves the
                                              numerator; abs still applies;
                                              the /thresh skips masked data)
  masked entry          : |x|                (raw garbage data: 0.0 for
                                              std/mean, 1e20 for ptp — the
                                              MaskedArray fill value)
plain diagnostic (max |rfft| — "type B", mask-blind per §8.L1):
  IEEE throughout: (x − med)/MAD with MAD == 0 gives ±inf / NaN.

Downstream of the scalers the masks are gone (mask-drop at the max step,
§8.L2): element-wise max of the channel/subint scalings, then a NaN-
propagating median across the four diagnostics.  NaN ≥ 1 is False, so
fully-masked profiles are never flagged (§8.L3).
"""

from __future__ import annotations

import string

import jax
import jax.numpy as jnp

from iterative_cleaner_tpu.ops.masked import (
    masked_median,
    median4_nonneg,
    median_select_mode,
    nan_propagating_median,
    sort_prefix,
)

# numpy.ma's default float fill value — the raw data np.ma.ptp leaves at
# fully-masked positions (only reachable for already-zapped profiles).
MA_FILL = 1e20


def diagnostics(weighted: jnp.ndarray, valid: jnp.ndarray):
    """The four per-profile outlier diagnostics along the bin axis.

    weighted: (nsub, nchan, nbin) residuals pre-scaled by the original
    weights; valid: (nsub, nchan) = w0 != 0.  Profiles are entirely valid or
    entirely masked (the mask comes from per-profile weights), so the masked
    reductions collapse to plain reductions + a fill at masked profiles.
    """
    mean = jnp.mean(weighted, axis=-1)
    centred = weighted - mean[..., None]
    std = jnp.sqrt(jnp.mean(centred * centred, axis=-1))
    ptp = jnp.max(weighted, axis=-1) - jnp.min(weighted, axis=-1)
    # Mask-blind FFT diagnostic (§8.L1): masked profiles were pre-zeroed by
    # the weight scaling, and the masked mean's raw data is 0.0, so they
    # contribute exactly |rfft(0)| = 0.
    fft_diag = fft_diagnostic(centred)
    d_mean, d_std, d_ptp = fill_moments(mean, std, ptp, valid)
    return d_std, d_mean, d_ptp, fft_diag


def fill_moments(mean, std, ptp, valid):
    """numpy.ma raw-data fills at fully-masked profiles: 0.0 for std/mean
    (masked reductions), 1e20 for ptp (the MaskedArray fill value).
    Returns in argument order: (mean, std, ptp)."""
    return (jnp.where(valid, mean, 0.0), jnp.where(valid, std, 0.0),
            jnp.where(valid, ptp, MA_FILL))


def scale_masked(diag: jnp.ndarray, valid: jnp.ndarray, axis: int, thresh: float):
    """Type-A robust scaling along ``axis`` with numpy.ma leak semantics.

    Returns the final |scaled|/thresh *data* (plain array — the caller is
    downstream of the mask-drop).  Reference implementation of the rule
    table above; the production path is the batched :func:`_scale_axis`,
    which must stay bit-identical to this.
    """
    med, n = masked_median(diag, valid, axis=axis)
    has = n > 0
    med_b = jnp.expand_dims(med, axis)
    has_b = jnp.expand_dims(has, axis)
    r = diag - med_b
    mad, _ = masked_median(jnp.abs(r), valid, axis=axis)
    mad_ok = has & (mad != 0) & ~jnp.isnan(mad)
    mad_ok_b = jnp.expand_dims(mad_ok, axis)
    mad_b = jnp.expand_dims(jnp.where(mad_ok, mad, 1.0), axis)
    # Two-division op order matches the reference: (r/MAD), abs, /thresh.
    scaled_ok = jnp.abs(r / mad_b) / thresh
    scaled_valid = jnp.where(mad_ok_b, scaled_ok, jnp.abs(r))
    return jnp.where(valid & has_b, scaled_valid, jnp.abs(diag))


def scale_plain(diag: jnp.ndarray, axis: int, thresh: float):
    """Type-B scaling: plain IEEE arithmetic, no mask anywhere (§8.L1).
    Reference implementation; the production path is :func:`_scale_axis`."""
    med = nan_propagating_median(diag, axis=axis)
    r = diag - jnp.expand_dims(med, axis)
    mad = nan_propagating_median(jnp.abs(r), axis=axis)
    return jnp.abs(r / jnp.expand_dims(mad, axis)) / thresh


def _select_medians(filled: jnp.ndarray, n: jnp.ndarray, ax3: int):
    """Per-row medians of a (4, nsub, nchan) stack along ``ax3``, ONE sort.

    Rows 0-2 carry +inf at invalid positions and use count-based selection
    with even-count averaging (np.ma.median semantics; NaN when ``n`` — the
    per-line valid count — is 0).  Row 3 carries raw values and uses plain
    np.median semantics: static middle pair, NaN if any NaN is present in
    the row along the axis.

    This full-sort form is the REFERENCE lowering (and the oracle for
    tests/test_selection_medians.py); the production `_scale_axis` goes
    through :func:`_select_medians_via`, which swaps the sort for a
    bit-identical k-th order-statistic selection when the platform's
    ``median_select_mode()`` says so.
    """
    return _select_medians_via(filled, n, ax3, mode="sort")


def _select_medians_topk(filled: jnp.ndarray, n: jnp.ndarray, ax3: int):
    """The selection lowering of :func:`_select_medians` — forced ``topk``
    regardless of platform (the TPU production path; the bit-identity
    property suite runs it on the CPU harness)."""
    return _select_medians_via(filled, n, ax3, mode="topk")


def _select_medians_via(filled: jnp.ndarray, n: jnp.ndarray, ax3: int,
                        mode: str | None = None):
    """Shared body of the two lowerings above.  Every selected position
    (lo = (n−1)//2, hi = n//2, and row 3's static middle pair) sits inside
    the first ``size//2 + 1`` ascending elements, so only that prefix is
    materialised — a full sort under ``mode="sort"``, a ``lax.top_k``
    selection over total-order keys under ``mode="topk"`` (bit-identical
    by element selection: ops/masked.sort_prefix)."""
    size = filled.shape[ax3]
    x = jnp.moveaxis(filled, ax3, -1)            # (4, A, size)
    srt = sort_prefix(x, size // 2 + 1, mode=mode)
    lo = jnp.clip((n - 1) // 2, 0, size - 1)     # (A,)
    hi = jnp.clip(n // 2, 0, size - 1)
    idx = jnp.stack((lo, hi), axis=-1)[None]     # (1, A, 2)
    pair = jnp.take_along_axis(srt[:3], jnp.broadcast_to(idx, (3,) + idx.shape[1:]),
                               axis=-1)
    med_masked = jnp.where(n > 0, jnp.sum(pair, axis=-1) * 0.5, jnp.nan)
    mid = (srt[3, ..., (size - 1) // 2] + srt[3, ..., size // 2]) * 0.5
    med_plain = jnp.where(jnp.isnan(x[3]).any(axis=-1), jnp.nan, mid)
    return jnp.concatenate((med_masked, med_plain[None]), axis=0)  # (4, A)


def _scale_axis(stack4: jnp.ndarray, valid: jnp.ndarray,
                axis: int, thresh: float) -> jnp.ndarray:
    """All four diagnostics robust-scaled along 2-D ``axis`` — the batched
    production form of :func:`scale_masked` (rows 0-2) + :func:`scale_plain`
    (row 3), two median selections over a (4, nsub, nchan) stack instead of
    eight separate sorts (full sort or ``lax.top_k`` order-statistic
    selection per ``median_select_mode()`` — bit-identical either way).
    Per-row selection is independent, so each row is bit-identical to its
    reference implementation.
    """
    ax3 = axis + 1
    n = jnp.sum(valid, axis=axis)
    valid3 = valid[None]
    mode = median_select_mode()
    filled = jnp.concatenate(
        (jnp.where(valid3, stack4[:3], jnp.inf), stack4[3:]), axis=0)
    med = _select_medians_via(filled, n, ax3, mode=mode)
    r = stack4 - jnp.expand_dims(med, ax3)
    abs_r = jnp.abs(r)
    filled_r = jnp.concatenate(
        (jnp.where(valid3, abs_r[:3], jnp.inf), abs_r[3:]), axis=0)
    mad = _select_medians_via(filled_r, n, ax3, mode=mode)

    has = n > 0                                   # (A,)
    madA, madB = mad[:3], mad[3]
    mad_ok = has[None] & (madA != 0) & ~jnp.isnan(madA)
    mad_ok_b = jnp.expand_dims(mad_ok, ax3)
    madA_b = jnp.expand_dims(jnp.where(mad_ok, madA, 1.0), ax3)
    # Two-division op order matches the reference: (r/MAD), abs, /thresh.
    scaled_ok = jnp.abs(r[:3] / madA_b) / thresh
    scaled_valid = jnp.where(mad_ok_b, scaled_ok, abs_r[:3])
    has_b = jnp.expand_dims(jnp.expand_dims(has, 0), ax3)
    type_a = jnp.where(valid3 & has_b, scaled_valid, jnp.abs(stack4[:3]))
    type_b = jnp.abs(r[3] / jnp.expand_dims(madB, ax3 - 1)) / thresh
    return jnp.concatenate((type_a, type_b[None]), axis=0)


def comprehensive_stats(
    weighted: jnp.ndarray,
    valid: jnp.ndarray,
    chanthresh: float,
    subintthresh: float,
) -> jnp.ndarray:
    """weighted residual cube → per-profile outlier score (plain array).

    axis=0 scaling compares a profile against others in the same *channel*
    (across subints, / chanthresh); axis=1 against the same *subint* (across
    channels, / subintthresh) — reference iterative_cleaner.py:221-223.
    """
    d_std, d_mean, d_ptp, d_fft = diagnostics(weighted, valid)
    return scale_and_combine(
        d_std, d_mean, d_ptp, d_fft, valid, chanthresh, subintthresh)


def _fft_diag_impl(centred: jnp.ndarray) -> jnp.ndarray:
    return jnp.max(jnp.abs(jnp.fft.rfft(centred, axis=-1)), axis=-1)


# XLA's SPMD partitioner cannot partition the FFT op: on a sharded cube it
# inserts a chain of all-gathers that materialises the FULL global cube on
# every device before one replicated fft — found by static analysis of the
# sharded lowering (the three cube-scale all-gathers all fed %fft), and
# fatal to the >HBM sharded route, whose whole point is that no single
# chip can hold the cube.  The diagnostic reduces along the BIN axis,
# which batch_spec never shards, so it is embarrassingly parallel across
# profiles: custom_partitioning tells the partitioner to keep the leading
# dims sharded as-is (bin axis replicated) and run the local rfft per
# shard — bitwise-identical values, zero collective traffic.  Pinned by
# tests/test_cost_model.py::test_sharded_lowering_never_gathers_the_cube.
#
# custom_partitioning has no batching rule, and the sharded batch path is
# vmap(fused_clean); rank-specific instances dispatched through
# custom_vmap restore composition (each vmap level promotes to the
# next-rank instance, so nested vmap — the sweep grid — works too).
_fft_diag_instances: dict = {}


def _fft_diag_instance(ndim: int):
    inst = _fft_diag_instances.get(ndim)
    if inst is not None:
        return inst
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec

    try:
        # jax >= 0.5: Shardy (the eventual default partitioner) reads a
        # sharding rule instead of the GSPMD callbacks.  Older jax within
        # the declared >=0.4.30 floor has neither the class nor the
        # ``sharding_rule=`` kwarg, so fall back to callbacks-only — GSPMD
        # is the only partitioner there, and the callbacks are authoritative
        # (ADVICE r05: the unconditional import broke every fft_diagnostic
        # call on older jax, sharded or not).
        from jax.experimental.custom_partitioning import SdyShardingRule
    except ImportError:
        SdyShardingRule = None

    def _supported(sharding, aval):
        """The operand sharding we can execute locally: leading dims as the
        operand already is, bin axis replicated."""
        spec = list(sharding.spec) + [None] * (aval.ndim - len(sharding.spec))
        spec = spec[: aval.ndim]
        spec[-1] = None
        return NamedSharding(sharding.mesh, PartitionSpec(*spec))

    def _shardings(arg_shapes):
        """(input, output) shardings for the local lowering: the output
        drops the reduced bin axis from the supported input sharding."""
        in_sh = _supported(arg_shapes[0].sharding, arg_shapes[0])
        out_sh = NamedSharding(in_sh.mesh,
                               PartitionSpec(*list(in_sh.spec)[:-1]))
        return in_sh, out_sh

    def _partition(mesh, arg_shapes, result_shape):
        in_sh, out_sh = _shardings(arg_shapes)
        return mesh, _fft_diag_impl, out_sh, (in_sh,)

    def _infer(mesh, arg_shapes, result_shape):
        return _shardings(arg_shapes)[1]

    inst = custom_partitioning(_fft_diag_impl)
    kw = {}
    if SdyShardingRule is not None:
        # Shardy (the jax>=0.9 default partitioner) reads this rule instead
        # of the GSPMD callbacks: every leading dim propagates, bins stay
        # whole.
        dims = tuple(string.ascii_lowercase[:ndim])
        kw["sharding_rule"] = SdyShardingRule((dims,), (dims[:-1],))
    inst.def_partition(
        partition=_partition,
        infer_sharding_from_operands=_infer,
        **kw,
    )
    _fft_diag_instances[ndim] = inst
    return inst


@jax.custom_batching.custom_vmap
def fft_diagnostic(centred: jnp.ndarray) -> jnp.ndarray:
    """max |rfft| over the bin axis of the centred residuals — the mask-blind
    diagnostic #4 (§8.L1, reference iterative_cleaner.py:209-211); shared by
    the XLA and Pallas-fused paths.  Partition-aware: see the note above."""
    return _fft_diag_instance(centred.ndim)(centred)


@fft_diagnostic.def_vmap
def _fft_diagnostic_vmap(axis_size, in_batched, centred):
    del axis_size
    batched, = in_batched
    if not batched:
        # vmap over other arguments only (the --sweep threshold grid): the
        # cube is broadcast, not batched.
        return fft_diagnostic(centred), False
    # custom_vmap delivers the batch axis at position 0; the diagnostic is
    # rank-polymorphic, so the batched call is just the next-rank instance.
    return fft_diagnostic(centred), True


def scale_and_combine(
    d_std, d_mean, d_ptp, d_fft, valid, chanthresh: float, subintthresh: float
) -> jnp.ndarray:
    """Robust-scale the four diagnostics and combine (reference :220-224).

    All four diagnostics are stacked so each axis needs TWO median
    selections over one (4, nsub, nchan) array (values, then absolute
    deviations) instead of eight separate ones — r03 phase telemetry put
    the scalers at ~44% of the device step, dominated by sort launches,
    and r06 replaced the remaining full sorts with k-th order-statistic
    selection (`_select_medians_via`; bit-identical by element selection).
    Rows select independently (type-A count-based selection for the masked
    rows, plain np.median semantics for the mask-blind FFT row), so every
    row is bit-identical to its unbatched reference implementation above.

    The final cross-diagnostic median runs as a sort-free selection
    network (`median4_nonneg`): ``combined`` is non-negative-or-NaN by
    construction (every row is |·| or |·|/thresh), which is exactly the
    domain where the network is bit-identical to the sort-based
    `nan_propagating_median` — the one launch the stack trick could not
    batch away.
    """
    stack4 = jnp.stack((d_std, d_mean, d_ptp, d_fft), axis=0)
    per_chan = _scale_axis(stack4, valid, axis=0, thresh=chanthresh)
    per_subint = _scale_axis(stack4, valid, axis=1, thresh=subintthresh)
    combined = jnp.maximum(per_chan, per_subint)  # mask-drop (§8.L2)
    return median4_nonneg(combined)
