"""Pallas TPU kernels for the hot per-iteration ops (SURVEY.md §7.M5).

The fused kernel below covers hot loop #1 plus the moment diagnostics of hot
loop #2 (reference ``iterative_cleaner.py:258-287`` and ``:205-208``) in ONE
pass over the cube's HBM: per (subint, channel) profile it computes the
closed-form template amplitude ``amp = <t, p> / <t, t>`` (§8.L7), the
pulse-region-scaled residual ``amp·t − p`` (:276, :279-282), the weight
pre-scaling (:290-296), the mean / std / ptp diagnostics (:205-208), and —
when the caller passes ``valid`` — the numpy.ma fill semantics of
``ops.stats.fill_moments``, emitting the *centred* weighted residual (which
the XLA FFT diagnostic consumes) and three scaler-ready (nsub, nchan) moment
maps.  With the fills fused, the whole stats phase outside the FFT is one
HBM pass: the XLA tail is exactly ``fft_diagnostic`` + the sort-based robust
scalers.

Why this is the right fusion: the un-fused XLA path materialises the residual
cube, the weighted cube, and the centred cube in HBM — ~5 cube-sized HBM
transfers per iteration.  This kernel reads D once and writes one cube; the
VPU does all the per-profile math while each block sits in VMEM.  The grid
is declared fully ``parallel`` (profiles are independent), so Mosaic may
pipeline/reorder blocks freely.  The FFT diagnostic stays in XLA (TPU FFT is
an XLA primitive; Pallas has none), as do the sort-based robust scalers
(nsub×nchan maps — three orders of magnitude smaller than the cube, not
worth kernel treatment until profiles say so).

Semantics match ``ops.template.fit_and_subtract`` + the moment part of
``ops.stats.diagnostics`` (+ ``fill_moments`` when ``valid`` is given)
bit-for-bit up to f32 reduction order; parity is pinned by
``tests/test_pallas.py`` (interpret mode on CPU, compiled on TPU).

Route viability is a *reasoned* decision now: :func:`pallas_route_status`
returns (ok, why) — platform, bin-axis tiling, and VMEM accounting — and
every caller (clean_step, the chunked backend, bench.py's ``pallas``
section) surfaces the reason instead of a bare bool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from iterative_cleaner_tpu.config import (
    pulse_region_active,
    pulse_region_bin_scale,
)
from iterative_cleaner_tpu.ops.stats import MA_FILL

_PREC = jax.lax.Precision.HIGHEST

# f32 min tile is (8, 128) on the last two dims.  The cube block's tiled dims
# are (BC, NB), so BC only needs sublane (8) alignment; the tiny 2D moment
# blocks tolerate a sub-128 lane via padding.  The budget keeps the block
# around 1 MB f32 — the kernel body holds ~6 block-sized temporaries on the
# Mosaic stack, and that plus pipeline double-buffering must fit 16 MB VMEM.
_SUBLANE = 8
_LANE = 128
_BLOCK_BUDGET = 1 << 18  # profiles*bins per block ≈ 1 MB f32

#: VMEM working-set model for viability reporting: the D block in, the
#: centred block out, each double-buffered by the Mosaic pipeline, plus
#: roughly one block of kernel temporaries — measured against the ~16 MB
#: per-core VMEM.  Kept as a *model* (not a Mosaic query) so the
#: viability decision is deterministic and explainable offline.
_VMEM_BYTES = 16 << 20
_VMEM_BLOCK_FACTOR = 5  # (in + out) × 2 (double-buffer) + ~1 temporaries

# TPUCompilerParams appeared mid-0.4.x; older jax within the declared
# floor simply skips the dimension-semantics hint.
_COMPILER_PARAMS = getattr(pltpu, "TPUCompilerParams", None)


def _block_shape(nb_p: int) -> tuple[int, int]:
    """Pick the (BS, BC) profile tile for a padded bin count."""
    bs = _SUBLANE
    bc = (_BLOCK_BUDGET // (bs * nb_p)) // _SUBLANE * _SUBLANE
    return bs, max(bc, _SUBLANE)


def _fused_kernel(tt_ref, D_ref, t_ref, bs_ref, w_ref, v_ref,
                  centred_ref, mean_ref, std_ref, ptp_ref,
                  *, nbin: int, nb_p: int, fill: bool):
    """One (BS, BC, NB) block: fit, subtract, weight, centre, moments, and
    (``fill``) the numpy.ma valid-fills — the whole per-profile stats chain
    in one VMEM residency."""
    # The (nsub, nchan) maps travel as (BS, BC, 1) blocks: Pallas TPU wants
    # the last two block dims (8, 128)-tiled OR equal to the array dims, and
    # a (BS, BC) block with the VMEM-budget-sized BC < 128 satisfies neither.
    D = D_ref[:]                      # (BS, BC, NB) f32
    t = t_ref[:]                      # (1, NB)
    bscale = bs_ref[:]                # (1, NB)
    w = w_ref[:, :, 0]                # (BS, BC)
    tt = tt_ref[0]

    # Closed-form amplitude (§8.L7); leastsq on a flat objective returns its
    # initial guess 1.0 — replicated for tt == 0 / non-finite tt.
    tp = jnp.sum(D * t[None, :, :], axis=-1)              # (BS, BC)
    ok = (tt != 0) & jnp.isfinite(tt)
    amp = jnp.where(ok, tp / jnp.where(ok, tt, 1.0), 1.0)

    # Residual (model − data, :276), pulse-region scale (:279-282), weight
    # pre-scaling (:290-296) — all elementwise on the VPU.
    wr = (amp[..., None] * t[None, :, :] - D) * bscale[None, :, :] * w[..., None]

    if nbin == nb_p:
        live = None
        mean = jnp.sum(wr, axis=-1) / nbin
    else:
        # Ragged nbin: bins >= nbin are zero padding the wrapper added; they
        # must not contaminate mean/std/ptp.
        live = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nb_p), 2) < nbin
        wr = jnp.where(live, wr, 0.0)
        mean = jnp.sum(wr, axis=-1) / nbin

    c = wr - mean[..., None]
    if live is None:
        var = jnp.sum(c * c, axis=-1) / nbin
        ptp = jnp.max(wr, axis=-1) - jnp.min(wr, axis=-1)
    else:
        var = jnp.sum(jnp.where(live, c * c, 0.0), axis=-1) / nbin
        ptp = (jnp.max(jnp.where(live, wr, -jnp.inf), axis=-1)
               - jnp.min(jnp.where(live, wr, jnp.inf), axis=-1))
    std = jnp.sqrt(var)

    if fill:
        # ops.stats.fill_moments fused in: 0.0 raw data at fully-masked
        # profiles for the masked mean/std reductions, the MaskedArray fill
        # value for ptp — elementwise selects, bit-identical to the XLA
        # tail they replace.
        valid = v_ref[:, :, 0] != 0
        mean = jnp.where(valid, mean, 0.0)
        std = jnp.where(valid, std, 0.0)
        ptp = jnp.where(valid, ptp, MA_FILL)

    centred_ref[:] = c
    mean_ref[:] = mean[..., None]
    std_ref[:] = std[..., None]
    ptp_ref[:] = ptp[..., None]


@functools.partial(jax.jit, static_argnames=("pulse_region", "interpret"))
def fused_fit_moments(D, template, w0, valid=None, *,
                      pulse_region=(0.0, 0.0, 1.0), interpret=False):
    """Fit + subtract + weight + centre + moment diagnostics, one HBM pass.

    D: (nsub, nchan, nbin) f32; template: (nbin,); w0: (nsub, nchan).
    Returns (centred, mean, std, ptp): the centred weighted-residual cube
    (input to the mask-blind FFT diagnostic, §8.L1) and the three moment
    maps.  With ``valid`` (= w0 != 0) the maps come back scaler-ready —
    ``ops.stats.fill_moments`` is fused into the kernel (0.0 at masked
    profiles for mean/std, the 1e20 MaskedArray fill for ptp) so the XLA
    tail is just the FFT diagnostic + robust scalers; with ``valid=None``
    the maps are raw (pre-fill), the original contract.
    """
    nsub, nchan, nbin = D.shape
    dtype = D.dtype

    # <t, t> at the same precision as the pure-XLA path (ops/template.py).
    tt = jnp.einsum("b,b->", template, template, precision=_PREC)

    # Static pulse-region bin scale (shared helper, §8.L5).
    if pulse_region_active(pulse_region):
        bin_scale = pulse_region_bin_scale(nbin, pulse_region)
    else:
        bin_scale = jnp.ones(nbin, dtype=jnp.float32)

    # Pad every dim to tile multiples; padded profiles/bins are zero and are
    # sliced away below (per-profile math — no cross-contamination).
    nb_p = -(-nbin // _LANE) * _LANE
    bs, bc = _block_shape(nb_p)
    nsub_p = -(-nsub // bs) * bs
    nchan_p = -(-nchan // bc) * bc

    Dp = jnp.pad(D, ((0, nsub_p - nsub), (0, nchan_p - nchan),
                     (0, nb_p - nbin)))
    tp_ = jnp.pad(template.astype(dtype), (0, nb_p - nbin))[None, :]
    bsc = jnp.pad(jnp.asarray(bin_scale, dtype), (0, nb_p - nbin))[None, :]
    wp = jnp.pad(w0.astype(dtype), ((0, nsub_p - nsub), (0, nchan_p - nchan)))
    fill = valid is not None
    vmask = wp if not fill else jnp.pad(
        valid.astype(dtype), ((0, nsub_p - nsub), (0, nchan_p - nchan)))

    grid = (nsub_p // bs, nchan_p // bc)
    prof_spec = pl.BlockSpec((bs, bc, 1), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    cube_spec = pl.BlockSpec((bs, bc, nb_p), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    bin_spec = pl.BlockSpec((1, nb_p), lambda i, j: (0, 0),
                            memory_space=pltpu.VMEM)

    kwargs = {}
    if not interpret and _COMPILER_PARAMS is not None:
        # Profiles are independent: a fully-parallel grid lets Mosaic
        # pipeline block DMA against compute and reorder freely.
        kwargs["compiler_params"] = _COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel"))
    centred, mean, std, ptp = pl.pallas_call(
        functools.partial(_fused_kernel, nbin=nbin, nb_p=nb_p, fill=fill),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # tt (1,)
            cube_spec,                                # D
            bin_spec,                                 # template
            bin_spec,                                 # bin_scale
            prof_spec,                                # w0 (S, C, 1)
            prof_spec,                                # valid mask (S, C, 1)
        ],
        out_specs=[cube_spec, prof_spec, prof_spec, prof_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nsub_p, nchan_p, nb_p), dtype),
            jax.ShapeDtypeStruct((nsub_p, nchan_p, 1), dtype),
            jax.ShapeDtypeStruct((nsub_p, nchan_p, 1), dtype),
            jax.ShapeDtypeStruct((nsub_p, nchan_p, 1), dtype),
        ],
        interpret=interpret,
        **kwargs,
    )(tt.reshape(1), Dp, tp_, bsc, wp[..., None], vmask[..., None])

    return (centred[:nsub, :nchan, :nbin], mean[:nsub, :nchan, 0],
            std[:nsub, :nchan, 0], ptp[:nsub, :nchan, 0])


def _platform() -> str:
    """The platform computations actually land on: ``jax_default_device``
    wins over ``default_backend()`` — the dev/test harness pins computation
    to the virtual CPU platform that way while an eagerly-initialised TPU
    backend still claims ``default_backend()``.  The config value may be a
    Device or a platform string (both supported by JAX)."""
    dev = jax.config.jax_default_device
    if dev is None:
        # Dispatch-time read: the caller is about to run a kernel on this
        # very backend, so init happens on this thread either way.
        return jax.default_backend()  # ict: backend-init-ok(dispatch-time; compute follows on this thread)
    return dev if isinstance(dev, str) else dev.platform


def use_interpret() -> bool:
    """Pallas TPU kernels run interpreted off-TPU (the CPU test harness)."""
    return _platform() != "tpu"


def pallas_route_status(nbin: int, platform: str | None = None) -> tuple[bool, str]:
    """Whether the Pallas route should be taken, WITH the reason when not
    (trace-time check; bench surfaces the string in ``pallas.skipped`` and
    the runtime warnings quote it).

    - TPU: yes, provided the minimum block fits the VMEM budget (the bin
      axis is never tiled — mean/std are two-pass per profile, so tiling
      bins would change the reduction structure the parity contract pins —
      and a huge nbin can make even a (8, 8, nb_p) block blow the ~16 MB
      VMEM with its temporaries).
    - CPU: yes — interpret mode, the test harness for the kernel body.
    - anything else (GPU): no — interpret mode there would be a silent
      orders-of-magnitude slowdown, not an optimisation.

    ``platform`` overrides the live-platform read: bench.py asks "what
    WOULD a TPU say for this shape" from the CPU harness, so the
    viability claim at the bench config stays visible without hardware.
    """
    if platform is None:
        platform = _platform()
    if platform == "cpu":
        return True, "cpu: interpret-mode kernel-body harness"
    if platform != "tpu":
        return False, (
            f"platform {platform!r} has no Pallas TPU lowering; interpret "
            "mode there would be a silent orders-of-magnitude slowdown")
    nb_p = -(-nbin // _LANE) * _LANE
    bs, bc = _block_shape(nb_p)
    if bs * bc * nb_p > _BLOCK_BUDGET:
        # The floored minimum block exceeds the budget the kernel's VMEM
        # accounting was sized for (nbin <= 4096 in practice).
        need_mb = (_VMEM_BLOCK_FACTOR * bs * bc * nb_p * 4) / (1 << 20)
        return False, (
            f"nbin={nbin}: the bin axis is never tiled and the minimum "
            f"({bs}, {bc}, {nb_p}) block implies ~{need_mb:.0f} MB of VMEM "
            f"working set (in+out, double-buffered, + temporaries) against "
            f"the {_VMEM_BYTES >> 20} MB/core budget")
    return True, f"tpu: ({bs}, {bc}, {nb_p}) blocks fit the VMEM budget"


def pallas_route_ok(nbin: int) -> bool:
    """Bare-bool view of :func:`pallas_route_status` (routing call sites)."""
    return pallas_route_status(nbin)[0]


def resolve_use_pallas(cfg, nbin: int, want_residual: bool = False) -> bool:
    """The ``use_pallas`` static every route actually dispatches with.

    ``cfg.pallas`` is tri-state since r06:

    - ``None`` (the default) — AUTO: the compiled megakernel wherever it
      is a real optimisation, i.e. on TPU when :func:`pallas_route_status`
      says the shape is viable and the request allows it (no residual —
      the kernel never materialises the cube; no x64 — Mosaic has no
      f64).  Off-TPU auto resolves False: interpret mode is a test
      harness, not a route (the CPU fuzz corpus still pins the kernel's
      mask parity by forcing ``pallas=True``).
    - ``True`` — forced on: resolves True whenever the *request* allows
      it (the residual/x64 fallbacks mirror clean_cube's); a non-viable
      shape still falls back inside the step with a warning quoting the
      route status, exactly as before.
    - ``False`` — forced off.

    Shared by all four routes AND the compile-cache keying
    (utils/compile_cache.inmemory_route_key) so routing and accounting
    can never disagree.
    """
    if want_residual or getattr(cfg, "x64", False):
        return False
    if cfg.pallas is None:
        return (not use_interpret()) and pallas_route_ok(nbin)
    return bool(cfg.pallas)
