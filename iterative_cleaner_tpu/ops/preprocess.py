"""Iteration-invariant preprocessing: pscrunch → baseline removal → dedisperse.

The reference performs these through PSRCHIVE on *every* iteration's fresh
clone (reference ``iterative_cleaner.py:88-90, 96-99``), but they are
weight-independent and iteration-invariant (SURVEY.md §7.M2), so the TPU
design hoists them: run once on host, ship the resulting static cube
``D:(nsub, nchan, nbin) float32`` to HBM once, and keep the whole iteration
loop on device.

Canonical NPZ-backend semantics (documented divergences from PSRCHIVE, which
only matter when comparing against real PSRCHIVE output, never for
numpy-vs-jax mask parity — both backends consume the same precompute):

- ``pscrunch``: Intensity → identity; Stokes → pol 0; Coherence → pol0+pol1.
- ``dedisperse``: per-channel *integer-bin* circular rotation using the
  standard dispersion constant 1/2.41e-4 MHz^2 s (PSRCHIVE rotates by exact
  phase; all four cleaning diagnostics are circular-shift invariant —
  SURVEY.md §8.L8 — so integer rotation is mask-equivalent).
- ``remove_baseline``: off-pulse window = the width-``0.15*nbin`` circular
  window minimising the weighted total dedispersed profile's running mean
  (PSRCHIVE's default minimum-window baseline on the total profile); subtract
  each profile's own mean over that window.  The reference removes baselines
  before dedispersing; we do it after, in the common phase frame — shift
  invariance makes this mask-equivalent as well.
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.io.base import (
    Archive,
    STATE_COHERENCE,
    STATE_INTENSITY,
    STATE_STOKES,
)

# PSRCHIVE's inverse dispersion constant: delay[s] = DM / 2.41e-4 * f^-2[MHz].
DM_CONST = 1.0 / 2.41e-4
BASELINE_FRAC = 0.15


def pscrunch(data: np.ndarray, state: str) -> np.ndarray:
    """(nsub, npol, nchan, nbin) → total intensity (nsub, nchan, nbin)."""
    if data.shape[1] == 1 or state == STATE_INTENSITY:
        return data[:, 0]
    if state == STATE_STOKES:
        return data[:, 0]
    if state == STATE_COHERENCE:
        return data[:, 0] + data[:, 1]
    raise ValueError(f"unknown polarization state {state!r}")


def dispersion_shifts(
    freqs: np.ndarray, dm: float, period: float, nbin: int, ref_freq: float
) -> np.ndarray:
    """Integer bin shift per channel that *dedisperses* the cube.

    A channel at frequency f lags the reference frequency by
    ``DM_CONST * dm * (f^-2 - fref^-2)`` seconds; dedispersion rotates the
    profile forward by that many phase bins.
    """
    if dm == 0.0 or period <= 0:
        return np.zeros(len(freqs), dtype=np.int64)
    delay = DM_CONST * dm * (
        np.asarray(freqs, np.float64) ** -2  # ict: f64-ok(host preprocessing shared by BOTH backends)
        - float(ref_freq) ** -2)
    return np.round(delay / period * nbin).astype(np.int64) % nbin


def roll_cube(cube: np.ndarray, shifts: np.ndarray, inverse: bool = False) -> np.ndarray:
    """Circularly rotate each channel of (..., nchan, nbin) by its shift."""
    nbin = cube.shape[-1]
    sh = (-shifts if inverse else shifts) % nbin
    idx = (np.arange(nbin)[None, :] + sh[:, None]) % nbin  # (nchan, nbin)
    return np.take_along_axis(cube, idx[(None,) * (cube.ndim - 2)], axis=-1)


def baseline_window(total_profile: np.ndarray, frac: float = BASELINE_FRAC) -> tuple[int, int]:
    """(start, width) of the circular window minimising the running mean."""
    nbin = total_profile.shape[-1]
    width = max(1, int(round(frac * nbin)))
    # Circular running mean via the cumulative-sum trick.
    ext = np.concatenate([total_profile, total_profile[:width]])
    csum = np.concatenate([[0.0], np.cumsum(ext)])
    means = (csum[width : width + nbin] - csum[:nbin]) / width
    return int(np.argmin(means)), width


def remove_baseline(cube: np.ndarray, weights: np.ndarray, frac: float = BASELINE_FRAC) -> np.ndarray:
    """Subtract each profile's off-pulse mean (window from the total profile).

    ``cube`` is (nsub, nchan, nbin) *dedispersed*; ``weights`` (nsub, nchan).
    """
    nbin = cube.shape[-1]
    total = np.einsum(
        "sc,scb->b", weights.astype(np.float64), cube.astype(np.float64))  # ict: f64-ok(shared host path)
    start, width = baseline_window(total, frac)
    idx = (start + np.arange(width)) % nbin
    # f64 accumulation: the native (C++) preprocess accumulates in double, and
    # f64 noise (2^-52) vanishes when the subtraction rounds back to f32, so
    # both hosts produce bit-identical cubes.  The subtraction runs per
    # subint to keep the f64 temporaries at nchan*nbin instead of tripling
    # peak host memory at GB cube scales.
    base = cube[..., idx].mean(axis=-1, keepdims=True, dtype=np.float64)  # ict: f64-ok(see f64 note above)
    out = np.empty_like(cube, dtype=np.float32)
    for s in range(cube.shape[0]):
        out[s] = (cube[s].astype(np.float64) - base[s]).astype(np.float32)  # ict: f64-ok(see f64 note above)
    return out


def preprocess(archive: Archive, prefer_native: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Archive → (D, w0): the static kernel inputs.

    D is the pscrunched, dedispersed, baseline-removed float32 cube
    (nsub, nchan, nbin); w0 the frozen original weights (SURVEY.md §8.L11).

    Uses the C++/OpenMP host runtime when built (bit-identical output,
    verified by tests/test_native.py); falls back to the numpy path.
    """
    if prefer_native:
        from iterative_cleaner_tpu import native

        if native.available():
            out = native.preprocess_native(archive)
            if out is not None:
                return out
    cube = pscrunch(archive.data, archive.state).astype(np.float32)
    if not archive.dedispersed:
        shifts = dispersion_shifts(
            archive.freqs, archive.dm, archive.period, archive.nbin, archive.centre_frequency
        )
        cube = roll_cube(cube, shifts)
    w0 = archive.weights.astype(np.float32)
    cube = remove_baseline(cube, w0)
    return np.ascontiguousarray(cube, dtype=np.float32), w0


def redisperse_cube(archive: Archive, cube: np.ndarray) -> np.ndarray:
    """Inverse of the dedispersion roll — used for residual-archive output,
    which the reference stores in the original dispersed frame
    (iterative_cleaner.py:103-107; SURVEY.md §3.5)."""
    if archive.dedispersed:
        return cube
    shifts = dispersion_shifts(
        archive.freqs, archive.dm, archive.period, archive.nbin, archive.centre_frequency
    )
    return roll_cube(cube, shifts, inverse=True)
