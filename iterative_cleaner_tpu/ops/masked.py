"""Masked reductions for JAX — numpy.ma semantics as explicit value+validity.

JAX has no masked arrays; these primitives reproduce the exact numpy.ma
behaviors the oracle inherits (SURVEY.md §7 "hard parts" #1):

- medians over only the valid entries, even-count averaging, NaN when a
  row/column has no valid entries (→ "never flagged", §8.L3);
- ``np.median``'s any-NaN-poisons-the-result rule for the plain (mask-blind)
  FFT-diagnostic path.

All functions are dtype-polymorphic (python-scalar literals only) so the same
code runs f32 on TPU and f64 under ``jax_enable_x64`` for bit-parity
debugging.

Selection lowering (the r06 scalers optimisation): a median never needs the
whole sorted axis — only the two middle *elements* — so the hot-path medians
can run as a k-th order-statistic selection (``lax.top_k`` over total-order
integer keys) instead of a full ``jnp.sort``: O(n log k) work and a
k-element output instead of O(n log n) and a full sorted copy.  Selection
picks the *same elements* the sort would put at the selected positions
(see :func:`sort_prefix` for the exact tie/NaN/−0.0 argument), so the two
lowerings are bit-identical — masks AND scores — and the choice is pure
lowering policy:

- ``ICT_MEDIAN_SELECT=sort``  — the full-sort reference lowering;
- ``ICT_MEDIAN_SELECT=topk``  — the selection lowering everywhere;
- ``ICT_MEDIAN_SELECT=auto``  (default) — selection on TPU (where XLA's
  TopK is a tuned partial reduction and full sorts are the measured
  bottleneck, BENCH_r05), full sort elsewhere (XLA *CPU* lowers top_k
  slower than its single-operand sort — measured 1.1–1.4× — so the CPU
  harness keeps the fast path while pinning the selection lowering
  bit-identical via tests/test_selection_medians.py).

Read once at import, like ``ICT_TEMPLATE_LOWERING`` (ops/template.py): the
mode participates in traced computations, so flipping it mid-process would
silently miss already-compiled executables.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_SELECT = os.environ.get("ICT_MEDIAN_SELECT", "auto")
if _SELECT not in ("auto", "sort", "topk"):
    raise ValueError(
        f"ICT_MEDIAN_SELECT={_SELECT!r}: expected one of auto|sort|topk")


def median_select_mode() -> str:
    """The resolved selection lowering: ``"sort"`` or ``"topk"``.

    ``auto`` resolves per platform at trace time (each backend traces and
    compiles its own executable, so the resolution is always consistent
    with the device the computation runs on).
    """
    if _SELECT != "auto":
        return _SELECT
    dev = jax.config.jax_default_device
    if dev is None:
        # Trace/dispatch-time read: compute follows on this very backend.
        platform = jax.default_backend()  # ict: backend-init-ok(dispatch-time; compute follows on this thread)
    else:
        platform = dev if isinstance(dev, str) else dev.platform
    return "topk" if platform == "tpu" else "sort"


def _totalorder_keys(x: jnp.ndarray) -> jnp.ndarray:
    """Monotone integer keys reproducing ``jnp.sort``'s float order.

    jax's float sort comparator (lax._sort_lt_comparator) canonicalizes
    before comparing — every ±0.0 to +0.0 and every NaN to the canonical
    quiet NaN — then compares in the IEEE total order, so −0.0 ties +0.0,
    all NaNs tie each other, and NaNs sort after +inf.  Reproducing that
    exactly: canonicalize the same way, then the standard sign-magnitude →
    two's-complement key flip.  Equal keys ⇔ the comparator calls the
    elements equal, which is what makes index-stable selection on these
    keys reproduce the stable sort (see :func:`sort_prefix`).
    """
    if x.dtype == jnp.float64:  # ict: f64-ok(x64 opt-in path; integer sort keys only, no f64 math)
        ik, mask = jnp.int64, jnp.int64(0x7FFFFFFFFFFFFFFF)
    else:
        ik, mask = jnp.int32, jnp.int32(0x7FFFFFFF)
    xc = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
    xc = jnp.where(jnp.isnan(x), jnp.full((), jnp.nan, x.dtype), xc)
    i = jax.lax.bitcast_convert_type(xc, ik)
    return jnp.where(i < 0, i ^ mask, i)


def sort_prefix(x: jnp.ndarray, k: int, mode: str | None = None) -> jnp.ndarray:
    """``jnp.sort(x, axis=-1)[..., :k]`` — bit-identically, by selection.

    With ``mode="sort"`` this IS that expression (the reference lowering).
    With ``mode="topk"`` the k smallest elements are selected by
    ``lax.top_k`` over negated total-order keys and gathered from ``x`` by
    index.  Bit-identity argument:

    - equal keys are only produced by elements the sort comparator calls
      equal (identical bit patterns, the ±0.0 pair, or any two NaNs);
    - ``lax.top_k`` breaks ties by lowest index first — the same order a
      *stable* ascending sort leaves equal elements in;
    - the gather returns the ORIGINAL elements (NaN payloads and zero
      signs included), exactly as ``jnp.sort`` moves originals.

    So every selected position holds the same bits the sorted prefix
    would.  Pinned adversarially (NaN payloads/signs, ±inf, −0.0, heavy
    ties) by tests/test_selection_medians.py.
    """
    if mode is None:
        mode = median_select_mode()
    size = x.shape[-1]
    if mode == "sort" or k >= size:
        return jnp.sort(x, axis=-1)[..., :k]
    _neg, idx = jax.lax.top_k(-_totalorder_keys(x), k)
    return jnp.take_along_axis(x, idx, axis=-1)


def masked_median(x: jnp.ndarray, valid: jnp.ndarray, axis: int,
                  mode: str | None = None):
    """Median over valid entries along ``axis`` (np.ma.median semantics).

    Returns (median, n_valid); median is NaN where n_valid == 0.  +inf
    padding at invalid entries, then count-based middle selection with
    even-count averaging.  Both selected positions sit in the first
    ``size//2 + 1`` sorted elements (lo = (n−1)//2 ≤ hi = n//2 ≤ size//2),
    so only that prefix is ever materialised (:func:`sort_prefix`).
    """
    x = jnp.moveaxis(x, axis, -1)
    valid = jnp.moveaxis(valid, axis, -1)
    size = x.shape[-1]
    filled = jnp.where(valid, x, jnp.inf)
    srt = sort_prefix(filled, size // 2 + 1, mode=mode)
    n = jnp.sum(valid, axis=-1)
    lo = jnp.clip((n - 1) // 2, 0, size - 1)
    hi = jnp.clip(n // 2, 0, size - 1)
    lo_v = jnp.take_along_axis(srt, lo[..., None], axis=-1)[..., 0]
    hi_v = jnp.take_along_axis(srt, hi[..., None], axis=-1)[..., 0]
    med = (lo_v + hi_v) * 0.5
    return jnp.where(n > 0, med, jnp.nan), n


def nan_propagating_median(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Plain np.median semantics: even-count averaging, NaN if any NaN.

    (np.median explicitly returns NaN when the reduction window contains one;
    a naive sort-and-pick would not, since NaN sorts last.)
    """
    size = x.shape[axis]
    srt = jnp.sort(x, axis=axis)
    lo = jnp.take(srt, (size - 1) // 2, axis=axis)
    hi = jnp.take(srt, size // 2, axis=axis)
    med = (lo + hi) * 0.5
    return jnp.where(jnp.isnan(x).any(axis=axis), jnp.nan, med)


def median4_nonneg(x: jnp.ndarray) -> jnp.ndarray:
    """``nan_propagating_median(x, axis=0)`` for a 4-row stack of
    NON-NEGATIVE-or-NaN data, as a sort-free selection network.

    The median of 4 averages the two middle *elements*; a 2-comparator
    min/max network selects them exactly: with (a,b) = minmax(x0,x1) and
    (c,d) = minmax(x2,x3), the middle pair is (max(a,c), min(b,d)).  The
    network's only tie ambiguity is which of two *comparator-equal*
    elements it picks — bit-identical anyway except for the ±0.0 pair and
    NaN payloads, which is why the domain is constrained: callers feed
    post-|·| data (no −0.0 exists downstream of an abs), and any NaN row
    is overridden to NaN by the same any-NaN rule as the sort path, so
    payload picks never surface.  The hot final combine (ops/stats.py)
    runs this on every platform: elementwise VPU ops replacing the one
    remaining cross-diagnostic sort launch.
    """
    a = jnp.minimum(x[0], x[1])
    b = jnp.maximum(x[0], x[1])
    c = jnp.minimum(x[2], x[3])
    d = jnp.maximum(x[2], x[3])
    med = (jnp.maximum(a, c) + jnp.minimum(b, d)) * 0.5
    return jnp.where(jnp.isnan(x).any(axis=0), jnp.nan, med)
