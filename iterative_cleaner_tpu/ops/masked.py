"""Masked reductions for JAX — numpy.ma semantics as explicit value+validity.

JAX has no masked arrays; these primitives reproduce the exact numpy.ma
behaviors the oracle inherits (SURVEY.md §7 "hard parts" #1):

- medians over only the valid entries, even-count averaging, NaN when a
  row/column has no valid entries (→ "never flagged", §8.L3);
- ``np.median``'s any-NaN-poisons-the-result rule for the plain (mask-blind)
  FFT-diagnostic path.

All functions are dtype-polymorphic (python-scalar literals only) so the same
code runs f32 on TPU and f64 under ``jax_enable_x64`` for bit-parity
debugging.
"""

from __future__ import annotations

import jax.numpy as jnp


def masked_median(x: jnp.ndarray, valid: jnp.ndarray, axis: int):
    """Median over valid entries along ``axis`` (np.ma.median semantics).

    Returns (median, n_valid); median is NaN where n_valid == 0.  Sort with
    +inf padding, then count-based middle selection with even-count
    averaging.
    """
    x = jnp.moveaxis(x, axis, -1)
    valid = jnp.moveaxis(valid, axis, -1)
    size = x.shape[-1]
    filled = jnp.where(valid, x, jnp.inf)
    srt = jnp.sort(filled, axis=-1)
    n = jnp.sum(valid, axis=-1)
    lo = jnp.clip((n - 1) // 2, 0, size - 1)
    hi = jnp.clip(n // 2, 0, size - 1)
    lo_v = jnp.take_along_axis(srt, lo[..., None], axis=-1)[..., 0]
    hi_v = jnp.take_along_axis(srt, hi[..., None], axis=-1)[..., 0]
    med = (lo_v + hi_v) * 0.5
    return jnp.where(n > 0, med, jnp.nan), n


def nan_propagating_median(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Plain np.median semantics: even-count averaging, NaN if any NaN.

    (np.median explicitly returns NaN when the reduction window contains one;
    a naive sort-and-pick would not, since NaN sorts last.)
    """
    size = x.shape[axis]
    srt = jnp.sort(x, axis=axis)
    lo = jnp.take(srt, (size - 1) // 2, axis=axis)
    hi = jnp.take(srt, size // 2, axis=axis)
    med = (lo + hi) * 0.5
    return jnp.where(jnp.isnan(x).any(axis=axis), jnp.nan, med)
