"""Template build + closed-form amplitude fit in JAX — hot loop #1.

The reference performs nsub×nchan Python→MINPACK round-trips per iteration
(iterative_cleaner.py:258-287; SURVEY.md §3.3).  The model is linear in its
single parameter, so the least-squares solution is the closed form
``amp = <t, p> / <t, t>`` (equal to leastsq to ~1e-9, §8.L7) — one einsum on
the MXU for all profiles at once.

einsums run at Precision.HIGHEST: the fit feeds a ≥-threshold decision, so we
want true f32 accumulation, not bf16 MXU passes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from iterative_cleaner_tpu.config import (
    pulse_region_active,
    pulse_region_bin_scale,
)

_PREC = lax.Precision.HIGHEST


def build_template(D: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Weighted scrunch over (subint, channel): PSRCHIVE's fscrunch+tscrunch
    collapse up to overall scale, which cancels out of amp·t (§8.L7 — the
    reference's ×10000 included)."""
    return jnp.einsum("sc,scb->b", weights, D, precision=_PREC)


def fit_and_subtract(
    D: jnp.ndarray, template: jnp.ndarray, pulse_region
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """All-profile amplitude fit + residual (model − data, reference :276).

    pulse_region is static config: (scale, start, end) per the reference's
    code-order semantics (§8.L5); applied as a static slice so XLA fuses it.
    """
    tt = jnp.einsum("b,b->", template, template, precision=_PREC)
    tp = jnp.einsum("scb,b->sc", D, template, precision=_PREC)
    ok = (tt != 0) & jnp.isfinite(tt)
    # leastsq on a flat objective returns its initial guess amp = 1 (§8.L7).
    amp = jnp.where(ok, tp / jnp.where(ok, tt, 1.0), 1.0)
    resid = amp[..., None] * template - D
    if pulse_region_active(pulse_region):
        # Static bin scale (shared helper, §8.L5); XLA fuses the multiply.
        bin_scale = pulse_region_bin_scale(D.shape[-1], pulse_region)
        resid = resid * jnp.asarray(bin_scale, dtype=resid.dtype)
    return amp, resid
