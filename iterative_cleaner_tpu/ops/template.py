"""Template build + closed-form amplitude fit in JAX — hot loop #1.

The reference performs nsub×nchan Python→MINPACK round-trips per iteration
(iterative_cleaner.py:258-287; SURVEY.md §3.3).  The model is linear in its
single parameter, so the least-squares solution is the closed form
``amp = <t, p> / <t, t>`` (equal to leastsq to ~1e-9, §8.L7) — one einsum on
the MXU for all profiles at once.

einsums run at Precision.HIGHEST: the fit feeds a ≥-threshold decision, so we
want true f32 accumulation, not bf16 MXU passes.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
from jax import lax

from iterative_cleaner_tpu.config import (
    pulse_region_active,
    pulse_region_bin_scale,
)

_PREC = lax.Precision.HIGHEST

# r03 on-chip phase telemetry measured the einsum lowering of the template
# reduction at 15.8 GB/s — 68 ms of a 146 ms step for one cube read
# (docs/bench_r03_interim.json; the two-contracting-dim dot is the suspected
# pathology).  The multiply-reduce form is a fused VPU reduction with the
# bin axis minor — the predictable bandwidth-bound lowering.  Flag masks are
# invariant to the switch across the fuzz corpus in every execution mode
# (reduction-order changes in the template never flip a >=-threshold
# decision; the TPU einsum already differed bitwise from the numpy oracle's
# and masks held).  ICT_TEMPLATE_LOWERING={mulreduce,matvec,einsum} selects
# at import for A/B measurement (tools/probe_template_perf.py).
_LOWERING = os.environ.get("ICT_TEMPLATE_LOWERING", "mulreduce")
if _LOWERING not in ("mulreduce", "matvec", "einsum"):
    raise ValueError(
        f"ICT_TEMPLATE_LOWERING={_LOWERING!r}: expected one of "
        "'mulreduce', 'matvec', 'einsum' (a typo here would silently "
        "mislabel an A/B measurement)")


def build_template(D: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Weighted scrunch over (subint, channel): PSRCHIVE's fscrunch+tscrunch
    collapse up to overall scale, which cancels out of amp·t (§8.L7 — the
    reference's ×10000 included)."""
    if _LOWERING == "einsum":
        return jnp.einsum("sc,scb->b", weights, D, precision=_PREC)
    if _LOWERING == "matvec":
        return jnp.matmul(
            weights.reshape(-1), D.reshape(-1, D.shape[-1]), precision=_PREC)
    return jnp.sum(weights[..., None] * D, axis=(0, 1))


def fit_and_subtract(
    D: jnp.ndarray, template: jnp.ndarray, pulse_region
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """All-profile amplitude fit + residual (model − data, reference :276).

    pulse_region is static config: (scale, start, end) per the reference's
    code-order semantics (§8.L5); applied as a static slice so XLA fuses it.
    """
    tt = jnp.einsum("b,b->", template, template, precision=_PREC)
    tp = jnp.einsum("scb,b->sc", D, template, precision=_PREC)
    ok = (tt != 0) & jnp.isfinite(tt)
    # leastsq on a flat objective returns its initial guess amp = 1 (§8.L7).
    amp = jnp.where(ok, tp / jnp.where(ok, tt, 1.0), 1.0)
    resid = amp[..., None] * template - D
    if pulse_region_active(pulse_region):
        # Static bin scale (shared helper, §8.L5); XLA fuses the multiply.
        bin_scale = pulse_region_bin_scale(D.shape[-1], pulse_region)
        resid = resid * jnp.asarray(bin_scale, dtype=resid.dtype)
    return amp, resid
