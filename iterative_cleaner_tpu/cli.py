"""Command-line interface.

Every flag of the reference CLI (iterative_cleaner.py:15-41) plus the TPU
framework extensions.  The ``--pulse_region`` help documents the *actual*
argument order the code implements — the reference's help text has the order
wrong (SURVEY.md §8.L5: replicate the code, fix the help).

Run as ``python -m iterative_cleaner_tpu`` or the ``iterative-cleaner-tpu``
console script.
"""

from __future__ import annotations

import argparse
import os
import sys

from iterative_cleaner_tpu.config import CleanConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="iterative-cleaner-tpu",
        description="TPU-native iterative surgical RFI cleaner for pulsar archives",
    )
    p.add_argument("archive", nargs="+", help="archives to clean (.npz, or .ar with psrchive)")
    p.add_argument(
        "-c", "--chanthresh", type=float, default=5, metavar="channel_threshold",
        help="sigma threshold for a profile to stand out against others in "
             "the same channel (default: 5)")
    p.add_argument(
        "-s", "--subintthresh", type=float, default=5, metavar="subint_threshold",
        help="sigma threshold for a profile to stand out against others in "
             "the same subint (default: 5)")
    p.add_argument(
        "-m", "--max_iter", type=int, default=5, metavar="maximum_iterations",
        help="maximum number of cleaning iterations (default: 5; must be >= 1)")
    p.add_argument("-z", "--print_zap", action="store_true",
                   help="save a plot showing which profiles were zapped")
    p.add_argument("-u", "--unload_res", action="store_true",
                   help="save an archive containing the pulse-free residual")
    p.add_argument("-p", "--pscrunch", action="store_true",
                   help="pscrunch the output archive")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="do not print cleaning information")
    p.add_argument("-l", "--no_log", action="store_true",
                   help="do not append to clean.log")
    p.add_argument(
        "-r", "--pulse_region", nargs=3, type=float, default=[0, 0, 1],
        metavar=("scaling_factor", "pulse_start", "pulse_end"),
        help="suppress residuals in phase bins [pulse_start:pulse_end] "
             "(dedispersed frame) by scaling_factor; 0 0 1 disables. NOTE: "
             "the scaling factor comes FIRST — this is the order the "
             "original implementation actually reads, despite its help text")
    p.add_argument(
        "-o", "--output", type=str, default="", metavar="output_filename",
        help="output name; 'std' uses the pattern NAME.FREQ.MJD")
    p.add_argument("--memory", action="store_true",
                   help="compatibility no-op (this framework never mutates "
                        "the in-memory archive, so no reload is ever needed)")
    p.add_argument("--bad_chan", type=float, default=1,
                   help="zap a whole channel when its zapped-subint fraction "
                        "strictly exceeds this (default 1 = never)")
    p.add_argument("--bad_subint", type=float, default=1,
                   help="zap a whole subint when its zapped-channel fraction "
                        "strictly exceeds this (default 1 = never)")
    # --- TPU framework extensions ---
    p.add_argument("--backend", choices=("numpy", "jax"), default="jax",
                   help="compute backend (default: jax)")
    p.add_argument("--fused", action="store_true",
                   help="jax: run the whole iteration loop as one device "
                        "dispatch (per-loop progress is derived afterwards "
                        "from the on-device mask history)")
    p.add_argument("--pallas", action="store_const", const=True,
                   default=None, dest="pallas",
                   help="jax: force the fused Pallas stats megakernel (one "
                        "HBM pass over the cube for fit+moments; "
                        "incompatible with --unload_res).  Default is AUTO: "
                        "on a TPU it engages whenever the shape is viable "
                        "and the request allows it; --no_pallas forces the "
                        "XLA route")
    p.add_argument("--no_pallas", action="store_const", const=False,
                   dest="pallas", help=argparse.SUPPRESS)
    p.add_argument("--x64", action="store_true",
                   help="jax: float64 intermediates (requires JAX_ENABLE_X64=1)")
    p.add_argument("--sharded_batch", action="store_true",
                   help="clean same-shape archives together, sharded over the "
                        "device mesh (one archive per dp slice)")
    p.add_argument("--resume", action="store_true",
                   help="skip archives whose cleaned output already exists "
                        "(rerun an interrupted batch; default naming mode only)")
    p.add_argument("--stream", action="store_true",
                   help="with --sharded_batch: the bounded-host-residency "
                        "batch LOADER for directories of complete archives "
                        "— dispatch each same-shape bucket as soon as its "
                        "archives are decoded, overlapping host I/O with "
                        "device compute (default loads the whole directory "
                        "before dispatching).  Not the real-time online "
                        "mode; for archives still being WRITTEN see "
                        "--follow")
    p.add_argument("--follow", action="store_true",
                   help="online mode: tail each archive as it GROWS on disk "
                        "(an observatory-side writer appending subint "
                        "blocks), emit provisional zap alerts within one "
                        "poll of each block landing, and at end-of-stream "
                        "(<archive>.eos sentinel, or no growth for "
                        "--follow_timeout) run the canonical clean on the "
                        "completed file — the final mask is the ordinary "
                        "offline result; the alerts are advisory "
                        "(docs/SERVING.md)")
    p.add_argument("--follow_poll", type=float, default=1.0, metavar="S",
                   help="--follow: seconds between growth polls (default 1)")
    p.add_argument("--follow_timeout", type=float, default=30.0, metavar="S",
                   help="--follow: end-of-stream after this many seconds "
                        "without growth when no .eos sentinel appears "
                        "(default 30)")
    p.add_argument("--alert_iters", type=int, default=2, metavar="N",
                   help="--follow: provisional clean-pass iterations per "
                        "ingested block (default 2)")
    p.add_argument("--no_auto_shard", action="store_true",
                   help="jax: never shard an oversized cube over the device "
                        "mesh (default: cubes whose working set exceeds one "
                        "chip's HBM are cleaned sharded when more chips exist)")
    p.add_argument("--chunk_block", type=int, default=0, metavar="N",
                   help="jax: force the single-device streaming backend with "
                        "N-subint blocks, regardless of the device-memory "
                        "estimate (0 = automatic; the escape hatch when the "
                        "working-set estimate or reported HBM is off)")
    p.add_argument("--no_incremental_template", action="store_true",
                   help="jax --fused: rebuild the template densely every "
                        "iteration instead of carrying it across iterations "
                        "and updating it from the flipped profiles (the "
                        "incremental update saves one full cube read per "
                        "iteration after the first; masks are pinned "
                        "identical across both routes by the fuzz corpus)")
    p.add_argument("--audit", action="store_true",
                   help="shadow-oracle parity audit: after each archive is "
                        "cleaned, replay it through the numpy oracle and "
                        "compare flag masks bit-for-bit; a divergence prints "
                        "loudly and writes a self-contained repro bundle "
                        "(ICT_REPRO_DIR, default ./ict_repro) replayable "
                        "with tools/replay_repro.py "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--dump_masks", action="store_true",
                   help="save the final mask (plus per-iteration history in "
                        "stepwise mode) as <output>_masks.npz")
    p.add_argument("--trace", type=str, default="", metavar="DIR",
                   help="write a jax.profiler trace to DIR")
    p.add_argument("--telemetry", type=str, default="", metavar="PATH",
                   help="append structured telemetry events (trace context, "
                        "route decisions, per-iteration convergence "
                        "forensics) to PATH as JSON lines; the run's "
                        "trace_id ties every event to this invocation "
                        "(ICT_TELEMETRY env equivalent; "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--report", type=str, default="", metavar="PATH",
                   help="write a machine-readable JSON run report (one object "
                        "per archive: output, loops, rfi_frac, converged, "
                        "error) after the batch finishes")
    p.add_argument("--sweep", nargs="+", default=None, metavar="C:S",
                   help="threshold sweep mode: clean each archive under every "
                        "given chanthresh:subintthresh pair in ONE batched "
                        "device dispatch (thresholds are traced, so the whole "
                        "grid shares a single compilation); prints a "
                        "rfi_frac/loops table per archive and saves "
                        "<archive>_sweep.npz with all masks. No cleaned "
                        "archives are written in this mode")
    return p


def config_from_args(args: argparse.Namespace) -> CleanConfig:
    return CleanConfig(
        chanthresh=args.chanthresh,
        subintthresh=args.subintthresh,
        max_iter=args.max_iter,
        pulse_region=tuple(args.pulse_region),
        bad_chan=args.bad_chan,
        bad_subint=args.bad_subint,
        output=args.output,
        pscrunch=args.pscrunch,
        memory=args.memory,
        unload_res=args.unload_res,
        print_zap=args.print_zap,
        quiet=args.quiet,
        no_log=args.no_log,
        backend=args.backend,
        fused=args.fused,
        pallas=args.pallas,
        x64=args.x64,
        sharded_batch=args.sharded_batch,
        auto_shard=not args.no_auto_shard,
        incremental_template=not args.no_incremental_template,
        chunk_block=args.chunk_block,
        stream=args.stream,
        resume=args.resume,
        dump_masks=args.dump_masks,
        audit=args.audit,
        trace_dir=args.trace,
    )


def parse_sweep_pairs(specs: list[str]) -> list[tuple[float, float]]:
    pairs = []
    for spec in specs:
        try:
            c, s = spec.split(":")
            pairs.append((float(c), float(s)))
        except ValueError:
            raise ValueError(
                f"bad --sweep pair {spec!r}; expected chanthresh:subintthresh "
                "like 5:5") from None
    return pairs


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve" and not os.path.isfile("serve"):
        # The long-running cleaning daemon (docs/SERVING.md).  Dispatched on
        # the literal first token — unless a regular FILE named "serve"
        # exists in cwd (a directory can never be an archive positional),
        # in which case the reference semantics win; the ``ict-serve``
        # script is the unambiguous entry point.
        from iterative_cleaner_tpu.service.daemon import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "campaign" and not os.path.isfile("campaign"):
        # Submit-and-follow a survey campaign against a fleet router
        # (docs/SERVING.md "Campaigns"); same literal-token dispatch rule
        # as ``serve``.
        from iterative_cleaner_tpu.campaign.cli import campaign_main

        return campaign_main(argv[1:])
    if argv and argv[0] == "prove" and not os.path.isfile("prove"):
        # The proving ground: scenario mix + chaos drills against an
        # in-process fleet, one JSON verdict line (docs/PROVING.md);
        # same literal-token dispatch rule as ``serve``.
        from iterative_cleaner_tpu.proving.soak import prove_main

        return prove_main(argv[1:])
    if argv and argv[0] == "explain" and not os.path.isfile("explain"):
        # The per-job explain plane: fetch GET /fleet/explain/<job_id>
        # from a fleet router and render the seven-plane causal report
        # (docs/OBSERVABILITY.md "Production recorder & explain plane");
        # same literal-token dispatch rule as ``serve``.
        from iterative_cleaner_tpu.fleet.explain import explain_main

        return explain_main(argv[1:])
    if argv and argv[0] == "trends" and not os.path.isfile("trends"):
        # One-shot performance-trend report: fetch GET /fleet/trends from
        # a fleet router and render fingerprints, sparklined rings, and
        # firing regressions (docs/OBSERVABILITY.md "Performance trends &
        # regression sentinel"); same literal-token dispatch rule as
        # ``serve``.
        from iterative_cleaner_tpu.fleet.trends import trends_main

        return trends_main(argv[1:])
    if argv and argv[0] == "serve-fleet" and not os.path.isfile("serve-fleet"):
        # The fleet router in front of N daemon replicas (docs/SERVING.md
        # "Fleet"); same literal-token dispatch rule as ``serve``, and
        # ``ict-serve-fleet`` is the unambiguous script entry point.
        from iterative_cleaner_tpu.fleet.router import fleet_main

        return fleet_main(argv[1:])
    args = build_parser().parse_args(argv)
    try:
        cfg = config_from_args(args)
        sweep_pairs = parse_sweep_pairs(args.sweep) if args.sweep else None
        if args.follow and (args.sharded_batch or args.sweep):
            raise ValueError("--follow tails growing single archives and "
                             "cannot combine with --sharded_batch/--sweep")
        if args.follow and args.alert_iters < 1:
            raise ValueError(
                f"--alert_iters must be >= 1, got {args.alert_iters}")
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    from iterative_cleaner_tpu.obs import events

    if args.telemetry:
        events.configure(args.telemetry)
    if cfg.backend == "jax":
        # A wedged remote-TPU tunnel hangs the first in-process jax call
        # forever; probe killably and demote to CPU loudly instead
        # (utils/device_probe.py — no-op when already pinned to CPU).
        from iterative_cleaner_tpu.utils.compile_cache import (
            enable_and_trim_persistent_cache,
        )
        from iterative_cleaner_tpu.utils.device_probe import (
            ensure_responsive_backend,
        )

        ensure_responsive_backend()
        # Cross-process executable reuse: a repeat clean of any
        # previously-seen shape skips its cold XLA compile entirely
        # (ICT_NO_COMPILE_CACHE=1 opts out).  The trim keeps the on-disk
        # cache size-bounded (ICT_COMPILE_CACHE_MAX_MB; ADVICE r05).
        enable_and_trim_persistent_cache()
        if events.active():
            # With the always-on flight recorder (obs/flight), compile
            # accounting is worth its one-time listener registration even
            # without a telemetry sink: real-compile phases then show up
            # in post-mortem rings too.
            from iterative_cleaner_tpu.obs import tracing

            tracing.install_compile_listener()
    # The first in-process jax.devices() of the run happens inside the
    # driver; the watchdog (utils/device_probe) turns a wedged-tunnel
    # first-init freeze into a structured warning after ICT_INIT_TIMEOUT_S
    # (it checks backend LIVENESS at the deadline, so a long clean on a
    # live backend stays silent).  No-op on the numpy backend.
    import contextlib

    from iterative_cleaner_tpu.utils.device_probe import init_watchdog

    watchdog = (init_watchdog("cli backend init")
                if cfg.backend == "jax" else contextlib.nullcontext())
    # The CLI is an entry point: mint the run's trace context and bind it
    # so every nested telemetry event (route decisions, per-iteration
    # forensics, per-archive spans) carries this invocation's trace_id.
    with watchdog, events.trace_scope(events.new_trace_id()), \
            events.span("cli_run", argv=list(argv)):
        if sweep_pairs is not None:
            from iterative_cleaner_tpu.driver import run_sweep

            reports = run_sweep(args.archive, cfg, sweep_pairs)
        elif args.follow:
            from iterative_cleaner_tpu.driver import run_follow

            reports = run_follow(
                args.archive, cfg, poll_s=args.follow_poll,
                idle_timeout_s=args.follow_timeout,
                alert_iters=args.alert_iters)
        else:
            from iterative_cleaner_tpu.driver import run

            reports = run(args.archive, cfg)
    if args.report:
        from iterative_cleaner_tpu.driver import write_report

        write_report(reports, args.report, cfg)
    return 0 if all(r.error is None for r in reports) else 1


if __name__ == "__main__":
    raise SystemExit(main())
