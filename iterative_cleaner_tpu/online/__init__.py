"""Real-time streaming-ingest subsystem (ict-online).

Subint blocks arrive incrementally — over the daemon's session API
(service/sessions.py, docs/SERVING.md) or the CLI's ``--follow`` file tail
(online/follow.py) — a resident per-session :class:`CleanState` grows by
amortized doubling, every block triggers a bounded provisional clean pass
with zap alerts (advisory, latency-first), and end-of-stream runs the
canonical pipeline on the completed cube so the authoritative mask stays
bit-identical to the numpy oracle by construction (online/finalize.py).
"""

from iterative_cleaner_tpu.online.finalize import (
    FinalizedSession,
    finalize_session,
)
from iterative_cleaner_tpu.online.session import OnlineSession, ZapAlert
from iterative_cleaner_tpu.online.state import CleanState, SessionMeta

__all__ = [
    "CleanState",
    "FinalizedSession",
    "OnlineSession",
    "SessionMeta",
    "ZapAlert",
    "finalize_session",
]
