"""End-of-stream finalization: the canonical clean on the completed cube.

This is deliberately NOT an incremental algorithm.  The provisional passes
exist for alert latency; the authoritative mask comes from running the
ordinary offline pipeline (:class:`..models.surgical.SurgicalCleaner` —
preprocess, clean_cube, bad-parts sweep, output policy) on the assembled
archive, so the streaming subsystem inherits the repo's core invariant —
**final masks bit-identical to the numpy oracle** — by construction rather
than by a parallel proof about incremental state.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from iterative_cleaner_tpu.io.base import Archive
from iterative_cleaner_tpu.models.surgical import SurgicalCleaner, SurgicalOutput


@dataclass
class FinalizedSession:
    archive: Archive               # the assembled completed cube
    output: SurgicalOutput         # canonical pipeline output
    n_provisional_zaps: int        # advisory mask's zap count at EOS
    n_final_zaps: int              # authoritative mask's zap count
    provisional_mismatches: int    # profiles where the two disagree

    @property
    def result(self):
        return self.output.result

    def to_dict(self) -> dict:
        res = self.output.result
        return {
            "loops": int(res.loops),
            "converged": bool(res.converged),
            "rfi_frac": float(res.rfi_frac),
            "nsub": int(self.archive.nsub),
            "n_provisional_zaps": int(self.n_provisional_zaps),
            "n_final_zaps": int(self.n_final_zaps),
            "provisional_mismatches": int(self.provisional_mismatches),
        }


def finalize_session(session, archive: Archive | None = None,
                     progress=None) -> FinalizedSession:
    """Run the canonical pipeline over the session's completed cube.

    ``archive`` overrides the assembled slab — the --follow tail passes the
    final on-disk archive so the authoritative clean sees byte-for-byte what
    any offline rerun of the same file would (metadata drift included).
    """
    if session.state.nsub == 0:
        raise ValueError("cannot finalize a session with no blocks")
    if archive is None:
        archive = session.state.assemble_archive()
    out = SurgicalCleaner(session.cfg).clean(archive, progress=progress)

    # Provisional-accuracy accounting — how good the advisory mask was at
    # the moment the stream ended (reported, never load-bearing).  Compare
    # against the pre-sweep iterative mask: the provisional pass never runs
    # the bad-parts sweep.
    prov = session.state.prov_w
    final_w = np.asarray(out.result.weights)
    mismatches = (
        int(np.sum((prov == 0) != (final_w == 0)))
        if prov.shape == final_w.shape else -1)
    return FinalizedSession(
        archive=archive,
        output=out,
        n_provisional_zaps=int((prov == 0).sum()),
        n_final_zaps=int((final_w == 0).sum()),
        provisional_mismatches=mismatches,
    )
