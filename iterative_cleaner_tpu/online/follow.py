"""CLI ``--follow``: the online subsystem over a growing archive file.

``iterative-cleaner-tpu --follow obs.npz`` tails the file (io/tail.py),
feeds each newly-landed subint range through an :class:`OnlineSession`
(provisional zap alerts within one poll of a block landing), and at
end-of-stream — the ``obs.npz.eos`` sentinel, or no growth for
``--follow_timeout`` — runs the canonical finalize on the completed file
and emits the standard outputs (cleaned archive, clean.log, zap plot,
residual, --report entry) exactly as an offline run of the finished file
would.  The final mask is therefore bit-identical to the numpy oracle on
the completed cube; the alerts along the way are advisory.

Not to be confused with ``--stream``, which is the bounded-host-residency
*batch loader* for directories of complete archives.
"""

from __future__ import annotations

import sys

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.base import get_io
from iterative_cleaner_tpu.io.tail import tail_blocks
from iterative_cleaner_tpu.online.session import (
    DEFAULT_ALERT_ITERS,
    OnlineSession,
    ZapAlert,
)
from iterative_cleaner_tpu.online.state import SessionMeta


def _print_alert(path: str, alert: ZapAlert) -> None:
    pairs = ", ".join(f"({s},{c})" for s, c in alert.new_zaps[:8])
    more = alert.n_new_zaps - min(len(alert.new_zaps), 8)
    print(
        f"follow {path}: block {alert.block_index} "
        f"(subints {alert.subint_lo}:{alert.subint_hi}) -> "
        f"{alert.n_new_zaps} provisional zap(s)"
        + (f" [{pairs}{f', +{more} more' if more > 0 else ''}]"
           if alert.n_new_zaps else "")
        + f", rfi_frac={alert.provisional_rfi_frac:.4f}, "
          f"{alert.latency_s * 1e3:.0f} ms",
        file=sys.stderr)


def follow_archive(
    path: str,
    cfg: CleanConfig,
    poll_s: float = 1.0,
    idle_timeout_s: float = 30.0,
    alert_iters: int = DEFAULT_ALERT_ITERS,
    log_dir: str = ".",
    all_paths: list[str] | None = None,
    sleep=None,
):
    """Tail one growing archive to completion; returns the ArchiveReport.
    Per-archive errors are the caller's to isolate (driver.run_follow)."""
    from iterative_cleaner_tpu.driver import emit_outputs, residual_name

    session = None
    final_archive = None
    for archive, lo, hi in tail_blocks(
            path, poll_s=poll_s, idle_timeout_s=idle_timeout_s, sleep=sleep):
        if session is None:
            session = OnlineSession(
                SessionMeta.from_archive(archive), cfg,
                alert_iters=alert_iters)
            if not cfg.quiet:
                print(f"follow {path}: session open "
                      f"(nchan={archive.nchan}, nbin={archive.nbin})",
                      file=sys.stderr)
        alert = session.ingest(archive.data[lo:hi], archive.weights[lo:hi])
        if not cfg.quiet:
            _print_alert(path, alert)
        final_archive = archive

    if session is None:
        raise ValueError(f"{path}: stream ended with no subints")
    from iterative_cleaner_tpu.online.finalize import finalize_session

    # Finalize against the LAST on-disk content, not the assembled slab:
    # byte-for-byte what an offline rerun of the finished file sees.
    fin = finalize_session(session, archive=final_archive)
    session.finalized = True
    out = fin.output
    res = out.result
    if not cfg.quiet:
        print(f"follow {path}: end of stream after "
              f"{session.blocks_ingested} block(s), "
              f"{final_archive.nsub} subints; running the canonical clean "
              f"(provisional mask disagreed on "
              f"{fin.provisional_mismatches} profile(s))", file=sys.stderr)

    io = get_io(path)
    if cfg.unload_res and out.residual is not None:
        io.save(out.residual, residual_name(path, res.loops))
    return emit_outputs(
        io,
        final_archive,
        path,
        out.cleaned,
        res.test_results,
        res.loops,
        res.converged,
        res.rfi_frac,
        cfg,
        log_dir,
        all_paths if all_paths is not None else [path],
        history=res.history,
        iteration_s=[i.duration_s for i in res.iterations] if res.timed
        else None,
    )
