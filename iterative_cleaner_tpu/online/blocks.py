"""Block wire format — one codec for the HTTP body and the session spool.

A block is two arrays, ``data`` (bsub, npol, nchan, nbin) and ``weights``
(bsub, nchan), carried as an in-memory NPZ (``np.savez_compressed`` into a
buffer): the same hermetic container the archive backend already uses, so
clients build uploads with nothing but numpy, and the daemon persists the
received bytes VERBATIM as the session's replay log — decode validates the
payload once and the spooled copy replays through the identical path after
a restart.
"""

from __future__ import annotations

import io

import numpy as np

#: Upload clamp for one block body (the service applies it to
#: Content-Length): a 256 MB f32 block is ~1M profiles of 64 bins — far
#: beyond any per-block observatory cadence — while an unbounded read
#: would let one client buffer the daemon out of host RAM.
MAX_BLOCK_BYTES = 256 << 20


def encode_block(data: np.ndarray, weights: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, data=np.asarray(data, np.float32),
                        weights=np.asarray(weights, np.float32))
    return buf.getvalue()


def decode_block(payload: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Bytes → (data, weights); raises ValueError on anything malformed
    (the API maps that to a 400, never a dropped socket)."""
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            return (np.asarray(z["data"], np.float32),
                    np.asarray(z["weights"], np.float32))
    except KeyError as exc:
        raise ValueError(f"block payload missing array {exc}") from None
    except Exception as exc:  # noqa: BLE001 — zipfile/format errors vary
        raise ValueError(f"undecodable block payload: {exc}") from None
