"""Block wire format — one codec for the HTTP body and the session spool.

A block is two arrays, ``data`` (bsub, npol, nchan, nbin) and ``weights``
(bsub, nchan).  Since the ingest tier landed they travel as a compressed
self-describing container (:mod:`..ingest.codec`: byteshuffle + DEFLATE,
zstd when available — lossless, bit-exact f32 round-trip) so the
spool/session path moves a fraction of the raw bytes over slow links;
``ICT_WIRE_CODEC=npz`` reverts to the legacy in-memory NPZ container.
Decoding sniffs the container magic, so spools written by older daemons
and uploads from older clients replay through the identical path — the
daemon still persists received bytes VERBATIM as the session's replay log,
and decode validates the payload once for both the live and replayed copy.
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.ingest.codec import decode_payload, encode_arrays

#: Upload clamp for one block body (the service applies it to
#: Content-Length): a 256 MB f32 block is ~1M profiles of 64 bins — far
#: beyond any per-block observatory cadence — while an unbounded read
#: would let one client buffer the daemon out of host RAM.  The clamp
#: applies to WIRE bytes; decode then caps the total RAW bytes the
#: container's header may declare at MAX_RAW_BLOCK_BYTES, with each
#: stream's inflation bounded to its declared size *during*
#: decompression — so a crafted payload can neither over-declare nor
#: over-inflate.
MAX_BLOCK_BYTES = 256 << 20

#: Decode-side cap on a block's declared raw size: 4x the wire clamp
#: covers every legitimate compression ratio on real f32 radio data (the
#: codec measures ~0.85; even pathological repetitive cubes stay well
#: inside 4:1) while bounding a decompression bomb to 1 GB.
MAX_RAW_BLOCK_BYTES = MAX_BLOCK_BYTES * 4


def encode_block(data: np.ndarray, weights: np.ndarray,
                 codec: str | None = None) -> bytes:
    return encode_arrays(
        {"data": np.asarray(data, np.float32),
         "weights": np.asarray(weights, np.float32)}, codec=codec)


def decode_block(payload: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Bytes → (data, weights); raises ValueError on anything malformed
    (the API maps that to a 400, never a dropped socket)."""
    arrays = decode_payload(payload, max_raw_bytes=MAX_RAW_BLOCK_BYTES)
    try:
        return (np.asarray(arrays["data"], np.float32),
                np.asarray(arrays["weights"], np.float32))
    except KeyError as exc:
        raise ValueError(f"block payload missing array {exc}") from None
