"""One streaming cleaning session: blocks in, provisional zap alerts out.

After every ingested block the session runs a BOUNDED incremental clean
pass over everything that has arrived (``alert_iters`` iterations, default
2) and reports which (subint, channel) profiles it would newly zap — the
operator's within-seconds RFI alarm.  The pass is the canonical loop
(:class:`..core.cleaner.LoopState` — the exact implementation clean_cube
runs), warm-started from the previous block's provisional mask so the
template starts near the fixed point, over the canonical per-iteration
kernels:

- jax backend: :class:`..parallel.chunked.ChunkedJaxCleaner` with a FIXED
  subint block size, so the executable set stays bounded while the session
  grows — a fresh whole-cube jit per arrived block would compile a new
  executable per distinct nsub and burn the process's ~70-executable budget
  (utils/compile_cache.py) in one observation;
- numpy backend: the oracle, one pass, no compilation story.

**Provisional masks are advisory, never authoritative** (docs/PARITY.md):
they exist for alert latency, and a session only produces its real mask at
:meth:`finalize`, which runs the canonical pipeline on the completed cube —
bit-identical to the numpy oracle by the repo's core invariant, because it
IS the normal offline path on the assembled archive.

Latency per block lands in the process-global phase counters
(``online_block_s/_n/_max_s``, ``online_pass_*`` — utils/tracing.py), which
the serving daemon's ``/metrics`` reports.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.core.cleaner import LoopState
from iterative_cleaner_tpu.obs import events, tracing
from iterative_cleaner_tpu.online.state import CleanState, SessionMeta

#: Alert payloads list at most this many newly-zapped (subint, channel)
#: pairs; beyond it only the count is reported (``truncated: true``) — an
#: alert is an alarm, not a mask transport.
MAX_ALERT_PAIRS = 256

#: Default bounded-pass iteration count.  Two is the warm-start sweet spot:
#: iteration 1 reacts to the new block through the carried template,
#: iteration 2 settles the template it perturbed; the canonical fixed point
#: is finalize's job.
DEFAULT_ALERT_ITERS = 2


@dataclass
class ZapAlert:
    """One block's provisional verdict."""

    block_index: int               # 0-based arrival number
    subint_lo: int                 # the block's first subint
    subint_hi: int                 # one past its last subint
    nsub_total: int                # session subints after this block
    n_new_zaps: int                # profiles newly zapped by this pass
    new_zaps: list[list[int]] = field(default_factory=list)
    truncated: bool = False        # new_zaps capped at MAX_ALERT_PAIRS
    provisional_rfi_frac: float = 0.0
    pass_iterations: int = 0
    pass_converged: bool = False
    latency_s: float = 0.0         # ingest+pass wall-clock for this block

    def to_dict(self) -> dict:
        from dataclasses import asdict

        return asdict(self)


class OnlineSession:
    """Accepts subint blocks incrementally; see the module docstring."""

    def __init__(
        self,
        meta: SessionMeta,
        cfg: CleanConfig | None = None,
        alert_iters: int = DEFAULT_ALERT_ITERS,
        pass_block: int = 0,
    ) -> None:
        self.meta = meta
        self.cfg = cfg or CleanConfig(backend="jax")
        if alert_iters < 1:
            raise ValueError(f"alert_iters must be >= 1, got {alert_iters}")
        self.alert_iters = int(alert_iters)
        # Fixed chunked-pass slab size (0 = derive from the first block).
        self._pass_block = int(pass_block)
        self.state = CleanState(meta)
        self.blocks_ingested = 0
        self.alerts: list[ZapAlert] = []
        self.finalized = False

    # --- ingest ---

    def _append(self, data: np.ndarray, weights: np.ndarray) -> int:
        lo = self.state.append_block(data, weights)
        if not self._pass_block:
            # Pow2 ceiling of the first block: most passes then run on
            # whole slabs of this one shape (plus at most pass_block
            # distinct remainder shapes over the session's life).
            self._pass_block = 1 << max(0, (self.state.nsub - lo) - 1
                                        ).bit_length()
        return lo

    def ingest(self, data: np.ndarray, weights: np.ndarray) -> ZapAlert:
        """Append one block, run the bounded provisional pass, return the
        alert.  Raises ValueError on shape mismatches and on a finalized
        session.  Exception-safe: a pass that dies (e.g. a backend runtime
        error) rolls the append back, so the session state never diverges
        from what the caller believes was accepted — the block can simply
        be resubmitted."""
        if self.finalized:
            raise ValueError("session already finalized")
        with tracing.phase("online_block"):
            import time

            t0 = time.perf_counter()
            lo = self._append(data, weights)
            hi = self.state.nsub
            try:
                with tracing.phase("online_pass"):
                    alert = self._provisional_pass(lo, hi)
            except Exception:
                # Roll the append back (rows beyond nsub are inert; the
                # capacity stays for the retry).  prov_w was not touched —
                # _provisional_pass only assigns it on success.
                self.state.nsub = lo
                raise
            alert.latency_s = time.perf_counter() - t0
        tracing.count("online_blocks_ingested")
        tracing.count("online_zap_alerts", alert.n_new_zaps)
        if events.active():
            # Inherits the session's trace context (service/sessions.py and
            # the --follow driver bind it around ingest).
            events.emit("online_block", block_index=alert.block_index,
                        subint_lo=alert.subint_lo, subint_hi=alert.subint_hi,
                        n_new_zaps=alert.n_new_zaps,
                        provisional_rfi_frac=round(
                            alert.provisional_rfi_frac, 6),
                        pass_converged=alert.pass_converged,
                        latency_s=round(alert.latency_s, 6))
        self.blocks_ingested += 1
        self.alerts.append(alert)
        return alert

    def replay_block(self, data: np.ndarray, weights: np.ndarray) -> None:
        """Spool replay (restart resume): append WITHOUT the per-block
        provisional pass — the alerts were already emitted in the previous
        daemon life and provisional state is advisory, so a restart costs
        O(slab copy), not O(blocks × device pass).  The first live ingest
        after a replay seeds its pass from the original weights (prov_w is
        empty), exactly like a fresh session's first pass over the full
        accumulated cube."""
        if self.finalized:
            raise ValueError("session already finalized")
        self._append(data, weights)
        self.blocks_ingested += 1

    def _backend(self, D: np.ndarray, w0: np.ndarray):
        if self.cfg.backend != "jax":
            from iterative_cleaner_tpu.backends.numpy_backend import (
                NumpyCleaner,
            )

            return NumpyCleaner(D, w0, self.cfg)
        from iterative_cleaner_tpu.parallel.chunked import ChunkedJaxCleaner
        from iterative_cleaner_tpu.utils.compile_cache import (
            note_compiled_shape,
        )

        # Same executable accounting as clean_cube's chunked branch (same
        # key layout, so a CLI chunked run of this slab shape shares the
        # budget entry): the step loop's slab executables, full + remainder.
        nsub, nchan, nbin = D.shape
        block = min(self._pass_block, nsub)
        fp = ("chunked", False, self.cfg.x64, False,
              self.cfg.incremental_template, tuple(self.cfg.pulse_region))
        note_compiled_shape((block, nchan, nbin, *fp))
        if nsub > block and nsub % block:
            note_compiled_shape((nsub % block, nchan, nbin, *fp))
        return ChunkedJaxCleaner(D, w0, self.cfg, block=block)

    def _provisional_pass(self, lo: int, hi: int) -> ZapAlert:
        D, w0 = self.state.provisional_inputs()
        # Warm-start seed: the previous provisional mask, extended with the
        # new block's own original weights.  The seed only shapes the first
        # template (stats run against the frozen w0 — §8.L11), so a bad
        # earlier provisional can always be un-flagged by a later pass.
        seed = np.concatenate([self.state.prov_w, w0[lo:]], axis=0) \
            if self.state.prov_w.size else w0.copy()
        loop = LoopState.start(seed)
        loop.run(self._backend(D, w0), self.alert_iters, timed=False)
        new_prov = loop.history[-1]

        newly = np.argwhere((new_prov == 0) & (seed != 0))
        pairs = newly[:MAX_ALERT_PAIRS].tolist()
        alert = ZapAlert(
            block_index=self.blocks_ingested,
            subint_lo=lo,
            subint_hi=hi,
            nsub_total=hi,
            n_new_zaps=int(len(newly)),
            new_zaps=pairs,
            truncated=len(newly) > MAX_ALERT_PAIRS,
            provisional_rfi_frac=float((new_prov == 0).mean()),
            pass_iterations=len(loop.infos),
            pass_converged=loop.converged,
        )
        self.state.prov_w = new_prov
        return alert

    # --- end of stream ---

    def finalize(self, progress=None):
        """Canonical end-of-stream clean (online/finalize.py); marks the
        session closed.  Returns the FinalizedSession."""
        from iterative_cleaner_tpu.online.finalize import finalize_session

        out = finalize_session(self, progress=progress)
        self.finalized = True
        return out
