"""Per-session resident cleaning state for the streaming-ingest subsystem.

A streaming session never knows its final subint count, so the cube lives
in capacity-doubling slabs (amortized O(1) per appended row, O(nsub) total
copies — the dynamic-array idiom) instead of a reallocation per block:

- the **raw** slab ``(cap, npol, nchan, nbin)`` — the authoritative record;
  end-of-stream assembles it into a plain :class:`..io.base.Archive` and the
  canonical pipeline runs on THAT, which is what keeps the final mask inside
  the repo's bit-identical-to-the-oracle guarantee by construction;
- the **pscrunched + dedispersed** slab ``(cap, nchan, nbin)`` — the two
  per-subint-independent preprocessing steps applied incrementally at
  ingest, so a provisional pass never re-does them over the whole history
  (the dispersion shifts depend only on session metadata, fixed at open).

Baseline removal is the one preprocessing step that is NOT per-subint (its
off-pulse window comes from the weighted TOTAL profile), so provisional
passes recompute it over the accumulated slab each block — O(slab) host
work, the same order as the template build the pass runs anyway — while
the finalize path re-derives everything from the raw slab canonically.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

import numpy as np

from iterative_cleaner_tpu.io.base import Archive, STATE_INTENSITY
from iterative_cleaner_tpu.ops.preprocess import (
    dispersion_shifts,
    pscrunch,
    remove_baseline,
    roll_cube,
)


@dataclass
class SessionMeta:
    """The archive-level metadata a session is opened with — everything an
    :class:`Archive` needs except the (still-arriving) cube and weights.
    JSON-roundtrippable: the daemon spools it as ``meta.json`` and rebuilds
    sessions from it after a restart."""

    nchan: int
    nbin: int
    npol: int = 1
    freqs: list[float] = field(default_factory=list)
    centre_frequency: float = 0.0
    dm: float = 0.0
    period: float = 1.0
    source: str = "STREAM"
    mjd_start: float = 60000.0
    mjd_end: float = 60000.0
    state: str = STATE_INTENSITY
    dedispersed: bool = False

    def __post_init__(self) -> None:
        if self.nchan < 1 or self.nbin < 1 or self.npol < 1:
            raise ValueError(
                f"bad session dims nchan={self.nchan} nbin={self.nbin} "
                f"npol={self.npol}")
        if not self.freqs:
            # A client that only knows the band centre still gets a valid
            # archive; DM=0 sessions never read per-channel frequencies.
            self.freqs = [float(self.centre_frequency)] * int(self.nchan)
        if len(self.freqs) != self.nchan:
            raise ValueError(
                f"freqs has {len(self.freqs)} entries, expected {self.nchan}")
        if self.dm != 0.0 and not self.dedispersed:
            # Dedispersion shifts divide by f^2 and by the reference
            # frequency squared: a zero/negative frequency (including the
            # centre-fill above when no centre was given) would rotate the
            # cube by garbage silently.  Refuse at open, not at first block.
            if self.centre_frequency <= 0 or any(
                    f <= 0 for f in self.freqs):
                raise ValueError(
                    "dm != 0 on a dispersed session requires positive "
                    "centre_frequency and per-channel freqs (got centre="
                    f"{self.centre_frequency!r})")

    @classmethod
    def from_archive(cls, archive: Archive) -> "SessionMeta":
        return cls(
            nchan=archive.nchan,
            nbin=archive.nbin,
            npol=archive.npol,
            freqs=[float(f) for f in archive.freqs],
            centre_frequency=float(archive.centre_frequency),
            dm=float(archive.dm),
            period=float(archive.period),
            source=archive.source,
            mjd_start=float(archive.mjd_start),
            mjd_end=float(archive.mjd_end),
            state=archive.state,
            dedispersed=bool(archive.dedispersed),
        )

    @classmethod
    def from_dict(cls, d: dict) -> "SessionMeta":
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown session meta fields {sorted(unknown)}")
        missing = {"nchan", "nbin"} - set(d)
        if missing:
            raise ValueError(f"session meta missing {sorted(missing)}")
        return cls(**{k: d[k] for k in d})

    def to_dict(self) -> dict:
        return asdict(self)


class CleanState:
    """The resident per-session state: growing slabs + the provisional mask.

    ``append_block`` is the only mutator; views returned by the properties
    are slices of the live slabs (copy before persisting them).
    """

    def __init__(self, meta: SessionMeta) -> None:
        self.meta = meta
        self.nsub = 0
        self._cap = 0
        self._raw: np.ndarray | None = None    # (cap, npol, nchan, nbin)
        self._w: np.ndarray | None = None      # (cap, nchan)
        self._psc: np.ndarray | None = None    # (cap, nchan, nbin)
        # Dedispersion rotation is fixed by the session metadata (the same
        # integer-bin shifts preprocess() derives), computed once.
        if meta.dedispersed:
            self._shifts = np.zeros(meta.nchan, dtype=np.int64)
        else:
            self._shifts = dispersion_shifts(
                np.asarray(meta.freqs, np.float64), meta.dm, meta.period,
                meta.nbin, meta.centre_frequency)
        # Provisional mask over the arrived subints — advisory by contract
        # (docs/PARITY.md): the authoritative mask only exists at finalize.
        self.prov_w = np.zeros((0, meta.nchan), dtype=np.float32)

    # --- slab growth ---

    def _grow_to(self, need: int) -> None:
        if need <= self._cap:
            return
        m = self.meta
        new_cap = max(4, self._cap)
        while new_cap < need:
            new_cap *= 2
        raw = np.zeros((new_cap, m.npol, m.nchan, m.nbin), np.float32)
        w = np.zeros((new_cap, m.nchan), np.float32)
        psc = np.zeros((new_cap, m.nchan, m.nbin), np.float32)
        if self.nsub:
            raw[: self.nsub] = self._raw[: self.nsub]
            w[: self.nsub] = self._w[: self.nsub]
            psc[: self.nsub] = self._psc[: self.nsub]
        self._raw, self._w, self._psc = raw, w, psc
        self._cap = new_cap

    @property
    def capacity(self) -> int:
        return self._cap

    def append_block(self, data: np.ndarray, weights: np.ndarray) -> int:
        """Validate + append one subint block; returns the block's first
        subint index.  ``data`` is (bsub, npol, nchan, nbin) (a 3-D block is
        accepted as npol=1), ``weights`` (bsub, nchan)."""
        m = self.meta
        data = np.asarray(data, dtype=np.float32)
        if data.ndim == 3:
            data = data[:, None]
        if data.ndim != 4 or data.shape[1:] != (m.npol, m.nchan, m.nbin):
            raise ValueError(
                f"block data shape {data.shape} does not match the session "
                f"(bsub, {m.npol}, {m.nchan}, {m.nbin})")
        bsub = data.shape[0]
        if bsub < 1:
            raise ValueError("empty block")
        weights = np.asarray(weights, dtype=np.float32)
        if weights.shape != (bsub, m.nchan):
            raise ValueError(
                f"block weights shape {weights.shape} != ({bsub}, {m.nchan})")
        lo = self.nsub
        self._grow_to(lo + bsub)
        self._raw[lo: lo + bsub] = data
        self._w[lo: lo + bsub] = weights
        # Incremental pscrunch + dedisperse — per-subint independent, so the
        # block's rows are final the moment they land.
        self._psc[lo: lo + bsub] = roll_cube(
            pscrunch(data, m.state), self._shifts)
        self.nsub += bsub
        return lo

    # --- views ---

    @property
    def raw(self) -> np.ndarray:
        return self._raw[: self.nsub]

    @property
    def weights(self) -> np.ndarray:
        return self._w[: self.nsub]

    @property
    def pscrunched(self) -> np.ndarray:
        return self._psc[: self.nsub]

    def provisional_inputs(self) -> tuple[np.ndarray, np.ndarray]:
        """(D, w0) for a provisional pass over everything arrived so far:
        the incremental pscrunched/dedispersed slab with the baseline
        re-removed against the CURRENT accumulated total-profile window
        (the one non-per-subint preprocessing step; module docstring)."""
        if self.nsub == 0:
            raise ValueError("no blocks ingested yet")
        D = remove_baseline(self.pscrunched, self.weights)
        return np.ascontiguousarray(D, np.float32), self.weights.copy()

    def assemble_archive(self) -> Archive:
        """The completed stream as a plain Archive — the canonical-finalize
        input (and, for a session fed from a file tail, identical to the
        file's own content)."""
        m = self.meta
        return Archive(
            data=self.raw.copy(),
            weights=self.weights.copy(),
            freqs=np.asarray(m.freqs, np.float64),
            centre_frequency=m.centre_frequency,
            dm=m.dm,
            period=m.period,
            source=m.source,
            mjd_start=m.mjd_start,
            mjd_end=m.mjd_end,
            state=m.state,
            dedispersed=m.dedispersed,
            filename=f"stream_{m.source}",
        )
