"""Backend protocol: one cleaning iteration as a pure array function.

A backend owns the static kernel inputs (the preprocessed cube ``D`` and the
frozen original weights ``w0`` — SURVEY.md §8.L11) and exposes ``step``:
given the previous iteration's weights (which shape the template and nothing
else — SURVEY.md §3.2), produce the outlier test results and the next weight
matrix.  The convergence loop above it is backend-agnostic
(:mod:`..core.cleaner`).
"""

from __future__ import annotations

from typing import Protocol

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig


class CleanerBackend(Protocol):
    def step(self, w_prev: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """w_prev (nsub, nchan) → (test_results, new_weights).

        ``new_weights = where(test_results >= 1, 0, w0)`` — the semantics of
        the reference's ``set_weights_archive`` applied to a fresh
        original-weights clone (iterative_cleaner.py:123-124, 299-304); NaN
        test results never flag (SURVEY.md §8.L3).
        """
        ...

    def residual(self) -> np.ndarray | None:
        """The last step's unweighted residual ``amp*template - D`` in the
        dedispersed frame (reference sign convention, iterative_cleaner.py:276),
        or None if no step has run."""
        ...


def make_backend(D: np.ndarray, w0: np.ndarray, cfg: CleanConfig) -> CleanerBackend:
    if cfg.backend == "numpy":
        from iterative_cleaner_tpu.backends.numpy_backend import NumpyCleaner

        return NumpyCleaner(D, w0, cfg)
    from iterative_cleaner_tpu.backends.jax_backend import JaxCleaner

    return JaxCleaner(D, w0, cfg)
