"""The numpy oracle backend — the executable specification.

Reproduces the reference's L2/L3 semantics (SURVEY.md §1) on the preprocessed
cube, *including* every numpy.ma landmine catalogued in SURVEY.md §8 — this
path is what the JAX kernel is tested against (flag-mask IoU == 1.0).

Faithfulness notes (each verified empirically on numpy 2.0.2, see
tests/test_landmines.py):

- The template amplitude fit is the closed form ``amp = <t,p>/<t,t>`` — the
  reference's per-profile ``scipy.optimize.leastsq`` solves the same linear
  1-parameter problem (equal to ~1e-9 relative, SURVEY.md §8.L7).  Both
  backends use the closed form; a degenerate template (<t,t> == 0) yields
  amp = 1, matching leastsq returning its initial guess.
- The robust scalers keep the reference's per-row/per-column ``numpy.ma``
  evaluation order, so masked-division and mask-drop behaviors (§8.L2-L4) come
  from numpy.ma itself rather than a re-implementation.
- The FFT diagnostic operates on raw ``._data`` (mask-blind, §8.L1).
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig, pulse_region_active


def fit_template(
    D: np.ndarray, template: np.ndarray, pulse_region: tuple[float, float, float]
) -> tuple[np.ndarray, np.ndarray]:
    """Closed-form per-profile template fit + subtraction.

    Replaces the reference's nsub*nchan Python→MINPACK round-trips
    (iterative_cleaner.py:258-287) with two einsums.  Residual sign is
    model − data, as in the reference (:276).
    """
    t = np.asarray(template, dtype=np.float32)
    tt = np.einsum("b,b->", t, t, dtype=np.float32)
    if tt == np.float32(0.0) or not np.isfinite(tt):
        # leastsq cannot improve a flat objective: it returns the initial
        # amp = 1.0 (SURVEY.md §8.L7 degenerate case).
        amp = np.ones(D.shape[:2], dtype=np.float32)
    else:
        amp = np.einsum("scb,b->sc", D, t, dtype=np.float32) / tt
    resid = amp[..., None] * t - D
    if pulse_region_active(pulse_region):
        # Reference reads [scale, start, end] despite its help text
        # (iterative_cleaner.py:279-282; SURVEY.md §8.L5).
        scale, start, end = pulse_region
        resid[..., int(start) : int(end)] *= np.float32(scale)
    return amp, resid


def build_template(D: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """Weighted scrunch over (subint, channel) → template profile.

    PSRCHIVE's fscrunch+tscrunch collapse is a weights-weighted combination;
    the overall template scale (including the reference's ×10000 at :93)
    cancels out of amp·t (SURVEY.md §8.L7), so the unnormalised weighted sum
    is used.
    """
    return np.einsum("sc,scb->b", weights.astype(np.float32), D, dtype=np.float32)


def robust_scale(arr2d, axis: int):
    """(x − median) / MAD along ``axis``, per the reference's scalers.

    axis=0: scale each channel across subints (channel_scaler,
    iterative_cleaner.py:228-240); axis=1: scale each subint across channels
    (subint_scaler, :243-255).  The per-line numpy.ma evaluation order is kept
    so MAD==0 / all-masked semantics are inherited from numpy.ma (SURVEY.md
    §8.L4), including the MAD convention without the 1.4826 consistency
    factor.
    """
    out = np.empty_like(arr2d)
    for i in range(arr2d.shape[1 - axis]):
        sl = (slice(None), i) if axis == 0 else (i, slice(None))
        with np.errstate(invalid="ignore", divide="ignore"):
            vec = arr2d[sl]
            dev = vec - np.ma.median(vec)
            out[sl] = dev / np.ma.median(np.abs(dev))
    return out


def scaled_diagnostics(data_ma: np.ma.MaskedArray, cfg: CleanConfig) -> list:
    """The four per-diagnostic combined scores, in (std, mean, ptp, fft)
    order — each the threshold-scaled, mask-dropping max of the per-channel
    / per-subint robust scalings (reference iterative_cleaner.py:180-225).
    :func:`comprehensive_stats` medians these into the outlier score; the
    forensics attribution (obs/forensics.py) votes on them individually —
    ONE implementation of the §8-landmine-heavy pipeline for both."""
    centred = data_ma - np.expand_dims(data_ma.mean(axis=2), axis=2)
    diagnostics = [
        np.ma.std(data_ma, axis=2),
        np.ma.mean(data_ma, axis=2),
        np.ma.ptp(data_ma, axis=2),
        # Mask-blind by construction: np.fft sees raw ._data (§8.L1).
        np.max(np.abs(np.fft.rfft(centred, axis=2)), axis=2),
    ]
    scaled = []
    for diag in diagnostics:
        per_chan = np.abs(robust_scale(diag, axis=0)) / cfg.chanthresh
        per_subint = np.abs(robust_scale(diag, axis=1)) / cfg.subintthresh
        # np.max over the pair coerces to raw data — the mask-drop (§8.L2).
        scaled.append(np.max((per_chan, per_subint), axis=0))
    return scaled


def comprehensive_stats(data_ma: np.ma.MaskedArray, cfg: CleanConfig) -> np.ndarray:
    """Four robust diagnostics → per-profile outlier score (reference
    iterative_cleaner.py:180-225).

    The returned array is plain (masks are dropped at the max step, §8.L2);
    fully-masked profiles come out NaN and are never flagged (§8.L3).
    """
    return np.median(scaled_diagnostics(data_ma, cfg), axis=0)


class NumpyCleaner:
    """Oracle backend over the preprocessed cube (D, w0)."""

    def __init__(self, D: np.ndarray, w0: np.ndarray, cfg: CleanConfig) -> None:
        self.D = np.ascontiguousarray(D, dtype=np.float32)
        self.w0 = np.asarray(w0, dtype=np.float32)
        self.cfg = cfg
        # 3-D mask from the frozen original weights, as the reference builds
        # it every iteration (iterative_cleaner.py:114-116).
        nbin = D.shape[-1]
        self._mask3d = np.repeat(
            np.expand_dims(~self.w0.astype(bool), 2), nbin, axis=2
        )
        self._residual: np.ndarray | None = None

    def step(self, w_prev: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        template = build_template(self.D, np.asarray(w_prev, np.float32))
        _amp, resid = fit_template(self.D, template, self.cfg.pulse_region)
        self._residual = resid
        # Stats always see the ORIGINAL weighting (§8.L11): weights scale the
        # data (raw values, not booleans — iterative_cleaner.py:290-296) and
        # define the mask.
        weighted = resid * self.w0[..., None]
        data_ma = np.ma.masked_array(weighted, mask=self._mask3d)
        test_results = comprehensive_stats(data_ma, self.cfg)
        new_w = self.w0.copy()
        new_w[test_results >= 1] = 0.0  # NaN >= 1 is False: never flags (§8.L3)
        return test_results, new_w

    def residual(self) -> np.ndarray | None:
        return self._residual
