from iterative_cleaner_tpu.backends.base import CleanerBackend, make_backend

__all__ = ["CleanerBackend", "make_backend"]
