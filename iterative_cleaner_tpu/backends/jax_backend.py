"""The JAX/TPU backend — the point of the project.

One host→device transfer of the preprocessed cube; the whole per-iteration
pipeline (template build → closed-form fit/subtract → four diagnostics →
robust scalers → zap map) is a single jitted kernel (SURVEY.md §7.M2).  Two
execution modes:

- **stepwise** (default): one jit call per iteration, convergence bookkeeping
  on host — print/log parity with the reference loop, still ~zero interpreter
  overhead per step.
- **fused** (``cfg.fused``): the entire convergence loop runs on device as a
  ``lax.while_loop`` carrying a fixed (max_iter+1, nsub, nchan) weight-history
  ring buffer for the full-history cycle detection (§8.L10) — one dispatch
  for the whole clean, the benchmark configuration.

Dedispersion does not appear anywhere in the loop: all four diagnostics are
circular-shift invariant (§8.L8), so the kernel works entirely in the
dedispersed frame the host precompute produced.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.ops.stats import comprehensive_stats
from iterative_cleaner_tpu.ops.template import build_template, fit_and_subtract


def _step_from_template(D, w0, valid, template, chanthresh, subintthresh, *,
                        pulse_region, use_pallas=False):
    """Fit/subtract/stats/zap given an already-built template — shared by
    clean_step (which builds it densely every call) and the incremental
    fused loop (which carries it across iterations)."""
    if use_pallas:
        from iterative_cleaner_tpu.ops.pallas_kernels import (
            fused_fit_moments,
            pallas_route_status,
            use_interpret,
        )

        route_ok, route_why = pallas_route_status(D.shape[-1])
        if not route_ok:
            import warnings

            warnings.warn(
                f"pallas=True but the Pallas route is not viable here "
                f"({route_why}); using the XLA route", stacklevel=2)
            use_pallas = False
    if use_pallas:
        # valid passed in: the kernel emits scaler-ready (filled) maps, so
        # the XLA tail is exactly the FFT diagnostic + robust scalers.
        from iterative_cleaner_tpu.ops.stats import (
            fft_diagnostic,
            scale_and_combine,
        )

        centred, d_mean, d_std, d_ptp = fused_fit_moments(
            D, template, w0, valid, pulse_region=pulse_region,
            interpret=use_interpret())
        test = scale_and_combine(
            d_std, d_mean, d_ptp, fft_diagnostic(centred), valid,
            chanthresh, subintthresh)
        resid = None
    else:
        _amp, resid = fit_and_subtract(D, template, pulse_region)
        weighted = resid * w0[..., None]
        test = comprehensive_stats(weighted, valid, chanthresh, subintthresh)
    # set_weights_archive on an original-weights clone: zap where test >= 1;
    # NaN >= 1 is False -> never flags (§8.L3).
    new_w = jnp.where(test >= 1.0, 0.0, w0)
    return test, new_w, resid


step_from_template = partial(
    jax.jit, static_argnames=("pulse_region", "use_pallas"))(
        _step_from_template)


@partial(jax.jit, static_argnames=("pulse_region", "use_pallas"))
def clean_step(D, w0, valid, w_prev, chanthresh, subintthresh, *, pulse_region,
               use_pallas=False):
    """One cleaning iteration as a pure function (jit-compiled once).

    w_prev shapes the template (previous iteration's zaps); the stats always
    run against the frozen original weights w0 (§8.L11).  The thresholds are
    traced scalars — a threshold sweep reuses one compilation; only
    pulse_region (trace-time slicing) and shapes are static.

    use_pallas routes the fit/subtract/weight/centre/moments through the
    fused Pallas kernel (one HBM pass over the cube instead of ~5 — see
    ops/pallas_kernels.py); it does not materialise the residual, so the
    stepwise --unload_res path keeps the XLA route.
    """
    template = build_template(D, w_prev)
    return _step_from_template(
        D, w0, valid, template, chanthresh, subintthresh,
        pulse_region=pulse_region, use_pallas=use_pallas)


# Per-iteration budget of profile flips the incremental template update
# handles sparsely; beyond it the template is rebuilt densely.  Iteration 1
# typically zaps the bulk (dense rebuild), later iterations flip a handful
# (sparse).  512 profiles x nbin is a ~2 MB gather at north-star scale —
# noise next to the cube passes it replaces.
INCREMENTAL_TEMPLATE_BUDGET = 512


def _incremental_template(D, T_prev, w_prev, new_w):
    """Next iteration's template without re-reading the cube.

    The dense template is ``sum_sc w[s,c] * D[s,c,:]``; between iterations
    only the profiles whose weight flipped contribute a change, so
    ``T_next = T_prev + sum_changed (new_w - w_prev) * profile`` — a
    static-size gather of at most INCREMENTAL_TEMPLATE_BUDGET profiles
    (jnp.nonzero with a static ``size``).  Falls back to a dense rebuild
    (lax.cond: the unused branch does not execute outside vmap) when:

    - more profiles flipped than the budget (typically iteration 1), or
    - the sparse candidate is non-finite — an inf/NaN profile entering or
      leaving the template support makes inf-inf = NaN where the dense
      rebuild is finite, so any poisoned cube stays on the per-iteration
      dense path and keeps today's bit-exact behavior (SURVEY §8.L9's
      exclusions are unaffected).

    Float caveat (documented in docs/SCALING.md): on the sparse path the
    template's f32 rounding differs from a dense rebuild (add/remove vs
    one fused reduction).  Flag-mask invariance to template summation
    order is the empirically-pinned property that already covers the three
    dense lowerings; the fuzz corpus revalidates it for this path.
    """
    nbin = D.shape[-1]
    budget = min(INCREMENTAL_TEMPLATE_BUDGET, w_prev.size)
    delta = (new_w - w_prev).reshape(-1)
    nchanged = jnp.sum(delta != 0)
    idx = jnp.nonzero(delta != 0, size=budget, fill_value=0)[0]
    # Padded slots repeat index 0; zero their contribution explicitly.
    slot_live = jnp.arange(budget) < nchanged
    dvals = jnp.where(slot_live, delta[idx], jnp.zeros((), delta.dtype))
    profiles = D.reshape(-1, nbin)[idx]
    T_sparse = T_prev + jnp.matmul(
        dvals, profiles, precision=jax.lax.Precision.HIGHEST)
    sparse_ok = (nchanged <= budget) & jnp.all(jnp.isfinite(T_sparse))
    return jax.lax.cond(
        sparse_ok,
        lambda: T_sparse,
        lambda: build_template(D, new_w),
    )


dense_template = jax.jit(build_template)
# T_prev is donated (registered in analysis/contracts.ROUTE_DONATIONS —
# ICT009 fails if the alias vanishes at lowering): the carried template is
# dead the instant its successor exists, the (nbin,) output aliases it, and
# every caller (JaxCleaner.step, precompile_for) reassigns the carry
# immediately.  D / the weight maps stay caller-owned and undonated.
advance_template = jax.jit(_incremental_template, donate_argnums=(1,))


def precompile_for(shape, cfg, want_residual: bool = False) -> None:
    """Warm the in-memory executables clean_cube will run for a
    preprocessed cube of ``shape`` by a dummy call on device ZEROS — the
    shapes are known from the archive header, so compilation can overlap
    the host preprocessing instead of serializing after it.

    A dummy run (not ``lower().compile()``) because the AOT path does NOT
    seed the executable cache the normal call hits on this jax version
    (measured: the post-AOT first call still pays its own
    backend_compile); a same-aval call from this thread does.  On a zero
    cube the loop converges after one iteration (zero template → amp=1 →
    zero residual → NaN scalers → no flags → cycle hit), so the run cost
    is noise next to the compile.  Mirrors clean_cube's route fallbacks
    (pallas/incremental forced off for residual requests) so the warmed
    executables are exactly the ones used.  The dummy buffers are forced
    complete and dropped before returning."""
    nsub, nchan, nbin = shape
    dtype = _x64_dtype(cfg)
    D = jnp.zeros((nsub, nchan, nbin), dtype)
    w = jnp.zeros((nsub, nchan), dtype)
    v = w != 0  # the real paths derive validity this way — warm that tiny
    #             executable too, not just the big one
    t = jnp.zeros((nbin,), dtype)
    from iterative_cleaner_tpu.ops.pallas_kernels import resolve_use_pallas

    pr = tuple(cfg.pulse_region)
    use_pallas = resolve_use_pallas(cfg, nbin, want_residual)
    incremental = cfg.incremental_template and not want_residual
    if cfg.fused:
        out = fused_clean(
            D, w, v, 5.0, 5.0, max_iter=int(cfg.max_iter), pulse_region=pr,
            want_residual=want_residual, use_pallas=use_pallas,
            incremental=incremental)
        # Mirror run_fused's epilogue, including its history slice for the
        # dummy run's own iteration count (the real archive's count may
        # differ — that per-length slice is a ~tens-of-ms executable the
        # real call compiles itself; warming all max_iter+1 variants would
        # bloat the per-executable segfault budget for no real gain).
        np.asarray(out[1])
        np.asarray(out[6][: int(out[4]) + 1])
    elif incremental:
        np.asarray(dense_template(D, w))
        out = step_from_template(
            D, w, v, t, 5.0, 5.0, pulse_region=pr, use_pallas=use_pallas)
        np.asarray(out[1])
        # LAST: advance_template donates its T_prev argument, so the dummy
        # ``t`` is dead after this call — any warm that reads it must run
        # before.
        np.asarray(advance_template(D, t, w, w))
    else:
        out = clean_step(
            D, w, v, w, 5.0, 5.0, pulse_region=pr, use_pallas=use_pallas)
        np.asarray(out[1])


def start_precompile(shape, cfg, want_residual: bool = False):
    """Fire the executable warmup on a daemon thread; returns the Thread to
    join before the first device call (a still-in-flight warm call must not
    race a duplicate compile from the real call), or None when trivially
    inapplicable (non-jax backend, ICT_NO_PRECOMPILE=1, explicit
    chunk_block).  Every check that touches the device — backend init,
    device_memory_bytes, the >HBM routing guard, the dummy-headroom guard —
    runs INSIDE the thread, so a cold backend initialization overlaps the
    host preprocessing too instead of serializing before it.  Failures are
    swallowed — the real call compiles normally."""
    import os
    import threading

    if cfg.backend != "jax" or os.environ.get("ICT_NO_PRECOMPILE") == "1":
        return None
    if cfg.chunk_block:
        return None

    def _run():
        try:
            from iterative_cleaner_tpu.parallel.autoshard import (
                HBM_USABLE_FRACTION,
                chunk_block_subints,
                device_memory_bytes,
                working_set_bytes,
            )

            if cfg.auto_shard and chunk_block_subints(shape, cfg) is not None:
                return  # >HBM: routes to sharded/chunked, not warmed here
            hbm = device_memory_bytes()
            itemsize = 8 if cfg.x64 else 4
            if hbm is not None and (2 * working_set_bytes(shape, itemsize)
                                    > hbm * HBM_USABLE_FRACTION):
                # The dummy cube would crowd out the real one's headroom.
                return
            from iterative_cleaner_tpu.utils.compile_cache import (
                already_noted,
                inmemory_route_key,
                note_compiled_shape,
            )

            key = inmemory_route_key(shape, cfg, want_residual)
            if already_noted(key):
                # Executables for this exact route already compiled in this
                # process (and a cache drop clears _seen with them): a
                # directory of same-shape archives must not pay a dummy
                # cube allocation + run per archive.
                return
            # Account the warm's executables BEFORE compiling them: a due
            # compile-cache drop then lands here, not between the warm and
            # the real call.  The real call re-notes the identical key — no
            # double count toward the drop budget (a set), and the re-note
            # lands in telemetry as a compile_cache_key_hit BY DESIGN: the
            # real dispatch reuses (or joins) this warm's executables, which
            # is exactly what the hit counter measures.
            note_compiled_shape(key)
            precompile_for(shape, cfg, want_residual)
        except Exception:  # noqa: BLE001 — warmup only; real call recovers
            pass

    th = threading.Thread(target=_run, daemon=True, name="ict-precompile")
    th.start()
    return th


@partial(jax.jit, static_argnames=(
    "max_iter", "pulse_region", "want_residual", "use_pallas", "incremental"))
def fused_clean(
    D, w0, valid, chanthresh, subintthresh, *, max_iter, pulse_region,
    want_residual=False, use_pallas=False, incremental=False,
):
    """The whole convergence loop on device (lax.while_loop).

    Carry: (x, w_prev, template, history, test[, resid], loops, done).
    history[0] is the pre-loop weights — included in the cycle detection
    exactly as the reference seeds test_weights with them
    (iterative_cleaner.py:77-78).  The D-sized residual buffer is only
    carried when want_residual is set, so the benchmark configuration does
    not pay a second cube of HBM.

    ``incremental`` (static) carries the template across iterations and
    updates it from the handful of flipped profiles instead of re-reading
    the whole cube each iteration (_incremental_template) — one full cube
    pass per iteration eliminated after the first.  Keep it False under
    vmap (sweep/batch): vmapped lax.cond becomes a select that executes
    BOTH branches, paying the dense rebuild plus the gather.
    """
    if want_residual and use_pallas:
        raise ValueError("the Pallas-fused path does not materialise the "
                         "residual cube; use_pallas requires "
                         "want_residual=False")
    nsub, nchan = w0.shape
    history0 = jnp.zeros((max_iter + 1, nsub, nchan), w0.dtype).at[0].set(w0)
    n_extra = 1 if incremental else 0  # template slot in the carry

    def cond(carry):
        return (~carry[-1]) & (carry[0] < max_iter)

    def body(carry):
        x, w_prev = carry[0] + 1, carry[1]
        if incremental:
            template = carry[2]
        else:
            template = build_template(D, w_prev)
        history = carry[2 + n_extra]
        test, new_w, resid = _step_from_template(
            D, w0, valid, template, chanthresh, subintthresh,
            pulse_region=pulse_region, use_pallas=use_pallas,
        )
        row_live = jnp.arange(max_iter + 1) < x  # rows 0..x-1 are populated
        hit = jnp.any(row_live & jnp.all(new_w[None] == history, axis=(1, 2)))
        history = history.at[x].set(new_w)
        loops = jnp.where(hit, x, max_iter)
        out = (x, new_w)
        if incremental:
            out += (_incremental_template(D, template, w_prev, new_w),)
        out += (history, test)
        if want_residual:
            out += (resid,)
        return out + (loops, hit)

    init = (0, w0)
    if incremental:
        # Iteration 1's template is the dense build from the pre-loop
        # weights on both routes (bitwise identical); only iterations >= 2
        # diverge onto the sparse-update path.
        init += (build_template(D, w0),)
    init += (history0, jnp.zeros_like(w0))
    if want_residual:
        init += (jnp.zeros_like(D),)
    init += (max_iter, False)
    out = jax.lax.while_loop(cond, body, init)
    x, w_final = out[0], out[1]
    history, test = out[2 + n_extra], out[3 + n_extra]
    resid = out[4 + n_extra] if want_residual else None
    loops, done = out[-2], out[-1]
    return test, w_final, loops, done, x, resid, history


def _x64_dtype(cfg: CleanConfig):
    """cfg.x64 requires jax_enable_x64 to be set by the caller (env
    JAX_ENABLE_X64=1 or jax.config) — we refuse to flip process-global state
    mid-run, since it would silently retype every other computation in the
    process."""
    if not cfg.x64:
        return jnp.float32
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "CleanConfig(x64=True) needs float64 support enabled before any "
            "JAX computation: set JAX_ENABLE_X64=1 or "
            "jax.config.update('jax_enable_x64', True) at startup")
    return jnp.float64  # ict: f64-ok(explicit --x64 opt-in; parity docs cover it)


class JaxCleaner:
    """Stepwise backend: same protocol as NumpyCleaner, device-resident.

    With ``cfg.incremental_template`` (the default) the template is carried
    across ``step()`` calls and advanced from the flipped profiles
    (_incremental_template: same budget/non-finite dense fallback as the
    fused kernel) — the default CLI route sheds its per-iteration full-cube
    template read just like --fused.  Note the residual this backend
    returns is then computed from the sparse-advanced template;
    ``clean_cube`` forces the dense route whenever the caller requests a
    residual, keeping residual output bit-exact (the ulp envelope is
    documented for scores only)."""

    def __init__(self, D: np.ndarray, w0: np.ndarray, cfg: CleanConfig) -> None:
        from iterative_cleaner_tpu.ops.pallas_kernels import resolve_use_pallas

        self.cfg = cfg
        dtype = _x64_dtype(cfg)
        # The megakernel static this backend dispatches with (cfg.pallas is
        # tri-state; None = auto-on where it is a real optimisation).  An
        # explicit True on a non-viable shape still warns-and-falls-back
        # inside _step_from_template.
        self._use_pallas = resolve_use_pallas(cfg, D.shape[-1])
        self._D = jax.device_put(jnp.asarray(D, dtype))
        self._w0 = jax.device_put(jnp.asarray(w0, dtype))
        self._valid = jax.device_put(jnp.asarray(w0 != 0))
        self._residual = None
        self._tmpl = None     # carried template (device) …
        self._tmpl_w = None   # … and the device weights it was built for

    def step(self, w_prev: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        w_prev = jnp.asarray(w_prev, self._w0.dtype)
        if not self.cfg.incremental_template:
            test, new_w, resid = clean_step(
                self._D,
                self._w0,
                self._valid,
                w_prev,
                float(self.cfg.chanthresh),
                float(self.cfg.subintthresh),
                pulse_region=tuple(self.cfg.pulse_region),
                use_pallas=self._use_pallas,
            )
        else:
            if self._tmpl is None:
                template = dense_template(self._D, w_prev)
            else:
                template = advance_template(
                    self._D, self._tmpl, self._tmpl_w, w_prev)
            # Reassign the carry IMMEDIATELY: advance_template donated the
            # old self._tmpl, so it must never be passed again (a failed
            # step below must not leave a dead buffer in the carry).
            self._tmpl, self._tmpl_w = template, w_prev
            test, new_w, resid = step_from_template(
                self._D,
                self._w0,
                self._valid,
                template,
                float(self.cfg.chanthresh),
                float(self.cfg.subintthresh),
                pulse_region=tuple(self.cfg.pulse_region),
                use_pallas=self._use_pallas,
            )
        self._residual = resid  # stays on device unless fetched
        return np.asarray(test), np.asarray(new_w)

    def residual(self) -> np.ndarray | None:
        return None if self._residual is None else np.asarray(self._residual)


def run_fused(D, w0, cfg: CleanConfig, want_residual: bool = False):
    """One-dispatch clean; returns (test, weights, loops, converged, iters,
    history[, residual]) as host values — history is the populated prefix of
    the on-device ring buffer (pre-loop weights first, §8.L10), so the fused
    mode dumps the same mask-history audit trail as the stepwise loop.
    Accepts numpy or device-resident arrays (pass device arrays to keep the
    cube upload out of timing loops)."""
    from iterative_cleaner_tpu.ops.pallas_kernels import resolve_use_pallas

    dtype = _x64_dtype(cfg)
    D = jnp.asarray(D, dtype)
    w0 = jnp.asarray(w0, dtype)
    test, w_final, loops, done, x, resid, history = fused_clean(
        D,
        w0,
        w0 != 0,
        float(cfg.chanthresh),
        float(cfg.subintthresh),
        max_iter=int(cfg.max_iter),
        pulse_region=tuple(cfg.pulse_region),
        want_residual=want_residual,
        use_pallas=resolve_use_pallas(cfg, D.shape[-1], want_residual),
        # A residual must come from a dense template (bit-exact output;
        # the sparse path's ulp envelope is documented for scores only).
        incremental=cfg.incremental_template and not want_residual,
    )
    n_iters = int(x)
    out = (
        np.asarray(test),
        np.asarray(w_final),
        int(loops),
        bool(done),
        n_iters,
        # rows 0..n_iters of the ring buffer are populated (row 0 = w0)
        np.asarray(history[: n_iters + 1]),
    )
    if want_residual:
        out = out + (np.asarray(resid),)
    return out
