"""ict-serve: the long-running cleaning service.

Every other entry point (CLI, driver.run, the directory batchers) is
one-shot — load, clean, exit — paying cold compiles and device setup per
invocation.  Real RFI-mitigation deployments are continuous pipelines
(cf. arXiv:1701.08197), so this subsystem keeps one process alive:

- :mod:`.context`   — ReplicaContext: one replica's identity + shared
                      mutable state (job index, idempotency map, demotion
                      machine, drain flag) — scheduler/worker/pool are
                      constructed from it alone, so fleet tests stand up
                      3+ replicas in one process (fleet/ routes across
                      them)
- :mod:`.jobs`      — job records + on-disk spool (restart-safe manifest)
- :mod:`.scheduler` — shape-bucketed admission queue (dp-slice / deadline)
- :mod:`.worker`    — fault-isolated dispatch (retry, oracle fallback)
- :mod:`.pool`      — warm executable pool (startup precompile)
- :mod:`.api`       — stdlib-HTTP endpoints (/jobs, /jobs/<id>/trace,
                      /healthz, Prometheus /metrics, legacy /metrics.json)
- :mod:`.daemon`    — lifecycle + the ``ict-serve`` CLI

Observability (obs/ package, docs/OBSERVABILITY.md): every job carries a
telemetry trace_id from submission through dispatch and per-iteration
forensics; ``--telemetry`` appends the JSON-lines event log.

The service is routing, not math: masks stay bit-identical to the numpy
oracle on every served route (the sharded bucket dispatch is pinned by
tests/test_parallel.py; the degraded route IS the oracle).
"""

from iterative_cleaner_tpu.service.jobs import Job, JobSpool
from iterative_cleaner_tpu.service.context import ReplicaContext, ServiceBusy
from iterative_cleaner_tpu.service.daemon import CleaningService, ServeConfig

__all__ = ["Job", "JobSpool", "CleaningService", "ServeConfig",
           "ReplicaContext", "ServiceBusy"]
