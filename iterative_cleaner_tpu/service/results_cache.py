"""Replica-side content-addressed result cache (ROADMAP item 2's reuse
half; keys from ingest/cas.py).

One record per :func:`~iterative_cleaner_tpu.ingest.cas.cube_key`: the
FINAL weights mask (post bad-parts sweep -- exactly what the emit path
hands the output policy) plus the scalar result fields a job manifest
reports.  The dispatch worker checks it before any device dispatch; a
hit re-emits the cached mask against the freshly decoded archive, so the
written output is byte-identical to a fresh clean while the device is
never touched (the key already covers cube bytes + config + version, so
"identical" is by construction, and the shadow auditor can still be
asked to prove it per job).

Two tiers, both bounded:

- an in-memory LRU of ``capacity`` records (masks are (nsub, nchan)
  f32 maps -- KBs to a few MBs each, nothing like cube residency);
- optional spool persistence under ``<spool>/results-cache/`` -- one
  ``<key>.npz`` next to the job index, same ``.part``-rename atomicity
  as job manifests, oldest files swept beyond ``2 x capacity`` -- so a
  restarted replica keeps answering yesterday's campaign from disk.

Invalidation is upstream: the key's salt (ingest/cas.py) folds in the
package version and every mask-affecting config field, so stale entries
go unreachable rather than wrong; the LRU/file sweeps reclaim them.
"""

from __future__ import annotations

import collections
import json
import os
import threading

import numpy as np

#: Persisted files kept per cache directory, as a multiple of the
#: in-memory capacity (disk is the warm-restart tier, not an archive).
DISK_KEEP_FACTOR = 2

#: The scalar fields a cache record carries next to the mask.
_META_FIELDS = ("loops", "converged", "rfi_frac", "termination",
                "origin_job_id")


class ResultCache:
    """Bounded LRU of cleaned-mask records, keyed by content address.
    Thread-safe: the loader/worker/HTTP threads share one instance per
    replica (it lives on the ReplicaContext, never process-global)."""

    def __init__(self, capacity: int, root: str = "") -> None:
        self.capacity = max(int(capacity), 0)
        self.root = root if self.capacity else ""
        # RLock, deliberately: the LRU trim takes it lexically (the
        # ICT007 discipline, the context._trim_idem_locked pattern)
        # while its callers already hold it.
        self._lock = threading.RLock()
        self._mem: collections.OrderedDict = collections.OrderedDict()  # ict: guarded-by(self._lock)
        # Approximate persisted-file count so the disk sweep (a full
        # listdir + stat pass) only runs when the budget may actually be
        # exceeded, not on every job completion.  None = not counted
        # yet; key overwrites over-count, which only sweeps early.
        self._disk_files: int | None = None  # ict: guarded-by(self._lock)
        if self.root:
            os.makedirs(self.root, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def _path(self, key: str) -> str | None:
        # Keys are hex digests we minted, but the path join stays
        # defensive anyway (the spool's job-id rule).
        name = f"{key}.npz"
        if not self.root or os.path.basename(name) != name \
                or key.startswith("."):
            return None
        return os.path.join(self.root, name)

    def get(self, key: str) -> dict | None:
        """The cached record for ``key`` (memory first, then disk --
        a disk hit is promoted), or None.  Returned dicts are copies;
        the weights array is shared read-only by convention."""
        if not self.enabled or not key:
            return None
        with self._lock:
            rec = self._mem.get(key)
            if rec is not None:
                self._mem.move_to_end(key)
                return dict(rec)
        rec = self._load(key)
        if rec is None:
            return None
        with self._lock:
            self._mem[key] = rec
            self._mem.move_to_end(key)
            self._trim_mem_locked()
        return dict(rec)

    def put(self, key: str, weights: np.ndarray, *, loops: int,
            converged: bool, rfi_frac: float, termination: str,
            origin_job_id: str = "") -> None:
        """Store one finished clean's record (write-through to disk when
        persistence is on).  Persistence failures are swallowed: the
        cache is an optimization, the spool manifest stays the durable
        record of the job itself."""
        if not self.enabled or not key:
            return
        rec = {
            "weights": np.ascontiguousarray(np.asarray(weights)),
            "loops": int(loops),
            "converged": bool(converged),
            "rfi_frac": float(rfi_frac),
            "termination": str(termination),
            "origin_job_id": str(origin_job_id),
        }
        with self._lock:
            self._mem[key] = rec
            self._mem.move_to_end(key)
            self._trim_mem_locked()
        self._persist(key, rec)

    def _trim_mem_locked(self) -> None:
        # Takes the (reentrant) lock itself so the eviction stays
        # lexically guarded; every caller already holds it.
        with self._lock:
            while len(self._mem) > self.capacity:
                self._mem.popitem(last=False)

    # --- the disk tier ---

    def _persist(self, key: str, rec: dict) -> None:
        path = self._path(key)
        if path is None:
            return
        tmp = f"{path}.part"
        try:
            meta = {f: rec[f] for f in _META_FIELDS}
            # A file handle, not the path: np.savez would append ".npz"
            # to a string name and break the .part-rename atomicity.
            with open(tmp, "wb") as fh:
                np.savez(fh, weights=rec["weights"],
                         meta=np.frombuffer(
                             json.dumps(meta).encode(), dtype=np.uint8))
            os.replace(tmp, path)
            keep = self.capacity * DISK_KEEP_FACTOR
            with self._lock:
                if self._disk_files is None:
                    self._disk_files = len(
                        [n for n in os.listdir(self.root)
                         if n.endswith(".npz")])
                else:
                    self._disk_files += 1
                due = self._disk_files > keep
            if due:
                self._sweep_disk()
        except Exception:  # noqa: BLE001 -- persistence is best-effort
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _load(self, key: str) -> dict | None:
        path = self._path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                weights = np.asarray(z["weights"])
                meta = json.loads(bytes(np.asarray(z["meta"])).decode())
            return {"weights": weights,
                    **{f: meta.get(f) for f in _META_FIELDS}}
        except Exception:  # noqa: BLE001 -- a corrupt entry is a miss
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _sweep_disk(self) -> None:
        """Drop the oldest persisted entries beyond the disk budget
        (mtime order; the spool-trim rationale).  Called only when the
        in-memory file counter says the budget may be exceeded; the
        counter is re-anchored to the true count afterwards."""
        keep = self.capacity * DISK_KEEP_FACTOR
        try:
            names = [n for n in os.listdir(self.root)
                     if n.endswith(".npz")]
            if len(names) > keep:
                stamped = sorted(
                    (os.path.getmtime(os.path.join(self.root, n)), n)
                    for n in names)
                for _mtime, name in stamped[: len(names) - keep]:
                    try:
                        os.remove(os.path.join(self.root, name))
                        names.remove(name)
                    except OSError:
                        continue
            with self._lock:
                self._disk_files = len(names)
        except OSError:
            pass
