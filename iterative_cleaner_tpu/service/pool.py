"""Warm executable pool: precompile declared shape classes at startup.

A serving daemon's reason to exist is that steady-state requests never see
a cold XLA compile (20-40 s per kernel on TPU).  Operators declare the
shape classes their telescope emits (``--warm NSUBxNCHANxNBIN``), and the
pool compiles, before the API accepts traffic, every batched executable
the scheduler can dispatch for them: one per power-of-two batch size up to
the bucket cap (the closed set scheduler.pow2_chunks emits).

Mechanics are the SurgicalCleaner precompile path's (backends/jax_backend
.precompile_for): a DUMMY RUN on device zeros — the AOT lower().compile()
path does not seed the executable cache the real call hits on this jax
version — guarded by the same compile-cache accounting
(already_noted/note_compiled_shape) under the same key the real bucket
dispatch notes (compile_cache.batch_route_key), so a warmed shape is
recognised and never re-warmed, and the ~70-executable segfault budget
sees the warm compiles too.  On a zero cube the fused loop converges after
one iteration, so the run cost is noise next to the compile.
"""

from __future__ import annotations

import sys

import numpy as np

from iterative_cleaner_tpu.obs import tracing
from iterative_cleaner_tpu.utils.compile_cache import (
    already_noted,
    batch_route_key,
    forget_noted,
    note_compiled_shape,
)


def warm_batch_sizes(bucket_cap: int) -> list[int]:
    """Every batch size the scheduler can emit for one shape: ALL powers of
    two up to the cap — deadline flushes chunk to any pow2 size (a 3-cube
    bucket under cap 8 emits [2, 1]), not just the cap itself."""
    return [1 << k for k in range(bucket_cap.bit_length())
            if (1 << k) <= bucket_cap]


class WarmPool:
    """Constructed purely from a :class:`~.context.ReplicaContext` — the
    pool holds no process-global state, so fleet tests can warm three
    replicas' pools in one process without them seeing each other."""

    def __init__(self, ctx, bucket_cap: int) -> None:
        self.ctx = ctx
        self.cfg = ctx.clean_cfg
        self.mesh = ctx.mesh
        self.bucket_cap = int(bucket_cap)
        self.quiet = ctx.serve_cfg.quiet  # gates info lines; warnings stay loud
        self.declared: tuple = ()   # shape classes declared at startup

    def warm_shape(self, shape) -> int:
        """Precompile the bucket executables for one (nsub, nchan, nbin)
        shape class; returns how many batch sizes actually compiled.
        Failures are swallowed per shape — warming is an optimization, the
        real dispatch compiles normally."""
        from iterative_cleaner_tpu.parallel.sharded import sharded_clean

        shape = tuple(int(v) for v in shape)
        compiled = 0
        with tracing.phase("service_warm"):
            for bsz in warm_batch_sizes(self.bucket_cap):
                key = batch_route_key((bsz, *shape), self.cfg)
                if already_noted(key):
                    continue
                # Note BEFORE compiling (start_precompile's rule): a due
                # compile-cache drop lands here, not between the warm and
                # a real dispatch of the same key.
                note_compiled_shape(key)
                try:
                    Db = np.zeros((bsz, *shape), np.float32)
                    w0b = np.zeros((bsz, *shape[:2]), np.float32)
                    with tracing.compile_scope(
                            tracing.shape_bucket_label((bsz, *shape))):
                        sharded_clean(Db, w0b, self.cfg, self.mesh)
                    # Startup is the right time to pay the per-bucket
                    # executable analysis (obs/memory: bytes/FLOPs gauges
                    # on /metrics, attached to manifests later): the
                    # operator already opted into compile cost by
                    # declaring the shape, and the memoized answer makes
                    # the first real dispatch analysis-free.
                    from iterative_cleaner_tpu.obs import (
                        memory as obs_memory,
                    )

                    obs_memory.analyze_batch_route((bsz, *shape), self.cfg)
                    compiled += 1
                except Exception as exc:  # noqa: BLE001 — best-effort, and
                    # per size: one failed compile must neither skip the
                    # remaining sizes nor leave its key claiming an
                    # executable that was never built.
                    forget_noted(key)
                    print(f"ict-serve: warmup for shape {shape} batch "
                          f"{bsz} failed: {exc}", file=sys.stderr)
        return compiled

    def warm_startup(self, shapes) -> None:
        from iterative_cleaner_tpu.utils.compile_cache import (
            DISTINCT_SHAPE_LIMIT,
        )

        self.declared = tuple(tuple(int(v) for v in s) for s in shapes)
        n_keys = len(self.declared) * len(warm_batch_sizes(self.bucket_cap))
        if n_keys >= DISTINCT_SHAPE_LIMIT:
            # The executable-budget drop (jax.clear_caches every
            # DISTINCT_SHAPE_LIMIT distinct keys — the virtual-CPU segfault
            # guard) will fire DURING this warmup and discard earlier
            # shapes' executables: only the last ~budget keys stay live
            # (is_warm reports honestly; the persistent disk cache still
            # shortens the re-compiles).  Say so instead of promising a
            # warmth that self-destructs.
            print(f"ict-serve: warning: {len(self.declared)} declared "
                  f"shapes x {len(warm_batch_sizes(self.bucket_cap))} batch "
                  f"sizes = {n_keys} executables exceeds the in-process "
                  f"budget ({DISTINCT_SHAPE_LIMIT}); earlier shapes will "
                  "re-compile on first dispatch — declare fewer shapes or "
                  "lower --bucket_cap", file=sys.stderr)
        for shape in self.declared:
            n = self.warm_shape(shape)
            if n and not self.quiet:
                print(f"ict-serve: warmed shape {shape} "
                      f"({n} batch-size executables)", file=sys.stderr)

    def is_warm(self, shape) -> bool:
        """Whether EVERY bucket executable for this shape is live right now.
        Computed from the compile-cache guard's accounting rather than a
        local set: a DISTINCT_SHAPE_LIMIT drop (jax.clear_caches at 20
        distinct executable keys — it also clears the accounting) silently
        discards warmed executables, and a stale local set would keep
        reporting warmth that no longer exists.  After a drop the next
        dispatch of each size re-warms naturally (and re-notes the key)."""
        shape = tuple(int(v) for v in shape)
        return all(
            already_noted(batch_route_key((bsz, *shape), self.cfg))
            for bsz in warm_batch_sizes(self.bucket_cap))

    def warm_shapes_now(self) -> list[tuple]:
        """The declared shapes currently fully warm (the /healthz view)."""
        return [s for s in self.declared if self.is_warm(s)]
