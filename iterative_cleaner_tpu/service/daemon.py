"""Daemon lifecycle + the ``ict-serve`` CLI.

Thread layout (all daemonic; ``stop()`` is graceful):

- N loader threads: decode + preprocess submitted archives (host-side,
  independent per file — the parallel/batch thread-pool idiom) and offer
  the cubes to the shape-bucketed scheduler;
- 1 tick thread: fires the scheduler's deadline flushes;
- 1 dispatch worker: runs flushed buckets on the mesh (service/worker.py);
- the ThreadingHTTPServer's per-request threads (service/api.py).

Jobs the daemon accepted but had not finished when it died stay in the
on-disk spool as ``pending``/``running`` manifests; the next start replays
them (service/jobs.py), so a restart loses no accepted work.

``python -m iterative_cleaner_tpu serve --smoke`` runs the whole stack
against one synthetic archive over real HTTP and verifies the returned
mask bit-identical to the numpy oracle — the offline health check CI and
operators share.
"""

from __future__ import annotations

import argparse
import os
import queue
import sys
import threading
import time
from dataclasses import dataclass, field

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.obs import (
    events,
    flight,
    memory as obs_memory,
    tracing,
)
from iterative_cleaner_tpu.service.context import (  # noqa: F401 — ServiceBusy
    ReplicaContext,                  # re-exported for compatibility: the
    ServiceBusy,                     # API layer and embedders import it here
)
from iterative_cleaner_tpu.service.jobs import TERMINAL, Job
from iterative_cleaner_tpu.service.scheduler import (
    ShapeBucketScheduler,
    bucket_label,
)
from iterative_cleaner_tpu.service.worker import DispatchWorker

_STOP = object()

#: Serializes the loader pool's one-time lazy `import jax` chain — see
#: the comment in :meth:`CleaningService._load_loop`.
_LOADER_IMPORT_LOCK = threading.Lock()


@dataclass
class ServeConfig:
    spool_dir: str = "./ict_serve_spool"
    host: str = "127.0.0.1"
    port: int = 8750                 # 0 = ephemeral (tests)
    replica_id: str = ""             # fleet identity on /healthz and every
                                     # 202 (docs/SERVING.md "Fleet");
                                     # "" = mint one per process life
    bucket_cap: int = 0              # 0 = the mesh's dp extent
    coalesce: int = 1                # coalescing rung (ROADMAP item 2):
                                     # pow2 factor on the flush threshold —
                                     # one dispatch packs dp_cap x coalesce
                                     # same-shape cubes, each device
                                     # vmapping `coalesce` archives
    result_cache: int = 256          # content-addressed result cache
                                     # entries kept per replica (0 = off;
                                     # ingest/cas.py keys, persisted under
                                     # <spool>/results-cache)
    deadline_s: float = 2.0          # max wait before a partial bucket flushes
    loaders: int = 2
    warm_shapes: tuple = ()          # (nsub, nchan, nbin) classes to precompile
    dispatch_retries: int = 2
    retry_backoff_s: float = 0.25
    demote_after: int = 2            # consecutive bucket failures -> oracle mode
    spool_keep: int = 10000          # terminal manifests kept as job history
    max_open_jobs: int = 64          # admission cap (0 = unbounded): bounds
                                     # decoded-cube host residency; size it
                                     # to host RAM / cube size
    alert_iters: int = 2             # streaming sessions: bounded provisional
                                     # clean-pass iterations per block
    root: str = ""                   # when set, submitted paths must resolve
                                     # under this directory (the non-loopback
                                     # trust boundary)
    telemetry: str = ""              # JSON-lines event-log path (obs/events);
                                     # "" = honor ICT_TELEMETRY / disabled
    audit_rate: float = -1.0         # shadow-oracle audit sampling fraction
                                     # (obs/audit): < 0 = honor the
                                     # ICT_AUDIT_RATE env (default 0); a
                                     # per-job {"audit": true} always audits
    quiet: bool = False
    clean: CleanConfig = field(
        default_factory=lambda: CleanConfig(backend="jax"))


class CleaningService:
    """The persistent cleaning daemon; see the module docstring for the
    thread layout and docs/SERVING.md for the operator contract."""

    def __init__(self, serve_cfg: ServeConfig, mesh=None) -> None:
        self.serve_cfg = serve_cfg
        self.clean_cfg = serve_cfg.clean
        # ALL per-replica mutable state (job index, idempotency map,
        # demotion machine, drain flag) lives on the explicit context —
        # the scheduler/worker/pool are constructed from it alone, so N
        # replicas coexist in one process (service/context.py).  This
        # object keeps only lifecycle: threads, the HTTP server, wiring.
        self.ctx = ReplicaContext(serve_cfg, mesh=mesh)
        self.started_s = time.time()   # re-stamped at start(); /healthz uptime
        self.bucket_cap = 1
        self.port = serve_cfg.port
        self.pool = None
        self._load_q: queue.Queue = queue.Queue()
        self._threads: list[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._server = None
        self.scheduler = None
        self.worker = None
        self.sessions = None

    # Compatibility views onto the context (tests and embedders predate
    # the ReplicaContext split; the context is the single owner).
    @property
    def spool(self):
        return self.ctx.spool

    @property
    def mesh(self):
        return self.ctx.mesh

    @property
    def replica_id(self) -> str:
        return self.ctx.replica_id

    @property
    def backend_mode(self) -> str:
        return self.ctx.backend_mode

    @property
    def auditor(self):
        return self.ctx.auditor

    @property
    def profile_root(self) -> str:
        return self.ctx.profile_root

    @property
    def flight_dir(self) -> str:
        return self.ctx.flight_dir

    @property
    def repro_dir(self) -> str:
        return self.ctx.repro_dir

    @property
    def _jobs(self):
        return self.ctx._jobs

    @property
    def _jobs_lock(self):
        return self.ctx._jobs_lock

    # --- lifecycle ---

    def start(self) -> None:
        # Single-daemon guard FIRST: a second daemon on the same spool
        # would sweep this one's atomic-write temps and re-dispatch its
        # running jobs before even failing to bind the port.
        self.spool.acquire_exclusive()
        try:
            self._start_locked()
        except BaseException:
            # A mid-start failure (e.g. EADDRINUSE at the HTTP bind, after
            # warmup and spool replay) must not leak the flock or the
            # already-started threads — a corrected retry on the same
            # spool would otherwise see "already served" from a dead
            # service object.
            try:
                self.stop()
            except Exception:  # noqa: BLE001 — surface the original error
                pass
            raise

    def _start_locked(self) -> None:
        self.started_s = time.time()
        # Unconditional: telemetry="" must MEAN "honor ICT_TELEMETRY /
        # disabled" (the ServeConfig contract) even when an earlier
        # service in this process configured an explicit sink — a
        # restarted daemon must not silently inherit its predecessor's
        # log file.
        events.configure(self.serve_cfg.telemetry or None)
        flight.note("daemon_starting", spool=self.spool.root,
                    backend=self.backend_mode,
                    replica_id=self.replica_id)
        if self.backend_mode == "jax":
            # Compile accounting on /metrics (compiles, compile seconds per
            # shape bucket, persistent-cache events).  JAX path only: the
            # numpy service stays jax-import-free.
            tracing.install_compile_listener()
            # The CLI front-door wedge guard (utils/device_probe.py): a hung
            # probe with indeterminable liveness means the next jax call may
            # hang the daemon — that, and only that, degrades the whole
            # service to the numpy oracle.  A plain "demoted" keeps the jax
            # route: it is pinned to CPU, masks identical, wall-clock not.
            from iterative_cleaner_tpu.utils.device_probe import (
                ensure_responsive_backend,
            )

            if ensure_responsive_backend() == "demote_failed":
                print("ict-serve: backend liveness indeterminable after a "
                      "hung probe; serving via the numpy oracle",
                      file=sys.stderr)
                self.ctx.demote_for_liveness()
        # An explicit --bucket_cap is honored on EVERY backend (a numpy
        # replica in a fleet test can park cubes in a wide bucket); the
        # default stays backend-dependent: the mesh's dp extent for jax,
        # 1 for the oracle.
        cap = self.serve_cfg.bucket_cap or 1
        if self.backend_mode == "jax":
            if self.ctx.mesh is None:
                from iterative_cleaner_tpu.parallel.mesh import make_mesh

                # make_mesh is this daemon's first in-process device read;
                # its internal init_watchdog turns a wedged-tunnel freeze
                # into a structured warning (ICT_INIT_TIMEOUT_S) instead
                # of a silent never-came-up.
                self.ctx.mesh = make_mesh()
            cap = self.serve_cfg.bucket_cap or max(
                int(self.ctx.mesh.shape["dp"]), 1)
        self.scheduler = ShapeBucketScheduler(
            cap, self.serve_cfg.deadline_s, self._on_flush,
            coalesce=self.serve_cfg.coalesce)
        # The pow2 clamp lives in the scheduler (the mechanism that owns
        # the invariant); the warm pool reads the clamped value so the
        # precompiled batch-size set matches the sizes actually emitted.
        self.bucket_cap = self.scheduler.bucket_cap
        if self.backend_mode == "jax":
            from iterative_cleaner_tpu.service.pool import WarmPool

            self.pool = WarmPool(self.ctx, self.bucket_cap)
            self.pool.warm_startup(self.serve_cfg.warm_shapes)
        from iterative_cleaner_tpu.service.sessions import SessionManager

        # Streaming sessions (docs/SERVING.md "Streaming sessions"): spool-
        # backed under the job spool, so the single-daemon flock covers them
        # and a restart finds the replay log in place.  The cfg_provider
        # re-reads backend_mode on every session touch, so both the startup
        # liveness demotion and a RUNTIME service-wide demotion
        # (note_dispatch_failure) reach streaming passes too.
        self.sessions = SessionManager(
            os.path.join(self.serve_cfg.spool_dir, "sessions"),
            self.clean_cfg.replace(backend=self.backend_mode),
            alert_iters=self.serve_cfg.alert_iters,
            quiet=self.serve_cfg.quiet,
            cfg_provider=lambda: self.clean_cfg.replace(
                backend=self.backend_mode))
        self.worker = DispatchWorker(self.ctx)
        # Spool trim + replay run BEFORE any thread starts: the trim's
        # .json.part sweep is only safe while no writer thread exists (the
        # invariant jobs.trim documents), and the worker object's _fail
        # needs no running thread.  One directory scan feeds both halves —
        # with a 10k-manifest history, scanning twice would double the
        # pre-API startup I/O.  Replayed jobs just queue; the loaders
        # drain them once started below.
        spooled = self.spool.all_jobs()
        self.spool.trim(self.serve_cfg.spool_keep, jobs=spooled)
        # The idempotency map is rebuilt over EVERY manifest, terminal
        # included: a router failover retry of a job that in fact
        # finished before the restart must dedupe to the finished
        # manifest, never trigger a second run.
        for job in spooled:
            self.ctx.remember_idem(job)
        # Recovered jobs keep their original (older, time-sortable) ids,
        # so they drain ahead of new traffic of the same shape.
        for job in self.spool.recover(jobs=spooled):
            self.ctx.index(job)
            try:
                # Replayed manifests are re-validated against the CURRENT
                # --root (the boundary may have changed across restarts,
                # and old manifests predate it).
                job.path = self._check_root(job.path)
            except ValueError as exc:
                self.worker._fail(job, str(exc))
                continue
            self._load_q.put(job)
            tracing.count("service_jobs_recovered")
        # The shadow auditor always exists (a per-job {"audit": true} must
        # work even at rate 0); idle it is one blocked queue.get.  Started
        # HERE, after the trim/replay block above, because _audit_one
        # writes spool manifests — the trim's .part sweep is only safe
        # while no writer thread exists (the invariant jobs.trim
        # documents).
        from iterative_cleaner_tpu.obs.audit import ShadowAuditor

        # Pre-register the correctness-health counters at 0 so they are
        # PRESENT on the exposition from the first scrape.  The fleet's
        # critical alert rules (audit_divergence, backend_demoted) are
        # gt-0 thresholds over these series; a lazily-registered counter
        # would vanish across a clean restart and freeze-on-missing
        # would pin an already-fired alert forever instead of resolving
        # it against the restarted replica's explicit 0.
        tracing.count("audit_divergences", 0)
        tracing.count("service_backend_demotions", 0)
        # Same lesson for the cost-accounting plane (ISSUE 15): every
        # ict_cost_* family is registered at 0 before the first scrape,
        # so the fleet's tenant-budget gt-thresholds can resolve against
        # a restarted replica's explicit 0 instead of freezing on a
        # missing series.  The ledger itself resumes its spool-persisted
        # lifetime aggregates separately (GET /costs).
        self.ctx.cost_ledger.register_counters()
        self.ctx.auditor = ShadowAuditor(
            self.spool, self.repro_dir,
            on_divergence=self.ctx.note_audit_divergence,
            quiet=self.serve_cfg.quiet)
        self.ctx.auditor.start()
        self._threads.append(self.ctx.auditor)
        self.worker.start()
        self._threads.append(self.worker)
        for i in range(max(self.serve_cfg.loaders, 1)):
            th = threading.Thread(target=self._load_loop, daemon=True,
                                  name=f"ict-serve-load-{i}")
            th.start()
            self._threads.append(th)
        th = threading.Thread(target=self._tick_loop, daemon=True,
                              name="ict-serve-tick")
        th.start()
        self._threads.append(th)
        from iterative_cleaner_tpu.service.api import make_http_server

        self._server = make_http_server(
            self, self.serve_cfg.host, self.serve_cfg.port)
        self.port = self._server.server_address[1]
        th = threading.Thread(target=self._server.serve_forever, daemon=True,
                              name="ict-serve-http")
        th.start()
        self._threads.append(th)
        if not self.serve_cfg.quiet:
            print(f"ict-serve: replica {self.replica_id} listening on "
                  f"http://{self.serve_cfg.host}:{self.port} "
                  f"(backend={self.backend_mode}, bucket_cap="
                  f"{self.bucket_cap}, spool={self.spool.root})",
                  file=sys.stderr)

    def stop(self) -> None:
        """Graceful stop: the API closes, threads drain their queues' poison
        pills, and any still-unfinished job stays in the spool for the next
        life (restart-resume is the durability story, not a shutdown barrier)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        self._stop_evt.set()
        for _ in range(max(self.serve_cfg.loaders, 1)):
            self._load_q.put(_STOP)
        if self.worker is not None:
            self.worker.stop()
        if self.auditor is not None:
            self.auditor.stop()
        stuck = []
        for th in self._threads:
            th.join(timeout=10)
            if th.is_alive():
                stuck.append(th.name)
        # The showback record survives the shutdown (restart-resume is
        # the ledger's contract too): flushed AFTER the worker joins, so
        # the last served jobs' records make it to disk; a no-op when
        # nothing is dirty.
        self.ctx.cost_ledger.flush()
        if stuck:
            # A live thread may still be WRITING spool manifests; releasing
            # the flock would let a successor daemon's .part sweep and
            # running-job replay race it (the exclusivity trim() depends
            # on).  Keep the lock — the kernel frees it at process exit.
            print(f"ict-serve: threads still running after stop "
                  f"({', '.join(stuck)}); keeping the spool lock until "
                  "process exit", file=sys.stderr)
        else:
            self.spool.release_exclusive()

    # --- submission / inspection (the API's surface) ---

    def submit(self, path: str, profile: bool = False,
               audit: bool = False, idempotency_key: str = "",
               trace_id: str = "", tenant: str = "",
               shape: list | tuple | None = None,
               synthetic: bool = False) -> Job:
        # A draining replica accepts no NEW work (503; the router reads the
        # same flag off /healthz and stops placing here) — already-accepted
        # jobs keep running to completion (docs/SERVING.md "Fleet").
        if self.ctx.is_draining():
            tracing.count("service_jobs_refused")
            raise ServiceBusy(
                f"replica {self.replica_id} is draining; no new admissions")
        path = self._check_root(path)
        # Idempotent re-submission (the router's failover path): the same
        # key returns the already-admitted job — open OR terminal (the
        # spool manifest outlives retire()) — instead of running it twice.
        if idempotency_key:
            prior = self.ctx.idem_job_id(idempotency_key)
            if prior is not None:
                known = self.job(prior)
                if known is not None:
                    tracing.count("service_jobs_deduped")
                    return known
        # The trace context is minted at the entry point unless the
        # submitter carried one across the router hop (X-ICT-Trace); it
        # rides on the job through every layer (admission, dispatch,
        # iteration events) — echoed in the 202 response and header.
        # ``profile`` asks for a jax.profiler capture around this job's
        # dispatch (obs/profiling); the artifact dir lands on the manifest.
        # ``audit`` asks for a shadow-oracle parity replay after it serves
        # (obs/audit; ICT_AUDIT_RATE / --audit_rate samples the rest).
        job = self.ctx.new_job(path, profile=profile, audit=audit,
                               idempotency_key=idempotency_key,
                               trace_id=trace_id, tenant=tenant,
                               synthetic=synthetic)
        dup_id = self.ctx.admit(job, idempotency_key)
        if dup_id is not None:
            # Lost an admission race on the same key: serve the winner.
            known = self.job(dup_id)
            if known is not None:
                tracing.count("service_jobs_deduped")
                return known
            raise ValueError(
                f"idempotency key {idempotency_key!r} maps to a pruned "
                "job manifest; resubmit with a fresh key")
        try:
            self.spool.save(job)
        except Exception:
            # Roll the admission back: a job that was never made durable is
            # also never enqueued, so leaving it indexed would leak one
            # max_open_jobs slot per failed save until restart.
            self.ctx.rollback(job, idempotency_key)
            raise
        tracing.count("service_jobs_submitted")
        if events.active():
            # The replay contract (proving/traces.py): this event must
            # carry everything a re-issue needs — arrival ts (the line's
            # own "ts"), tenant, the idempotency key, the replica's
            # config salt, and the declared shape/bucket hint — at every
            # entry point (POST /jobs directly, via the router, campaign
            # orchestrator submissions all funnel through here).
            shape_hint = ([int(v) for v in shape]
                          if shape is not None and len(shape) == 3 else [])
            events.emit("job_submitted", trace_id=job.trace_id,
                        job_id=job.id, path=path,
                        replica_id=self.replica_id,
                        entry="service", tenant=job.tenant,
                        idem_key=job.idem_key,
                        cache_salt=self.ctx.cache_salt,
                        shape=shape_hint,
                        bucket=(bucket_label(shape_hint)
                                if shape_hint else ""))
        self._load_q.put(job)
        return job

    def job(self, job_id: str) -> Job | None:
        job = self.ctx.lookup(job_id)
        return job if job is not None else self.spool.get(job_id)

    def _check_root(self, path: str) -> str:
        """Validate ``path`` against --root and return its RESOLVED real
        path.  The resolved path is what gets stored and later opened, so
        a symlink retargeted between admission and load (or before a
        restart replay) cannot redirect the read outside the boundary —
        the check and the use see the same target."""
        root = self.serve_cfg.root
        if not root:
            return path
        real = os.path.realpath(path)
        real_root = os.path.realpath(root)
        try:
            # commonpath, not startswith: '--root /' must mean "any
            # absolute path", and '/data' must not admit '/database'.
            inside = os.path.commonpath([real, real_root]) == real_root
        except ValueError:   # e.g. a relative submission path
            inside = False
        if not inside:
            raise ValueError(f"path {path!r} is outside --root {root!r}")
        return real

    def retire(self, job: Job) -> None:
        """Drop a terminal job from the in-memory index — the spool manifest
        is the durable record (job() falls back to it), so a continuous-
        traffic daemon's memory stays bounded by OPEN work, not by every
        job it ever served."""
        self.ctx.retire(job)

    def audit_rate(self) -> float:
        """The effective shadow-audit sampling fraction: an explicit
        --audit_rate wins; < 0 honors ICT_AUDIT_RATE (default 0)."""
        return self.ctx.audit_rate()

    def set_draining(self, flag: bool = True) -> None:
        """Enter (or leave) drain mode: /healthz flips ``draining``, new
        submissions get 503, and parked partial buckets flush immediately
        so accepted work finishes as fast as it can — the fleet router
        reads the flag and stops placing here (docs/SERVING.md)."""
        self.ctx.set_draining(flag)
        if flag and self.scheduler is not None:
            self.scheduler.flush_all()
        if events.active():
            events.emit("replica_draining" if flag else "replica_undraining",
                        replica_id=self.replica_id)

    def health(self) -> dict:
        """Liveness + the drain signals a load balancer needs: uptime,
        version, and every queue/spool depth (a degraded daemon shows up
        as depths that only grow).  The audit fields let a load balancer
        gate on CORRECTNESS health, not just liveness: a daemon whose
        audit_divergences moves is serving wrong masks."""
        from iterative_cleaner_tpu import __version__
        from iterative_cleaner_tpu.obs import audit as obs_audit

        open_jobs = self.ctx.open_count()
        audit_rep = obs_audit.audit_report()
        return {
            "status": "ok",
            "replica_id": self.replica_id,
            "draining": self.ctx.is_draining(),
            "backend": self.backend_mode,
            "version": __version__,
            "uptime_s": round(time.time() - self.started_s, 3),
            "open_jobs": open_jobs,
            "load_queue_depth": self._load_q.qsize(),
            "dispatch_queue_depth": (self.worker.queue_depth()
                                     if self.worker else 0),
            "bucketed_cubes": (self.scheduler.pending_count()
                               if self.scheduler else 0),
            # Bucket-RESOLVED queue depths (NSUBxNCHANxNBIN -> cubes):
            # the fleet router's affinity-placement signal — aggregate
            # depths cannot tell it which replica is working a shape.
            "bucket_queue_depths": (self.scheduler.pending_by_bucket()
                                    if self.scheduler else {}),
            "bucket_cap": self.bucket_cap,
            "coalesce": (self.scheduler.coalesce if self.scheduler
                         else self.serve_cfg.coalesce),
            # The content-cache identity + size: the fleet router only
            # serves a cached result when every candidate replica
            # advertises the SAME salt (fleet/cache.py; advertised even
            # with the replica-local tier off — the router tier is its
            # own knob), and fleet_top's cache columns read the entry
            # counts next to the hit/miss counters on /metrics.
            "cache_salt": self.ctx.cache_salt,
            "result_cache_entries": len(self.ctx.result_cache),
            "deadline_s": self.serve_cfg.deadline_s,
            "warm_shapes": (self.pool.warm_shapes_now() if self.pool else []),
            "open_sessions": (self.sessions.open_count()
                              if self.sessions else 0),
            "audits_run": audit_rep["audits_run"],
            "audit_divergences": audit_rep["divergences"],
            "last_divergence_ts": audit_rep["last_divergence_ts"],
            "spool": self.spool.root,
        }

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Block until every accepted job is terminal (tests + shutdown
        hooks); True on success, False on timeout."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._jobs_lock:
                if all(j.state in TERMINAL for j in self._jobs.values()):
                    return True
            time.sleep(0.02)
        return False

    # --- internals ---

    def _load_loop(self) -> None:
        # Serialized deliberately: with loaders >= 2, the pool's threads
        # race the FIRST `import jax` chain here, and CPython's
        # circular-import deadlock avoidance can hand a loser a
        # partially-initialized module — both loader threads then die at
        # startup and every future job wedges in the load queue (observed
        # on a fresh `ict-serve --backend numpy` subprocess).  After the
        # winner finishes, the import is a sys.modules hit; laziness is
        # kept so an idle numpy-mode daemon still never imports jax.
        with _LOADER_IMPORT_LOCK:
            from iterative_cleaner_tpu.parallel.batch import (
                _load_and_preprocess,
            )

        while True:
            job = self._load_q.get()
            if job is _STOP:
                return
            try:
                with tracing.phase("service_load"):
                    archive, D, w0 = _load_and_preprocess(job.path)
            except Exception as exc:  # noqa: BLE001 — a poisoned archive
                # fails ALONE, before it can join (and take down) a bucket.
                self.worker._fail(job, f"load failed: {exc}")
                continue
            # Content addressing at ingest (ingest/cas.py): the cube key
            # the worker's result cache checks, and the file digest +
            # salt the fleet router's placement-time cache learns off the
            # terminal manifest.  Hashing is one pass over bytes already
            # resident — noise next to the clean it can save.  The
            # digest is recomputed HERE even when a router already
            # hashed the file at placement time, deliberately: the
            # manifest digest seeds the FLEET-WIDE reuse index, and
            # accepting a submitter-supplied value would let one buggy
            # or hostile client map digest(X) -> result(Y) for every
            # other tenant's byte-identical submission — the replica's
            # own read is the trust boundary (the cost is bounded by
            # the router's ICT_FLEET_CACHE_MAX_BYTES skip).
            from iterative_cleaner_tpu.ingest import cas

            job.cache_salt = self.ctx.cache_salt
            job.file_digest = cas.file_digest(job.path)
            if self.ctx.result_cache.enabled:
                job.content_key = cas.cube_key(D, w0, self.clean_cfg)
            self.scheduler.offer(job, archive, D, w0)

    def _tick_loop(self) -> None:
        interval = min(max(self.serve_cfg.deadline_s / 4, 0.01), 0.25)
        last_gauges = 0.0
        while not self._stop_evt.wait(interval):
            self.scheduler.tick()
            # Keep the memory gauges (/metrics: host RSS, per-device
            # current/peak HBM) no staler than a couple of seconds; the
            # read is a stats-dict fetch, not device work.
            now = time.monotonic()
            if now - last_gauges >= 2.0:
                last_gauges = now
                obs_memory.update_process_gauges()
                # Spool disk headroom rides the same cadence — the fleet
                # alert pack's spool_disk_low rule reads it off the
                # federated scrape (docs/OBSERVABILITY.md "Alerting &
                # history").
                obs_memory.update_spool_gauge(self.serve_cfg.spool_dir)
                # The cost ledger's dirty aggregates ride it too — a
                # bounded-staleness persist instead of one atomic write
                # per served job (obs/costs.py; flush never raises).
                self.ctx.cost_ledger.flush()
                # Ingest overlap efficiency as a scrapeable gauge (the
                # trend plane's ingest_overlap fingerprint reads it off
                # the federated exposition; the "last" hint keeps the
                # fleet merge a max, never a sum of fractions).  Only
                # once real pipelined blocks exist — a 0 published
                # before any ingest would read as a regression.
                try:
                    from iterative_cleaner_tpu.ingest import pipeline
                    pstats = pipeline.stats_snapshot()
                    if pstats.get("blocks", 0) > 0:
                        tracing.set_gauge("ingest_last_overlap_efficiency",
                                          pstats["overlap_efficiency"])
                except Exception:
                    pass    # a gauge miss must never wedge the tick loop

    def _on_flush(self, entries) -> None:
        tracing.count("service_buckets_dispatched")
        self.worker.submit(entries)


# --- CLI ---

def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ict-serve",
        description="Long-running cleaning daemon: shape-bucketed admission, "
                    "warm executable pool, fault-isolated job execution "
                    "(docs/SERVING.md)")
    p.add_argument("--spool", default="./ict_serve_spool",
                   help="job-manifest directory; a restarted daemon resumes "
                        "the pending jobs found here (default: "
                        "./ict_serve_spool)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8750,
                   help="HTTP port (0 = ephemeral; default 8750)")
    p.add_argument("--replica_id", default="", metavar="ID",
                   help="stable fleet identity, echoed on /healthz and "
                        "every POST /jobs 202 so trace logs attribute jobs "
                        "to replicas (default: mint one per process life)")
    p.add_argument("--bucket_cap", type=int, default=0, metavar="N",
                   help="archives per sharded dispatch (0 = the mesh's "
                        "data-parallel extent; clamped to a power of two)")
    p.add_argument("--coalesce", type=int, default=1, metavar="K",
                   help="request-coalescing factor (clamped to a power of "
                        "two): a shape bucket flushes at bucket_cap x K "
                        "cubes, so one batched executable launch amortizes "
                        "over K cubes per data-parallel slice — the "
                        "small-cube campaign throughput knob; raises "
                        "per-device residency by the same factor "
                        "(default 1; docs/SERVING.md)")
    p.add_argument("--result_cache", type=int, default=256, metavar="N",
                   help="content-addressed result-cache entries kept "
                        "(0 = off): a resubmitted cube whose bytes + "
                        "config hash to a known key is served from the "
                        "cached mask without touching the device, "
                        "byte-identical by construction; entries persist "
                        "under <spool>/results-cache and are invalidated "
                        "by the code-version/config salt "
                        "(default 256; docs/SERVING.md)")
    p.add_argument("--deadline_s", type=float, default=2.0, metavar="S",
                   help="max seconds a partial bucket waits before it is "
                        "dispatched anyway (default 2.0)")
    p.add_argument("--loaders", type=int, default=2,
                   help="archive-decode threads (default 2)")
    p.add_argument("--spool_keep", type=int, default=10000, metavar="N",
                   help="finished-job manifests kept as history; older ones "
                        "are pruned at startup (default 10000)")
    p.add_argument("--max_open_jobs", type=int, default=64, metavar="N",
                   help="admission cap: submissions beyond N open jobs get "
                        "503 (backpressure — every open job can hold one "
                        "decoded cube on host; 0 = unbounded; default 64)")
    p.add_argument("--root", default="", metavar="DIR",
                   help="only accept archive paths under DIR (REQUIRED "
                        "hardening for non-loopback --host: without it any "
                        "reachable client can make the daemon read any file "
                        "and write a _cleaned output next to it)")
    p.add_argument("--alert_iters", type=int, default=2, metavar="N",
                   help="streaming sessions: bounded provisional clean-pass "
                        "iterations per ingested block (default 2; the "
                        "authoritative mask always comes from the canonical "
                        "finalize, docs/SERVING.md)")
    p.add_argument("--warm", action="append", default=[],
                   metavar="NSUBxNCHANxNBIN",
                   help="shape class to precompile at startup (repeatable), "
                        "e.g. --warm 256x1024x1024")
    p.add_argument("--audit_rate", type=float, default=-1.0, metavar="F",
                   help="shadow-oracle audit sampling fraction in [0, 1]: "
                        "this share of completed jobs is replayed through "
                        "the numpy oracle on a background thread and the "
                        "masks compared bit-for-bit (divergences write "
                        "repro bundles under <spool>/repro and show on "
                        "/healthz; docs/OBSERVABILITY.md).  Default: honor "
                        "ICT_AUDIT_RATE (0 = off); a per-job "
                        '{"audit": true} always audits')
    p.add_argument("--telemetry", default="", metavar="PATH",
                   help="append structured telemetry events (trace spans, "
                        "per-iteration forensics) to PATH as JSON lines "
                        "(docs/OBSERVABILITY.md; ICT_TELEMETRY env "
                        "equivalent; default off)")
    p.add_argument("--backend", choices=("numpy", "jax"), default="jax")
    p.add_argument("-c", "--chanthresh", type=float, default=5)
    p.add_argument("-s", "--subintthresh", type=float, default=5)
    p.add_argument("-m", "--max_iter", type=int, default=5)
    p.add_argument("--bad_chan", type=float, default=1)
    p.add_argument("--bad_subint", type=float, default=1)
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="offline self-check: start the daemon, clean one "
                        "synthetic archive through the HTTP API, verify the "
                        "mask against the numpy oracle, print one JSON line, "
                        "exit")
    return p


def parse_warm_shapes(specs: list[str]) -> tuple:
    shapes = []
    for spec in specs:
        try:
            nsub, nchan, nbin = (int(v) for v in spec.lower().split("x"))
            shapes.append((nsub, nchan, nbin))
        except ValueError:
            raise ValueError(
                f"bad --warm shape {spec!r}; expected NSUBxNCHANxNBIN "
                "like 256x1024x1024") from None
    return tuple(shapes)


def serve_config_from_args(args: argparse.Namespace) -> ServeConfig:
    # Reject ambiguous negatives up front (serve_main turns the ValueError
    # into the one-line error + rc 2 contract): -1 is NOT "unbounded" —
    # it would make the cap check refuse every submission forever.
    if args.max_open_jobs < 0:
        raise ValueError(f"--max_open_jobs must be >= 0 (0 = unbounded), "
                         f"got {args.max_open_jobs}")
    if args.bucket_cap < 0:
        raise ValueError(f"--bucket_cap must be >= 0 (0 = the mesh's dp "
                         f"extent), got {args.bucket_cap}")
    if args.coalesce < 1:
        raise ValueError(f"--coalesce must be >= 1, got {args.coalesce}")
    if args.result_cache < 0:
        raise ValueError(f"--result_cache must be >= 0 (0 = off), "
                         f"got {args.result_cache}")
    if args.alert_iters < 1:
        raise ValueError(f"--alert_iters must be >= 1, got {args.alert_iters}")
    if args.audit_rate > 1:
        raise ValueError(f"--audit_rate must be a fraction in [0, 1] "
                         f"(negative = honor ICT_AUDIT_RATE), got "
                         f"{args.audit_rate}")
    return ServeConfig(
        spool_dir=args.spool,
        host=args.host,
        port=args.port,
        replica_id=args.replica_id,
        bucket_cap=args.bucket_cap,
        coalesce=args.coalesce,
        result_cache=args.result_cache,
        deadline_s=args.deadline_s,
        loaders=args.loaders,
        spool_keep=args.spool_keep,
        max_open_jobs=args.max_open_jobs,
        alert_iters=args.alert_iters,
        root=args.root,
        telemetry=args.telemetry,
        audit_rate=args.audit_rate,
        warm_shapes=parse_warm_shapes(args.warm),
        quiet=args.quiet,
        clean=CleanConfig(
            backend=args.backend,
            chanthresh=args.chanthresh,
            subintthresh=args.subintthresh,
            max_iter=args.max_iter,
            bad_chan=args.bad_chan,
            bad_subint=args.bad_subint,
            quiet=args.quiet,
        ),
    )


def run_smoke(serve_cfg: ServeConfig) -> int:
    import json
    import tempfile
    import urllib.request

    import numpy as np

    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.io.npz import NpzIO
    from iterative_cleaner_tpu.io.synthetic import make_archive
    from iterative_cleaner_tpu.ops.preprocess import preprocess

    with tempfile.TemporaryDirectory(prefix="ict_serve_smoke_") as tmp:
        path = os.path.join(tmp, "smoke.npz")
        archive = make_archive(nsub=4, nchan=16, nbin=64, seed=99)
        NpzIO().save(archive, path)
        # Hermetic overrides: the smoke archive lives in this tempdir, so
        # an operator --root (or a tiny cap) must not refuse the probe.
        cfg = ServeConfig(**{**serve_cfg.__dict__,
                             "spool_dir": os.path.join(tmp, "spool"),
                             "port": 0, "deadline_s": 0.2,
                             "root": "", "max_open_jobs": 0})
        service = CleaningService(cfg)
        service.start()
        try:
            base = f"http://{cfg.host}:{service.port}"
            # Every smoke run exercises the shadow-oracle audit end-to-end
            # on top of the external mask check below — through the
            # SAMPLING path when it is deterministic (rate exactly 1.0,
            # the CI audit lane: genuinely covers the trigger the plain
            # lane cannot), through the per-job opt-in otherwise (a
            # FRACTIONAL rate would make the audits_run >= 1 requirement
            # a coin flip on a healthy daemon).
            want_flag = service.audit_rate() < 1.0
            req = urllib.request.Request(
                f"{base}/jobs",
                data=json.dumps({"path": path, "audit": want_flag}).encode(),
                headers={"Content-Type": "application/json"})
            job = json.load(urllib.request.urlopen(req, timeout=30))
            deadline = time.time() + 300
            while job["state"] not in TERMINAL and time.time() < deadline:
                time.sleep(0.1)
                job = json.load(urllib.request.urlopen(
                    f"{base}/jobs/{job['id']}", timeout=30))
            # The audit runs on a background thread; /healthz must read
            # its verdict, not its backlog.
            service.auditor.drain(60)
            health = json.load(urllib.request.urlopen(
                f"{base}/healthz", timeout=30))
            ok = job["state"] == "done" and health.get("status") == "ok"
            audits_ok = (health.get("audits_run", 0) >= 1
                         and health.get("audit_divergences", 0) == 0)
            masks_ok = False
            if ok:
                from iterative_cleaner_tpu.parallel.batch import (
                    finalize_weights,
                )

                cfg_np = cfg.clean.replace(backend="numpy")
                # Same finalization as every served route (shared helper):
                # the oracle comparison includes the bad-parts sweep.
                want, _rfi = finalize_weights(
                    clean_cube(*preprocess(archive), cfg_np).weights, cfg_np)
                got = NpzIO().load(job["out_path"])
                masks_ok = bool(np.array_equal(got.weights, want))
            print(json.dumps({
                "smoke": "ok" if ok and masks_ok and audits_ok else "FAIL",
                "job_state": job["state"],
                "served_by": job.get("served_by", ""),
                "mask_identical_to_oracle": masks_ok,
                "audits_run": health.get("audits_run", 0),
                "audit_divergences": health.get("audit_divergences", 0),
                "backend": health.get("backend"),
            }))
            return 0 if ok and masks_ok and audits_ok else 1
        finally:
            service.stop()


def serve_main(argv: list[str] | None = None) -> int:
    args = build_serve_parser().parse_args(argv)
    try:
        serve_cfg = serve_config_from_args(args)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if serve_cfg.clean.backend == "jax":
        # Same CLI-layer policy as cli.main (one shared helper): persistent
        # XLA compile cache on by default, size-bounded at startup — a
        # long-lived heterogeneous-shape service is exactly the unbounded-
        # growth workload (ADVICE r05).
        from iterative_cleaner_tpu.utils.compile_cache import (
            enable_and_trim_persistent_cache,
        )

        enable_and_trim_persistent_cache()
    if args.smoke:
        return run_smoke(serve_cfg)
    service = CleaningService(serve_cfg)
    try:
        service.start()
    except (RuntimeError, OSError) as exc:
        # e.g. the spool's single-daemon flock, or EADDRINUSE on the bind —
        # the operator contract is a one-line error + rc 1, not a traceback.
        print(f"error: {exc}", file=sys.stderr)
        return 1
    # SIGTERM (the orchestrator's stop signal) and SIGINT (a Ctrl-C'd dev
    # daemon) both dump the flight ring before the graceful shutdown:
    # "what was the daemon doing when it was killed" becomes a file in the
    # spool instead of a guess — dev forensics matter as much as
    # production ones.  Registered only for the real daemon run (not
    # --smoke, not library embedders), and only from the main thread
    # (signal.signal refuses elsewhere).
    import signal

    def _on_stop_signal(signum, frame):
        name = signal.Signals(signum).name
        path = flight.dump(name, service.flight_dir)
        print(f"ict-serve: {name} — shutting down (unfinished jobs stay in "
              f"the spool{'; flight ring at ' + path if path else ''})",
              file=sys.stderr)
        raise SystemExit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_stop_signal)
        except (ValueError, OSError):  # noqa: PERF203 — non-main-thread embed
            pass
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # Reached only when the SIGINT handler could not be installed (a
        # non-main-thread embed): same graceful stop, same flight dump.
        path = flight.dump("KeyboardInterrupt", service.flight_dir)
        print("ict-serve: shutting down (unfinished jobs stay in the spool"
              f"{'; flight ring at ' + path if path else ''})",
              file=sys.stderr)
    finally:
        service.stop()
    return 0


def console_main() -> int:
    return serve_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(serve_main())
