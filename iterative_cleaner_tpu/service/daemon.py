"""Daemon lifecycle + the ``ict-serve`` CLI.

Thread layout (all daemonic; ``stop()`` is graceful):

- N loader threads: decode + preprocess submitted archives (host-side,
  independent per file — the parallel/batch thread-pool idiom) and offer
  the cubes to the shape-bucketed scheduler;
- 1 tick thread: fires the scheduler's deadline flushes;
- 1 dispatch worker: runs flushed buckets on the mesh (service/worker.py);
- the ThreadingHTTPServer's per-request threads (service/api.py).

Jobs the daemon accepted but had not finished when it died stay in the
on-disk spool as ``pending``/``running`` manifests; the next start replays
them (service/jobs.py), so a restart loses no accepted work.

``python -m iterative_cleaner_tpu serve --smoke`` runs the whole stack
against one synthetic archive over real HTTP and verifies the returned
mask bit-identical to the numpy oracle — the offline health check CI and
operators share.
"""

from __future__ import annotations

import argparse
import os
import queue
import sys
import threading
import time
from dataclasses import dataclass, field

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.obs import (
    events,
    flight,
    memory as obs_memory,
    tracing,
)
from iterative_cleaner_tpu.service.jobs import TERMINAL, Job, JobSpool
from iterative_cleaner_tpu.service.scheduler import ShapeBucketScheduler
from iterative_cleaner_tpu.service.worker import DispatchWorker

_STOP = object()


class ServiceBusy(RuntimeError):
    """Admission refused: the open-job cap is reached (the API maps this to
    503 + Retry-After).  The cap is the daemon's backpressure — every open
    job can hold one decoded f32 cube on host, so unbounded admission would
    let a submission burst outrun the single dispatch thread and OOM."""


@dataclass
class ServeConfig:
    spool_dir: str = "./ict_serve_spool"
    host: str = "127.0.0.1"
    port: int = 8750                 # 0 = ephemeral (tests)
    bucket_cap: int = 0              # 0 = the mesh's dp extent
    deadline_s: float = 2.0          # max wait before a partial bucket flushes
    loaders: int = 2
    warm_shapes: tuple = ()          # (nsub, nchan, nbin) classes to precompile
    dispatch_retries: int = 2
    retry_backoff_s: float = 0.25
    demote_after: int = 2            # consecutive bucket failures -> oracle mode
    spool_keep: int = 10000          # terminal manifests kept as job history
    max_open_jobs: int = 64          # admission cap (0 = unbounded): bounds
                                     # decoded-cube host residency; size it
                                     # to host RAM / cube size
    alert_iters: int = 2             # streaming sessions: bounded provisional
                                     # clean-pass iterations per block
    root: str = ""                   # when set, submitted paths must resolve
                                     # under this directory (the non-loopback
                                     # trust boundary)
    telemetry: str = ""              # JSON-lines event-log path (obs/events);
                                     # "" = honor ICT_TELEMETRY / disabled
    audit_rate: float = -1.0         # shadow-oracle audit sampling fraction
                                     # (obs/audit): < 0 = honor the
                                     # ICT_AUDIT_RATE env (default 0); a
                                     # per-job {"audit": true} always audits
    quiet: bool = False
    clean: CleanConfig = field(
        default_factory=lambda: CleanConfig(backend="jax"))


class CleaningService:
    """The persistent cleaning daemon; see the module docstring for the
    thread layout and docs/SERVING.md for the operator contract."""

    def __init__(self, serve_cfg: ServeConfig, mesh=None) -> None:
        self.serve_cfg = serve_cfg
        self.clean_cfg = serve_cfg.clean
        self.spool = JobSpool(serve_cfg.spool_dir)
        self.mesh = mesh
        self.started_s = time.time()   # re-stamped at start(); /healthz uptime
        # Demotion state ("jax" | "numpy") is written by three threads
        # (startup, the dispatch worker's note_dispatch_failure, the shadow
        # auditor's note_audit_divergence) and read everywhere: one lock
        # makes the count-then-demote transition atomic, so two racing
        # failure reports can neither lose an increment nor double-fire
        # the demotion side effects (flight dump, stderr line).
        self._mode_lock = threading.Lock()
        self.backend_mode = self.clean_cfg.backend  # ict: guarded-by(self._mode_lock)
        self.bucket_cap = 1
        self.port = serve_cfg.port
        self.pool = None
        self._jobs: dict[str, Job] = {}  # ict: guarded-by(self._jobs_lock)
        self._jobs_lock = threading.Lock()
        self._load_q: queue.Queue = queue.Queue()
        self._consecutive_failures = 0  # ict: guarded-by(self._mode_lock)
        self._threads: list[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._server = None
        self.scheduler = None
        self.worker = None
        self.sessions = None
        # Device-level observability artifacts live under the spool (the
        # single-daemon flock already covers it): profiler captures
        # (obs/profiling — POST /debug/profile, per-job capture) and
        # flight-recorder dumps (obs/flight — fault-ladder trips, SIGTERM).
        self.profile_root = os.path.join(serve_cfg.spool_dir, "profiles")
        self.flight_dir = os.path.join(serve_cfg.spool_dir, "flight")
        # Divergence repro bundles (obs/audit): the shadow auditor writes
        # one self-contained directory per confirmed mask mismatch here.
        self.repro_dir = os.path.join(serve_cfg.spool_dir, "repro")
        self.auditor = None
        self._audit_divergences = 0  # ict: guarded-by(self._mode_lock)

    # --- lifecycle ---

    def start(self) -> None:
        # Single-daemon guard FIRST: a second daemon on the same spool
        # would sweep this one's atomic-write temps and re-dispatch its
        # running jobs before even failing to bind the port.
        self.spool.acquire_exclusive()
        try:
            self._start_locked()
        except BaseException:
            # A mid-start failure (e.g. EADDRINUSE at the HTTP bind, after
            # warmup and spool replay) must not leak the flock or the
            # already-started threads — a corrected retry on the same
            # spool would otherwise see "already served" from a dead
            # service object.
            try:
                self.stop()
            except Exception:  # noqa: BLE001 — surface the original error
                pass
            raise

    def _start_locked(self) -> None:
        self.started_s = time.time()
        # Unconditional: telemetry="" must MEAN "honor ICT_TELEMETRY /
        # disabled" (the ServeConfig contract) even when an earlier
        # service in this process configured an explicit sink — a
        # restarted daemon must not silently inherit its predecessor's
        # log file.
        events.configure(self.serve_cfg.telemetry or None)
        flight.note("daemon_starting", spool=self.spool.root,
                    backend=self.backend_mode)
        if self.backend_mode == "jax":
            # Compile accounting on /metrics (compiles, compile seconds per
            # shape bucket, persistent-cache events).  JAX path only: the
            # numpy service stays jax-import-free.
            tracing.install_compile_listener()
            # The CLI front-door wedge guard (utils/device_probe.py): a hung
            # probe with indeterminable liveness means the next jax call may
            # hang the daemon — that, and only that, degrades the whole
            # service to the numpy oracle.  A plain "demoted" keeps the jax
            # route: it is pinned to CPU, masks identical, wall-clock not.
            from iterative_cleaner_tpu.utils.device_probe import (
                ensure_responsive_backend,
            )

            if ensure_responsive_backend() == "demote_failed":
                print("ict-serve: backend liveness indeterminable after a "
                      "hung probe; serving via the numpy oracle",
                      file=sys.stderr)
                with self._mode_lock:
                    self.backend_mode = "numpy"
        cap = 1
        if self.backend_mode == "jax":
            if self.mesh is None:
                from iterative_cleaner_tpu.parallel.mesh import make_mesh

                # make_mesh is this daemon's first in-process device read;
                # its internal init_watchdog turns a wedged-tunnel freeze
                # into a structured warning (ICT_INIT_TIMEOUT_S) instead
                # of a silent never-came-up.
                self.mesh = make_mesh()
            cap = self.serve_cfg.bucket_cap or max(int(self.mesh.shape["dp"]), 1)
        self.scheduler = ShapeBucketScheduler(
            cap, self.serve_cfg.deadline_s, self._on_flush)
        # The pow2 clamp lives in the scheduler (the mechanism that owns
        # the invariant); the warm pool reads the clamped value so the
        # precompiled batch-size set matches the sizes actually emitted.
        self.bucket_cap = self.scheduler.bucket_cap
        if self.backend_mode == "jax":
            from iterative_cleaner_tpu.service.pool import WarmPool

            self.pool = WarmPool(self.clean_cfg, self.mesh, self.bucket_cap,
                                 quiet=self.serve_cfg.quiet)
            self.pool.warm_startup(self.serve_cfg.warm_shapes)
        from iterative_cleaner_tpu.service.sessions import SessionManager

        # Streaming sessions (docs/SERVING.md "Streaming sessions"): spool-
        # backed under the job spool, so the single-daemon flock covers them
        # and a restart finds the replay log in place.  The cfg_provider
        # re-reads backend_mode on every session touch, so both the startup
        # liveness demotion and a RUNTIME service-wide demotion
        # (note_dispatch_failure) reach streaming passes too.
        self.sessions = SessionManager(
            os.path.join(self.serve_cfg.spool_dir, "sessions"),
            self.clean_cfg.replace(backend=self.backend_mode),
            alert_iters=self.serve_cfg.alert_iters,
            quiet=self.serve_cfg.quiet,
            cfg_provider=lambda: self.clean_cfg.replace(
                backend=self.backend_mode))
        self.worker = DispatchWorker(self)
        # Spool trim + replay run BEFORE any thread starts: the trim's
        # .json.part sweep is only safe while no writer thread exists (the
        # invariant jobs.trim documents), and the worker object's _fail
        # needs no running thread.  One directory scan feeds both halves —
        # with a 10k-manifest history, scanning twice would double the
        # pre-API startup I/O.  Replayed jobs just queue; the loaders
        # drain them once started below.
        spooled = self.spool.all_jobs()
        self.spool.trim(self.serve_cfg.spool_keep, jobs=spooled)
        # Recovered jobs keep their original (older, time-sortable) ids,
        # so they drain ahead of new traffic of the same shape.
        for job in self.spool.recover(jobs=spooled):
            with self._jobs_lock:
                self._jobs[job.id] = job
            try:
                # Replayed manifests are re-validated against the CURRENT
                # --root (the boundary may have changed across restarts,
                # and old manifests predate it).
                job.path = self._check_root(job.path)
            except ValueError as exc:
                self.worker._fail(job, str(exc))
                continue
            self._load_q.put(job)
            tracing.count("service_jobs_recovered")
        # The shadow auditor always exists (a per-job {"audit": true} must
        # work even at rate 0); idle it is one blocked queue.get.  Started
        # HERE, after the trim/replay block above, because _audit_one
        # writes spool manifests — the trim's .part sweep is only safe
        # while no writer thread exists (the invariant jobs.trim
        # documents).
        from iterative_cleaner_tpu.obs.audit import ShadowAuditor

        self.auditor = ShadowAuditor(
            self.spool, self.repro_dir,
            on_divergence=self.note_audit_divergence,
            quiet=self.serve_cfg.quiet)
        self.auditor.start()
        self._threads.append(self.auditor)
        self.worker.start()
        self._threads.append(self.worker)
        for i in range(max(self.serve_cfg.loaders, 1)):
            th = threading.Thread(target=self._load_loop, daemon=True,
                                  name=f"ict-serve-load-{i}")
            th.start()
            self._threads.append(th)
        th = threading.Thread(target=self._tick_loop, daemon=True,
                              name="ict-serve-tick")
        th.start()
        self._threads.append(th)
        from iterative_cleaner_tpu.service.api import make_http_server

        self._server = make_http_server(
            self, self.serve_cfg.host, self.serve_cfg.port)
        self.port = self._server.server_address[1]
        th = threading.Thread(target=self._server.serve_forever, daemon=True,
                              name="ict-serve-http")
        th.start()
        self._threads.append(th)
        if not self.serve_cfg.quiet:
            print(f"ict-serve: listening on "
                  f"http://{self.serve_cfg.host}:{self.port} "
                  f"(backend={self.backend_mode}, bucket_cap="
                  f"{self.bucket_cap}, spool={self.spool.root})",
                  file=sys.stderr)

    def stop(self) -> None:
        """Graceful stop: the API closes, threads drain their queues' poison
        pills, and any still-unfinished job stays in the spool for the next
        life (restart-resume is the durability story, not a shutdown barrier)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        self._stop_evt.set()
        for _ in range(max(self.serve_cfg.loaders, 1)):
            self._load_q.put(_STOP)
        if self.worker is not None:
            self.worker.stop()
        if self.auditor is not None:
            self.auditor.stop()
        stuck = []
        for th in self._threads:
            th.join(timeout=10)
            if th.is_alive():
                stuck.append(th.name)
        if stuck:
            # A live thread may still be WRITING spool manifests; releasing
            # the flock would let a successor daemon's .part sweep and
            # running-job replay race it (the exclusivity trim() depends
            # on).  Keep the lock — the kernel frees it at process exit.
            print(f"ict-serve: threads still running after stop "
                  f"({', '.join(stuck)}); keeping the spool lock until "
                  "process exit", file=sys.stderr)
        else:
            self.spool.release_exclusive()

    # --- submission / inspection (the API's surface) ---

    def submit(self, path: str, profile: bool = False,
               audit: bool = False) -> Job:
        path = self._check_root(path)
        from iterative_cleaner_tpu.service.jobs import new_job_id

        # The trace context is minted HERE, at the entry point, and rides
        # on the job through every layer (admission, dispatch, iteration
        # events) — echoed in the 202 response and the X-ICT-Trace header.
        # ``profile`` asks for a jax.profiler capture around this job's
        # dispatch (obs/profiling); the artifact dir lands on the manifest.
        # ``audit`` asks for a shadow-oracle parity replay after it serves
        # (obs/audit; ICT_AUDIT_RATE / --audit_rate samples the rest).
        job = Job(id=new_job_id(), path=path, submitted_s=time.time(),
                  trace_id=events.new_trace_id(), profile=bool(profile),
                  audit=bool(audit))
        # Cap check and insert under ONE lock hold: concurrent POST handler
        # threads must not all pass the check before any of them inserts
        # (the cap is the OOM backpressure — a race would breach it).
        with self._jobs_lock:
            if self.serve_cfg.max_open_jobs:
                # retire() evicts terminal jobs, so this scan is O(open).
                open_n = sum(1 for j in self._jobs.values()
                             if j.state not in TERMINAL)
                if open_n >= self.serve_cfg.max_open_jobs:
                    tracing.count("service_jobs_refused")
                    raise ServiceBusy(
                        f"{open_n} open jobs at the --max_open_jobs cap "
                        f"({self.serve_cfg.max_open_jobs}); retry later")
            self._jobs[job.id] = job
        try:
            self.spool.save(job)
        except Exception:
            # Roll the admission back: a job that was never made durable is
            # also never enqueued, so leaving it in _jobs would leak one
            # max_open_jobs slot per failed save until restart.
            with self._jobs_lock:
                self._jobs.pop(job.id, None)
            raise
        tracing.count("service_jobs_submitted")
        if events.active():
            events.emit("job_submitted", trace_id=job.trace_id,
                        job_id=job.id, path=path)
        self._load_q.put(job)
        return job

    def job(self, job_id: str) -> Job | None:
        with self._jobs_lock:
            job = self._jobs.get(job_id)
        return job if job is not None else self.spool.get(job_id)

    def _check_root(self, path: str) -> str:
        """Validate ``path`` against --root and return its RESOLVED real
        path.  The resolved path is what gets stored and later opened, so
        a symlink retargeted between admission and load (or before a
        restart replay) cannot redirect the read outside the boundary —
        the check and the use see the same target."""
        root = self.serve_cfg.root
        if not root:
            return path
        real = os.path.realpath(path)
        real_root = os.path.realpath(root)
        try:
            # commonpath, not startswith: '--root /' must mean "any
            # absolute path", and '/data' must not admit '/database'.
            inside = os.path.commonpath([real, real_root]) == real_root
        except ValueError:   # e.g. a relative submission path
            inside = False
        if not inside:
            raise ValueError(f"path {path!r} is outside --root {root!r}")
        return real

    def retire(self, job: Job) -> None:
        """Drop a terminal job from the in-memory index — the spool manifest
        is the durable record (job() falls back to it), so a continuous-
        traffic daemon's memory stays bounded by OPEN work, not by every
        job it ever served."""
        with self._jobs_lock:
            self._jobs.pop(job.id, None)

    def audit_rate(self) -> float:
        """The effective shadow-audit sampling fraction: an explicit
        --audit_rate wins; < 0 honors ICT_AUDIT_RATE (default 0)."""
        from iterative_cleaner_tpu.obs import audit as obs_audit

        if self.serve_cfg.audit_rate >= 0:
            return min(self.serve_cfg.audit_rate, 1.0)
        return obs_audit.audit_rate()

    def health(self) -> dict:
        """Liveness + the drain signals a load balancer needs: uptime,
        version, and every queue/spool depth (a degraded daemon shows up
        as depths that only grow).  The audit fields let a load balancer
        gate on CORRECTNESS health, not just liveness: a daemon whose
        audit_divergences moves is serving wrong masks."""
        from iterative_cleaner_tpu import __version__
        from iterative_cleaner_tpu.obs import audit as obs_audit

        with self._jobs_lock:
            open_jobs = sum(1 for j in self._jobs.values()
                            if j.state not in TERMINAL)
        audit_rep = obs_audit.audit_report()
        return {
            "status": "ok",
            "backend": self.backend_mode,
            "version": __version__,
            "uptime_s": round(time.time() - self.started_s, 3),
            "open_jobs": open_jobs,
            "load_queue_depth": self._load_q.qsize(),
            "dispatch_queue_depth": (self.worker.queue_depth()
                                     if self.worker else 0),
            "bucketed_cubes": (self.scheduler.pending_count()
                               if self.scheduler else 0),
            "bucket_cap": self.bucket_cap,
            "deadline_s": self.serve_cfg.deadline_s,
            "warm_shapes": (self.pool.warm_shapes_now() if self.pool else []),
            "open_sessions": (self.sessions.open_count()
                              if self.sessions else 0),
            "audits_run": audit_rep["audits_run"],
            "audit_divergences": audit_rep["divergences"],
            "last_divergence_ts": audit_rep["last_divergence_ts"],
            "spool": self.spool.root,
        }

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Block until every accepted job is terminal (tests + shutdown
        hooks); True on success, False on timeout."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._jobs_lock:
                if all(j.state in TERMINAL for j in self._jobs.values()):
                    return True
            time.sleep(0.02)
        return False

    # --- internals ---

    def _load_loop(self) -> None:
        from iterative_cleaner_tpu.parallel.batch import _load_and_preprocess

        while True:
            job = self._load_q.get()
            if job is _STOP:
                return
            try:
                with tracing.phase("service_load"):
                    archive, D, w0 = _load_and_preprocess(job.path)
            except Exception as exc:  # noqa: BLE001 — a poisoned archive
                # fails ALONE, before it can join (and take down) a bucket.
                self.worker._fail(job, f"load failed: {exc}")
                continue
            self.scheduler.offer(job, archive, D, w0)

    def _tick_loop(self) -> None:
        interval = min(max(self.serve_cfg.deadline_s / 4, 0.01), 0.25)
        last_gauges = 0.0
        while not self._stop_evt.wait(interval):
            self.scheduler.tick()
            # Keep the memory gauges (/metrics: host RSS, per-device
            # current/peak HBM) no staler than a couple of seconds; the
            # read is a stats-dict fetch, not device work.
            now = time.monotonic()
            if now - last_gauges >= 2.0:
                last_gauges = now
                obs_memory.update_process_gauges()

    def _on_flush(self, entries) -> None:
        tracing.count("service_buckets_dispatched")
        self.worker.submit(entries)

    def note_dispatch_ok(self) -> None:
        with self._mode_lock:
            self._consecutive_failures = 0

    def note_dispatch_failure(self, exc) -> None:
        # Count-then-demote under the mode lock (the worker and auditor
        # threads both reach the demotion transition); side effects fire
        # outside it, exactly once, on the thread that flipped the mode.
        with self._mode_lock:
            self._consecutive_failures += 1
            n_failures = self._consecutive_failures
            demote = (self.backend_mode == "jax"
                      and n_failures >= self.serve_cfg.demote_after)
            if demote:
                self.backend_mode = "numpy"
        if demote:
            tracing.count("service_backend_demotions")
            # The top rung of the fault ladder: dump the flight ring — the
            # post-mortem of what led to a service-wide demotion is worth a
            # file even when nobody configured telemetry.
            flight.note("service_demoted", error=str(exc))
            flight.dump(f"service_demotion: {exc}", self.flight_dir)
            print(f"ict-serve: {n_failures} consecutive "
                  f"bucket dispatches failed (last: {exc}); demoting the "
                  "service to the numpy oracle backend", file=sys.stderr)

    def note_audit_divergence(self, record: dict) -> None:
        """The shadow auditor confirmed a served mask differed from the
        oracle.  Repeated confirmed divergences demote the service the
        same way repeated dispatch failures do (the worker ladder's top
        rung): a route that keeps producing wrong masks is worse than a
        route that keeps crashing."""
        with self._mode_lock:
            self._audit_divergences += 1
            n_div = self._audit_divergences
            demote = (self.backend_mode == "jax"
                      and n_div >= self.serve_cfg.demote_after)
            if demote:
                self.backend_mode = "numpy"
        if demote:
            tracing.count("service_backend_demotions")
            flight.note("service_demoted_audit",
                        n_divergences=n_div,
                        job_id=record.get("job_id", ""))
            flight.dump(f"audit_divergence_demotion: "
                        f"{n_div} confirmed divergences "
                        f"(last: job {record.get('job_id', '?')})",
                        self.flight_dir)
            print(f"ict-serve: {n_div} confirmed audit "
                  "divergences vs the numpy oracle; demoting the service "
                  "to the oracle backend (repro bundles under "
                  f"{self.repro_dir})", file=sys.stderr)


# --- CLI ---

def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ict-serve",
        description="Long-running cleaning daemon: shape-bucketed admission, "
                    "warm executable pool, fault-isolated job execution "
                    "(docs/SERVING.md)")
    p.add_argument("--spool", default="./ict_serve_spool",
                   help="job-manifest directory; a restarted daemon resumes "
                        "the pending jobs found here (default: "
                        "./ict_serve_spool)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8750,
                   help="HTTP port (0 = ephemeral; default 8750)")
    p.add_argument("--bucket_cap", type=int, default=0, metavar="N",
                   help="archives per sharded dispatch (0 = the mesh's "
                        "data-parallel extent; clamped to a power of two)")
    p.add_argument("--deadline_s", type=float, default=2.0, metavar="S",
                   help="max seconds a partial bucket waits before it is "
                        "dispatched anyway (default 2.0)")
    p.add_argument("--loaders", type=int, default=2,
                   help="archive-decode threads (default 2)")
    p.add_argument("--spool_keep", type=int, default=10000, metavar="N",
                   help="finished-job manifests kept as history; older ones "
                        "are pruned at startup (default 10000)")
    p.add_argument("--max_open_jobs", type=int, default=64, metavar="N",
                   help="admission cap: submissions beyond N open jobs get "
                        "503 (backpressure — every open job can hold one "
                        "decoded cube on host; 0 = unbounded; default 64)")
    p.add_argument("--root", default="", metavar="DIR",
                   help="only accept archive paths under DIR (REQUIRED "
                        "hardening for non-loopback --host: without it any "
                        "reachable client can make the daemon read any file "
                        "and write a _cleaned output next to it)")
    p.add_argument("--alert_iters", type=int, default=2, metavar="N",
                   help="streaming sessions: bounded provisional clean-pass "
                        "iterations per ingested block (default 2; the "
                        "authoritative mask always comes from the canonical "
                        "finalize, docs/SERVING.md)")
    p.add_argument("--warm", action="append", default=[],
                   metavar="NSUBxNCHANxNBIN",
                   help="shape class to precompile at startup (repeatable), "
                        "e.g. --warm 256x1024x1024")
    p.add_argument("--audit_rate", type=float, default=-1.0, metavar="F",
                   help="shadow-oracle audit sampling fraction in [0, 1]: "
                        "this share of completed jobs is replayed through "
                        "the numpy oracle on a background thread and the "
                        "masks compared bit-for-bit (divergences write "
                        "repro bundles under <spool>/repro and show on "
                        "/healthz; docs/OBSERVABILITY.md).  Default: honor "
                        "ICT_AUDIT_RATE (0 = off); a per-job "
                        '{"audit": true} always audits')
    p.add_argument("--telemetry", default="", metavar="PATH",
                   help="append structured telemetry events (trace spans, "
                        "per-iteration forensics) to PATH as JSON lines "
                        "(docs/OBSERVABILITY.md; ICT_TELEMETRY env "
                        "equivalent; default off)")
    p.add_argument("--backend", choices=("numpy", "jax"), default="jax")
    p.add_argument("-c", "--chanthresh", type=float, default=5)
    p.add_argument("-s", "--subintthresh", type=float, default=5)
    p.add_argument("-m", "--max_iter", type=int, default=5)
    p.add_argument("--bad_chan", type=float, default=1)
    p.add_argument("--bad_subint", type=float, default=1)
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="offline self-check: start the daemon, clean one "
                        "synthetic archive through the HTTP API, verify the "
                        "mask against the numpy oracle, print one JSON line, "
                        "exit")
    return p


def parse_warm_shapes(specs: list[str]) -> tuple:
    shapes = []
    for spec in specs:
        try:
            nsub, nchan, nbin = (int(v) for v in spec.lower().split("x"))
            shapes.append((nsub, nchan, nbin))
        except ValueError:
            raise ValueError(
                f"bad --warm shape {spec!r}; expected NSUBxNCHANxNBIN "
                "like 256x1024x1024") from None
    return tuple(shapes)


def serve_config_from_args(args: argparse.Namespace) -> ServeConfig:
    # Reject ambiguous negatives up front (serve_main turns the ValueError
    # into the one-line error + rc 2 contract): -1 is NOT "unbounded" —
    # it would make the cap check refuse every submission forever.
    if args.max_open_jobs < 0:
        raise ValueError(f"--max_open_jobs must be >= 0 (0 = unbounded), "
                         f"got {args.max_open_jobs}")
    if args.bucket_cap < 0:
        raise ValueError(f"--bucket_cap must be >= 0 (0 = the mesh's dp "
                         f"extent), got {args.bucket_cap}")
    if args.alert_iters < 1:
        raise ValueError(f"--alert_iters must be >= 1, got {args.alert_iters}")
    if args.audit_rate > 1:
        raise ValueError(f"--audit_rate must be a fraction in [0, 1] "
                         f"(negative = honor ICT_AUDIT_RATE), got "
                         f"{args.audit_rate}")
    return ServeConfig(
        spool_dir=args.spool,
        host=args.host,
        port=args.port,
        bucket_cap=args.bucket_cap,
        deadline_s=args.deadline_s,
        loaders=args.loaders,
        spool_keep=args.spool_keep,
        max_open_jobs=args.max_open_jobs,
        alert_iters=args.alert_iters,
        root=args.root,
        telemetry=args.telemetry,
        audit_rate=args.audit_rate,
        warm_shapes=parse_warm_shapes(args.warm),
        quiet=args.quiet,
        clean=CleanConfig(
            backend=args.backend,
            chanthresh=args.chanthresh,
            subintthresh=args.subintthresh,
            max_iter=args.max_iter,
            bad_chan=args.bad_chan,
            bad_subint=args.bad_subint,
            quiet=args.quiet,
        ),
    )


def run_smoke(serve_cfg: ServeConfig) -> int:
    import json
    import tempfile
    import urllib.request

    import numpy as np

    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.io.npz import NpzIO
    from iterative_cleaner_tpu.io.synthetic import make_archive
    from iterative_cleaner_tpu.ops.preprocess import preprocess

    with tempfile.TemporaryDirectory(prefix="ict_serve_smoke_") as tmp:
        path = os.path.join(tmp, "smoke.npz")
        archive = make_archive(nsub=4, nchan=16, nbin=64, seed=99)
        NpzIO().save(archive, path)
        # Hermetic overrides: the smoke archive lives in this tempdir, so
        # an operator --root (or a tiny cap) must not refuse the probe.
        cfg = ServeConfig(**{**serve_cfg.__dict__,
                             "spool_dir": os.path.join(tmp, "spool"),
                             "port": 0, "deadline_s": 0.2,
                             "root": "", "max_open_jobs": 0})
        service = CleaningService(cfg)
        service.start()
        try:
            base = f"http://{cfg.host}:{service.port}"
            # Every smoke run exercises the shadow-oracle audit end-to-end
            # on top of the external mask check below — through the
            # SAMPLING path when it is deterministic (rate exactly 1.0,
            # the CI audit lane: genuinely covers the trigger the plain
            # lane cannot), through the per-job opt-in otherwise (a
            # FRACTIONAL rate would make the audits_run >= 1 requirement
            # a coin flip on a healthy daemon).
            want_flag = service.audit_rate() < 1.0
            req = urllib.request.Request(
                f"{base}/jobs",
                data=json.dumps({"path": path, "audit": want_flag}).encode(),
                headers={"Content-Type": "application/json"})
            job = json.load(urllib.request.urlopen(req, timeout=30))
            deadline = time.time() + 300
            while job["state"] not in TERMINAL and time.time() < deadline:
                time.sleep(0.1)
                job = json.load(urllib.request.urlopen(
                    f"{base}/jobs/{job['id']}", timeout=30))
            # The audit runs on a background thread; /healthz must read
            # its verdict, not its backlog.
            service.auditor.drain(60)
            health = json.load(urllib.request.urlopen(
                f"{base}/healthz", timeout=30))
            ok = job["state"] == "done" and health.get("status") == "ok"
            audits_ok = (health.get("audits_run", 0) >= 1
                         and health.get("audit_divergences", 0) == 0)
            masks_ok = False
            if ok:
                from iterative_cleaner_tpu.parallel.batch import (
                    finalize_weights,
                )

                cfg_np = cfg.clean.replace(backend="numpy")
                # Same finalization as every served route (shared helper):
                # the oracle comparison includes the bad-parts sweep.
                want, _rfi = finalize_weights(
                    clean_cube(*preprocess(archive), cfg_np).weights, cfg_np)
                got = NpzIO().load(job["out_path"])
                masks_ok = bool(np.array_equal(got.weights, want))
            print(json.dumps({
                "smoke": "ok" if ok and masks_ok and audits_ok else "FAIL",
                "job_state": job["state"],
                "served_by": job.get("served_by", ""),
                "mask_identical_to_oracle": masks_ok,
                "audits_run": health.get("audits_run", 0),
                "audit_divergences": health.get("audit_divergences", 0),
                "backend": health.get("backend"),
            }))
            return 0 if ok and masks_ok and audits_ok else 1
        finally:
            service.stop()


def serve_main(argv: list[str] | None = None) -> int:
    args = build_serve_parser().parse_args(argv)
    try:
        serve_cfg = serve_config_from_args(args)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if serve_cfg.clean.backend == "jax":
        # Same CLI-layer policy as cli.main (one shared helper): persistent
        # XLA compile cache on by default, size-bounded at startup — a
        # long-lived heterogeneous-shape service is exactly the unbounded-
        # growth workload (ADVICE r05).
        from iterative_cleaner_tpu.utils.compile_cache import (
            enable_and_trim_persistent_cache,
        )

        enable_and_trim_persistent_cache()
    if args.smoke:
        return run_smoke(serve_cfg)
    service = CleaningService(serve_cfg)
    try:
        service.start()
    except (RuntimeError, OSError) as exc:
        # e.g. the spool's single-daemon flock, or EADDRINUSE on the bind —
        # the operator contract is a one-line error + rc 1, not a traceback.
        print(f"error: {exc}", file=sys.stderr)
        return 1
    # SIGTERM (the orchestrator's stop signal) and SIGINT (a Ctrl-C'd dev
    # daemon) both dump the flight ring before the graceful shutdown:
    # "what was the daemon doing when it was killed" becomes a file in the
    # spool instead of a guess — dev forensics matter as much as
    # production ones.  Registered only for the real daemon run (not
    # --smoke, not library embedders), and only from the main thread
    # (signal.signal refuses elsewhere).
    import signal

    def _on_stop_signal(signum, frame):
        name = signal.Signals(signum).name
        path = flight.dump(name, service.flight_dir)
        print(f"ict-serve: {name} — shutting down (unfinished jobs stay in "
              f"the spool{'; flight ring at ' + path if path else ''})",
              file=sys.stderr)
        raise SystemExit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_stop_signal)
        except (ValueError, OSError):  # noqa: PERF203 — non-main-thread embed
            pass
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # Reached only when the SIGINT handler could not be installed (a
        # non-main-thread embed): same graceful stop, same flight dump.
        path = flight.dump("KeyboardInterrupt", service.flight_dir)
        print("ict-serve: shutting down (unfinished jobs stay in the spool"
              f"{'; flight ring at ' + path if path else ''})",
              file=sys.stderr)
    finally:
        service.stop()
    return 0


def console_main() -> int:
    return serve_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(serve_main())
