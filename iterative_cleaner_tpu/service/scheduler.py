"""Shape-bucketed admission: fill a coalesced dp slice or hit a deadline.

The batching rules are the ones `parallel/batch.py` established for
directories, applied to a continuous arrival stream:

- same-shape cubes stack into ONE sharded dispatch (zero-weight padding
  is never used — it would perturb the mask-blind FFT diagnostic, see
  parallel/sharded.py);
- the **coalescing rung** (ROADMAP item 2's throughput half): the flush
  threshold is ``dp_cap x coalesce`` cubes — one data-parallel slice
  times a pow2 coalesce factor — so ONE ``batched_fused_clean`` launch
  amortizes over K cubes, each device vmapping ``coalesce`` archives of
  its slice.  ``coalesce=1`` (the default) is the historical
  one-archive-per-slice behavior; raising it trades bounded added
  latency (the deadline still caps the wait) and per-device residency
  (``coalesce`` cubes live per chip) for launch amortization on
  small-cube campaign traffic;
- a bucket flushes the moment it holds ``bucket_cap`` cubes, or when its
  OLDEST entry has waited ``deadline_s`` (latency bound for sparse
  traffic);
- deadline flushes are chunked to power-of-two batch sizes, the
  clean_directory_streaming pressure-flush trick: the batched executable
  specializes on batch size, so pow2 chunking bounds the compile set to
  O(log cap) sizes per shape — exactly the set service/pool.py precompiles
  at startup (dp_cap and coalesce are each pow2-clamped, so their product
  keeps the warm-pool key set closed), which is what makes "an
  already-warm shape never compiles" hold for partial buckets too.

The scheduler owns no threads: the daemon's loader threads call
:meth:`offer` and a tick loop calls :meth:`tick`; ``flush_fn(entries)``
must be cheap (the worker enqueues, it does not dispatch inline).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from iterative_cleaner_tpu.io.base import Archive
from iterative_cleaner_tpu.obs import events, tracing
from iterative_cleaner_tpu.service.jobs import Job

#: Canonical shape-bucket label, ``8x16x64`` — ONE grammar shared by the
#: ``--warm`` CLI spec, ``/healthz`` bucket depths, the fleet router's
#: placement keys, and compile-scope attribution.  The implementation
#: lives in obs/tracing.py (the lowest layer that needs it); this alias
#: is the name the service/fleet tier imports, so the two spellings can
#: never drift apart again (tests/test_coalesce.py pins the unification).
bucket_label = tracing.shape_bucket_label


@dataclass
class Entry:
    """One admitted job with its decoded cube (host arrays)."""

    job: Job
    archive: Archive
    D: np.ndarray
    w0: np.ndarray
    arrived_s: float            # time.monotonic() — immune to clock steps


def pow2_chunks(n: int, cap: int) -> list[int]:
    """Split ``n`` into power-of-two chunk sizes <= cap, largest first
    (5, cap 4 -> [4, 1]) — the closed set of batch sizes the scheduler can
    emit, {1, 2, 4, ..., cap}."""
    sizes = []
    while n > 0:
        k = 1 << (n.bit_length() - 1)
        k = min(k, 1 << (cap.bit_length() - 1))
        sizes.append(k)
        n -= k
    return sizes


class ShapeBucketScheduler:
    def __init__(self, bucket_cap: int, deadline_s: float, flush_fn,
                 coalesce: int = 1) -> None:
        if bucket_cap < 1:
            raise ValueError(f"bucket_cap must be >= 1, got {bucket_cap}")
        if coalesce < 1:
            raise ValueError(f"coalesce must be >= 1, got {coalesce}")
        # Clamp to powers of two HERE, in the mechanism that owns the
        # invariant: full-bucket flushes emit exactly bucket_cap entries
        # unchunked, and the warm pool only precompiles pow2 batch sizes —
        # a cap of 3 would dispatch batches no warm set covers.  dp_cap
        # and coalesce are clamped separately so their product (the
        # effective flush threshold) stays pow2 AND dp-divisible: a full
        # coalesced batch shards evenly over the mesh's dp axis, each
        # device vmapping `coalesce` archives.
        self.dp_cap = 1 << (int(bucket_cap).bit_length() - 1)
        self.coalesce = 1 << (int(coalesce).bit_length() - 1)
        self.bucket_cap = self.dp_cap * self.coalesce
        self.deadline_s = float(deadline_s)
        self._flush_fn = flush_fn
        self._buckets: dict[tuple, list[Entry]] = {}  # ict: guarded-by(self._lock)
        self._lock = threading.Lock()

    def offer(self, job: Job, archive: Archive, D, w0) -> None:
        """Admit one decoded cube; flushes its bucket if that fills a dp
        slice.  Shape is the preprocessed-cube shape — the executable
        identity, exactly the key parallel/batch buckets on."""
        entry = Entry(job=job, archive=archive, D=D, w0=w0,
                      arrived_s=time.monotonic())
        job.shape = list(D.shape)
        if events.active():
            events.emit("admission", trace_id=job.trace_id, job_id=job.id,
                        shape=list(D.shape))
        flush = None
        with self._lock:
            group = self._buckets.setdefault(tuple(D.shape), [])
            group.append(entry)
            if len(group) >= self.bucket_cap:
                flush = self._buckets.pop(tuple(D.shape))
        if flush:
            tracing.count("service_bucket_full_flushes")
            self._flush_fn(flush)

    def tick(self, now: float | None = None) -> None:
        """Flush every bucket whose oldest entry has exceeded the deadline,
        in pow2 chunks (see module docstring)."""
        now = time.monotonic() if now is None else now
        due: list[list[Entry]] = []
        with self._lock:
            for shape in [s for s, g in self._buckets.items()
                          if now - g[0].arrived_s >= self.deadline_s]:
                due.append(self._buckets.pop(shape))
        for group in due:
            tracing.count("service_bucket_deadline_flushes")
            self._emit_chunks(group)

    def flush_all(self) -> None:
        """Drain everything (shutdown / drain barrier)."""
        with self._lock:
            groups = list(self._buckets.values())
            self._buckets.clear()
        for group in groups:
            self._emit_chunks(group)

    def _emit_chunks(self, group: list[Entry]) -> None:
        i = 0
        for size in pow2_chunks(len(group), self.bucket_cap):
            self._flush_fn(group[i: i + size])
            i += size

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(g) for g in self._buckets.values())

    def pending_by_bucket(self) -> dict[str, int]:
        """Queued-cube depth per shape bucket, keyed by the ``NSUBxNCHANx
        NBIN`` label (the ``--warm`` spec grammar).  This is the
        bucket-resolved signal the fleet router's affinity placement
        reads off ``/healthz`` — the aggregate depths alone cannot tell
        it WHICH replica is already working a shape."""
        with self._lock:
            return {bucket_label(shape): len(group)
                    for shape, group in self._buckets.items()}
