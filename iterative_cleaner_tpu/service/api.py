"""JSON HTTP surface over stdlib ``http.server`` — zero new dependencies.

Endpoints (JSON unless noted; full reference in docs/SERVING.md and
docs/OBSERVABILITY.md):

- ``POST /jobs``            ``{"path": "/abs/archive.npz"}`` -> 202 + job
                            (the response and its ``X-ICT-Trace`` header
                            carry the job's telemetry ``trace_id``; an
                            inbound ``X-ICT-Trace`` — the fleet router's
                            proxied hop — is adopted instead of minting;
                            the 202 body carries ``replica_id`` so trace
                            logs attribute jobs to replicas; an optional
                            ``"idempotency_key"`` dedupes re-submissions —
                            the router's failover path)
- ``POST /drain``           enter/leave drain mode (body optional
                            ``{"drain": false}`` to undrain): a draining
                            replica 503s new submissions, reports
                            ``draining: true`` on ``/healthz`` (the fleet
                            router stops placing on it), and flushes
                            parked partial buckets so accepted work
                            finishes fast
- ``GET  /jobs/<id>``       job manifest (state machine in service/jobs.py)
- ``GET  /jobs/<id>/trace`` convergence forensics: trace id, termination
                            reason, per-iteration timeline
- ``POST /sessions``        open a streaming session (body: SessionMeta
                            fields + optional out_path/alert_iters)
- ``POST /sessions/<id>/blocks``  one subint block as an NPZ body
                            (online/blocks.py) -> provisional zap alert
- ``POST /sessions/<id>/finish``  canonical finalize -> final manifest
- ``GET  /sessions/<id>``   session manifest
- ``GET  /healthz``         liveness + backend mode + uptime/version +
                            queue/spool depths (the load-balancer drain view)
- ``GET  /metrics``         Prometheus text exposition (obs/metrics.py):
                            per-phase log2 latency histograms, counters,
                            compile/cache accounting with shape-bucket and
                            route labels
- ``GET  /metrics.json``    the legacy raw-JSON counter snapshot
                            (obs/tracing.py: ``*_s`` total seconds, ``*_n``
                            counts, ``*_err_n`` failures, ``*_max_s`` worst
                            single occurrence, ``service_*``/``online_*``
                            events)
- ``POST /debug/profile``   start a bounded ``jax.profiler`` capture around
                            whatever is in flight (body: optional
                            ``{"duration_s": 5}``; ``{"stop": true}`` ends
                            the running one); 409 when a capture is already
                            running (obs/profiling.py)
- ``GET  /debug/profiles``  list capture artifacts (name/bytes/files/mtime)
                            plus the active capture, if any
- ``GET  /debug/flight``    the always-on flight-recorder ring of recent
                            events/phase timings (obs/flight.py) — the live
                            view of what fault-ladder/SIGTERM dumps write
- ``GET  /debug/memory``    host RSS + per-device HBM view + recorded
                            executable analyses (obs/memory.py)
- ``GET  /debug/audit``     shadow-oracle audit state (obs/audit.py):
                            cumulative counters, sampling rate, queue
                            depth, recent audit records, and the repro
                            bundles on disk

ThreadingHTTPServer: each request gets a thread, so a slow client cannot
stall the poll loop; all handlers only touch thread-safe service surfaces
(spool writes are serialized, counters are locked, submission enqueues,
session mutations hold per-session locks).
"""

from __future__ import annotations

import json
import os
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from iterative_cleaner_tpu.obs import metrics as obs_metrics
from iterative_cleaner_tpu.obs import tracing

#: Default per-socket-read timeout; ``ICT_HTTP_TIMEOUT_S`` overrides — a
#: streaming client uploading multi-hundred-MB blocks over a slow link
#: needs more than the one-shot default, and raising it globally for
#: everyone would let dead sockets pin handler threads longer.
DEFAULT_HTTP_TIMEOUT_S = 30.0


def http_timeout_s() -> float:
    env = os.environ.get("ICT_HTTP_TIMEOUT_S")
    if env is None:
        return DEFAULT_HTTP_TIMEOUT_S
    try:
        val = float(env)
        if val <= 0:
            raise ValueError
        return val
    except ValueError:
        print(f"warning: ignoring unparseable ICT_HTTP_TIMEOUT_S={env!r} "
              f"(want a positive seconds count); using "
              f"{DEFAULT_HTTP_TIMEOUT_S:g}", file=sys.stderr)
        return DEFAULT_HTTP_TIMEOUT_S


class _Handler(BaseHTTPRequestHandler):
    # Bound every socket read (BaseRequestHandler.setup applies this via
    # connection.settimeout): a client that under-sends its declared body
    # or never sends a request line must time out, not leak this handler
    # thread and its FD forever.  The value is resolved per server at bind
    # time (make_http_server) so ICT_HTTP_TIMEOUT_S takes effect without
    # mutating class state shared by other servers in the process.
    timeout = DEFAULT_HTTP_TIMEOUT_S

    def setup(self) -> None:
        self.timeout = self.server.http_timeout_s
        BaseHTTPRequestHandler.setup(self)

    # The default handler logs every request line to stderr; route through
    # the service's quiet flag instead (a health-checked daemon would spam).
    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        if not self.server.service.serve_cfg.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code: int, payload: dict, headers: dict | None = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if isinstance(payload, dict) and payload.get("trace_id"):
            # Echo the telemetry trace context wherever a payload carries
            # one, so header-only clients can correlate with the event log.
            self.send_header("X-ICT-Trace", str(payload["trace_id"]))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self, clamp: int) -> bytes:
        # Clamp the client-supplied length: a negative value would make
        # read() block until EOF (leaking this handler thread) and a
        # huge one would buffer it all.  A MALFORMED header reads as an
        # empty body — the downstream parse then 400s, it never drops the
        # socket (online/blocks.py's contract).
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            n = 0
        return self.rfile.read(max(0, min(n, clamp)))

    def do_GET(self) -> None:  # noqa: N802 — stdlib signature
        service = self.server.service
        if self.path == "/healthz":
            self._reply(200, service.health())
        elif self.path == "/metrics":
            self._reply_text(200, obs_metrics.render_prometheus(),
                             obs_metrics.CONTENT_TYPE)
        elif self.path == "/metrics.json":
            self._reply(200, tracing.counters_snapshot())
        elif self.path == "/costs":
            # The replica's lifetime showback ledger (obs/costs.py):
            # spool-persisted, restart-resumed — the durable record next
            # to the per-process-life ict_cost_* counters on /metrics.
            self._reply(200, service.ctx.cost_ledger.report())
        elif self.path.startswith("/jobs/"):
            jid, sep, verb = self.path[len("/jobs/"):].partition("/")
            job = service.job(jid)
            if job is None or (sep and verb != "trace"):
                self._reply(404, {"error": "no such job"
                                  if job is None else
                                  f"no such route {self.path!r}"})
            elif sep:
                # replica_id rides on the trace the same way it rides on
                # the 202: the fleet router's cross-hop trace assembly
                # labels each stitched span with its source replica.
                self._reply(200, {**job.trace_dict(),
                                  "replica_id": service.replica_id})
            else:
                self._reply(200, job.to_dict())
        elif self.path == "/debug/profiles":
            from iterative_cleaner_tpu.obs import profiling

            self._reply(200, {
                "active": profiling.active(),
                "profiles": profiling.list_profiles(service.profile_root),
            })
        elif self.path == "/debug/flight":
            from iterative_cleaner_tpu.obs import flight

            self._reply(200, {
                "enabled": flight.enabled(),
                "capacity": flight.capacity(),
                "events": flight.snapshot(),
            })
        elif self.path == "/debug/memory":
            from iterative_cleaner_tpu.obs import memory as obs_memory

            self._reply(200, obs_memory.memory_report())
        elif self.path == "/debug/audit":
            from iterative_cleaner_tpu.obs import audit as obs_audit

            report = obs_audit.audit_report()
            report["rate"] = service.audit_rate()
            report["queue_depth"] = (service.auditor.queue_depth()
                                     if service.auditor else 0)
            report["recent"] = (service.auditor.recent()
                                if service.auditor else [])
            report["bundles"] = obs_audit.list_bundles(service.repro_dir)
            self._reply(200, report)
        elif self.path.startswith("/sessions/"):
            sid = self.path[len("/sessions/"):]
            self._session_call(lambda s: s.manifest(sid))
        else:
            self._reply(404, {"error": f"no such route {self.path!r}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib signature
        service = self.server.service
        if self.path == "/jobs":
            self._post_job()
            return
        if self.path == "/sessions":
            self._post_session_open()
            return
        if self.path == "/debug/profile":
            self._post_debug_profile()
            return
        if self.path == "/drain":
            self._post_drain()
            return
        if self.path.startswith("/sessions/"):
            rest = self.path[len("/sessions/"):]
            sid, sep, verb = rest.partition("/")
            if sep and verb == "blocks":
                from iterative_cleaner_tpu.online.blocks import (
                    MAX_BLOCK_BYTES,
                )

                payload = self._read_body(MAX_BLOCK_BYTES)
                self._session_call(lambda s: s.add_block(sid, payload))
                return
            if sep and verb == "finish":
                self._session_call(lambda s: s.finish(sid))
                return
        self._reply(404, {"error": f"no such route {self.path!r}"})

    # --- debug: profiler capture (obs/profiling) ---

    def _post_debug_profile(self) -> None:
        service = self.server.service
        from iterative_cleaner_tpu.obs import profiling

        try:
            body = json.loads(self._read_body(1 << 20) or b"{}")
            if not isinstance(body, dict):
                raise TypeError("body must be a JSON object")
            stop = bool(body.get("stop", False))
            duration_s = float(body.get("duration_s", 5.0))
        except (ValueError, TypeError) as exc:
            self._reply(400, {"error": f"bad profile request: {exc!r}; "
                                       'expected {"duration_s": 5} or '
                                       '{"stop": true}'})
            return
        if stop:
            rec = profiling.stop()
            if rec is None:
                self._reply(409, {"error": "no capture is running"})
            else:
                self._reply(200, rec)
            return
        try:
            rec = profiling.start(service.profile_root, duration_s=duration_s)
        except RuntimeError as exc:   # capture already running
            self._reply(409, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 — the client deserves a 500
            self._reply(500, {"error": f"profiler start failed: {exc}"})
            return
        self._reply(200, rec)

    # --- drain mode (the fleet router's /healthz-driven eviction hook) ---

    def _post_drain(self) -> None:
        service = self.server.service
        try:
            body = json.loads(self._read_body(1 << 20) or b"{}")
            if not isinstance(body, dict):
                raise TypeError("body must be a JSON object")
            flag = bool(body.get("drain", True))
        except (ValueError, TypeError) as exc:
            self._reply(400, {"error": f"bad drain request: {exc!r}; "
                                       'expected {} or {"drain": false}'})
            return
        service.set_draining(flag)
        self._reply(200, {"replica_id": service.replica_id,
                          "draining": flag})

    # --- jobs ---

    def _post_job(self) -> None:
        service = self.server.service
        try:
            body = json.loads(self._read_body(1 << 20) or b"{}")
            path = body["path"]
            profile = bool(body.get("profile", False))
            audit = bool(body.get("audit", False))
            idem_key = str(body.get("idempotency_key", "") or "")
            tenant = str(body.get("tenant", "") or "")
            # Router-injected canary probes (fleet/canary.py) stamp this;
            # it rides the job record end-to-end so every observer can
            # exclude synthetic traffic from the planes it measures.
            synthetic = bool(body.get("synthetic", False))
            shape = body.get("shape")
            if shape is not None:
                # Same optional grammar the fleet router accepts: the
                # declared [nsub, nchan, nbin] hint rides into the
                # job_submitted event so a recorded trace replays with
                # its original bucket (proving/traces.py).
                shape = [int(v) for v in shape]
        # TypeError covers valid-JSON non-dict bodies ('[]', '5', 'null'):
        # the client gets a 400, not a dropped socket.
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"error": f"bad request body: {exc!r}; expected "
                                       '{"path": "/abs/archive"}'})
            return
        from iterative_cleaner_tpu.service.daemon import ServiceBusy

        # A submission that already crossed the fleet router carries its
        # trace context in the X-ICT-Trace header; adopt it instead of
        # minting so the event log threads router placement -> replica
        # dispatch under ONE trace_id.  The tenant rides the same way
        # (the router forwards its admission tenant in the body; direct
        # submitters may send the X-ICT-Tenant header) — it is the cost
        # ledger's showback key (obs/costs.py).
        trace_id = str(self.headers.get("X-ICT-Trace", "") or "")
        tenant = tenant or str(self.headers.get("X-ICT-Tenant", "") or "")
        try:
            job = service.submit(str(path), profile=profile, audit=audit,
                                 idempotency_key=idem_key,
                                 trace_id=trace_id, tenant=tenant,
                                 shape=shape, synthetic=synthetic)
        except ServiceBusy as exc:
            self._reply(503, {"error": str(exc)}, headers={"Retry-After": "5"})
            return
        except ValueError as exc:   # --root refusal
            self._reply(400, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 — e.g. a spool write failure:
            # the client deserves a 500, not a dropped socket
            self._reply(500, {"error": f"submission failed: {exc}"})
            return
        # replica_id rides on every 202 so multi-replica trace logs (and
        # the fleet router's placement table) attribute jobs to replicas.
        self._reply(202, {**job.to_dict(), "replica_id": service.replica_id})

    # --- streaming sessions ---

    def _post_session_open(self) -> None:
        service = self.server.service
        try:
            body = json.loads(self._read_body(1 << 20) or b"{}")
            if not isinstance(body, dict):
                raise TypeError("body must be a JSON object")
            out_path = body.pop("out_path", None)
            alert_iters = body.pop("alert_iters", None)
            if out_path:
                # The write target obeys the same --root trust boundary as
                # submitted read paths (docs/SERVING.md trust model).
                out_path = service._check_root(str(out_path))
        except (ValueError, TypeError) as exc:
            self._reply(400, {"error": f"bad session request: {exc}"})
            return
        self._session_call(
            lambda s: s.create(body, out_path=out_path,
                               alert_iters=alert_iters), code=201)

    def _session_call(self, fn, code: int = 200) -> None:
        """Run one SessionManager operation with the shared error mapping
        (unknown id → 404, closed → 409, bad payload → 400)."""
        from iterative_cleaner_tpu.service.sessions import (
            SessionClosed,
            UnknownSession,
        )

        sessions = self.server.service.sessions
        if sessions is None:
            self._reply(404, {"error": "streaming sessions are disabled"})
            return
        try:
            self._reply(code, fn(sessions))
        except UnknownSession:
            self._reply(404, {"error": "no such session"})
        except SessionClosed as exc:
            self._reply(409, {"error": str(exc)})
        except ValueError as exc:
            self._reply(400, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 — the client deserves a 500
            self._reply(500, {"error": f"session operation failed: {exc}"})


def make_http_server(service, host: str, port: int) -> ThreadingHTTPServer:
    """Bind (port 0 -> ephemeral, for tests); caller runs serve_forever on
    a thread and shutdown() on stop."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.service = service
    server.http_timeout_s = http_timeout_s()
    return server
