"""JSON HTTP surface over stdlib ``http.server`` — zero new dependencies.

Endpoints (all JSON; full reference in docs/SERVING.md):

- ``POST /jobs``            ``{"path": "/abs/archive.npz"}`` -> 202 + job
- ``GET  /jobs/<id>``       job manifest (state machine in service/jobs.py)
- ``GET  /healthz``         liveness + backend mode + queue depths
- ``GET  /metrics``         the process-global per-phase counters
                            (utils/tracing.py: ``*_s`` total seconds,
                            ``*_n`` counts, ``service_*`` events)

ThreadingHTTPServer: each request gets a thread, so a slow client cannot
stall the poll loop; all handlers only touch thread-safe service surfaces
(spool writes are serialized, counters are locked, submission enqueues).
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from iterative_cleaner_tpu.utils import tracing


class _Handler(BaseHTTPRequestHandler):
    # Bound every socket read (BaseRequestHandler.setup applies this via
    # connection.settimeout): a client that under-sends its declared body
    # or never sends a request line must time out, not leak this handler
    # thread and its FD forever.
    timeout = 30

    # The default handler logs every request line to stderr; route through
    # the service's quiet flag instead (a health-checked daemon would spam).
    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        if not self.server.service.serve_cfg.quiet:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code: int, payload: dict, headers: dict | None = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — stdlib signature
        service = self.server.service
        if self.path == "/healthz":
            self._reply(200, service.health())
        elif self.path == "/metrics":
            self._reply(200, tracing.counters_snapshot())
        elif self.path.startswith("/jobs/"):
            job = service.job(self.path[len("/jobs/"):])
            if job is None:
                self._reply(404, {"error": "no such job"})
            else:
                self._reply(200, job.to_dict())
        else:
            self._reply(404, {"error": f"no such route {self.path!r}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib signature
        service = self.server.service
        if self.path != "/jobs":
            self._reply(404, {"error": f"no such route {self.path!r}"})
            return
        try:
            # Clamp the client-supplied length: a negative value would make
            # read() block until EOF (leaking this handler thread) and a
            # huge one would buffer it all; job bodies are tiny.
            n = max(0, min(int(self.headers.get("Content-Length", 0)),
                           1 << 20))
            body = json.loads(self.rfile.read(n) or b"{}")
            path = body["path"]
        # TypeError covers valid-JSON non-dict bodies ('[]', '5', 'null'):
        # the client gets a 400, not a dropped socket.
        except (ValueError, KeyError, TypeError) as exc:
            self._reply(400, {"error": f"bad request body: {exc!r}; expected "
                                       '{"path": "/abs/archive"}'})
            return
        from iterative_cleaner_tpu.service.daemon import ServiceBusy

        try:
            job = service.submit(str(path))
        except ServiceBusy as exc:
            self._reply(503, {"error": str(exc)}, headers={"Retry-After": "5"})
            return
        except ValueError as exc:   # --root refusal
            self._reply(400, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 — e.g. a spool write failure:
            # the client deserves a 500, not a dropped socket
            self._reply(500, {"error": f"submission failed: {exc}"})
            return
        self._reply(202, job.to_dict())


def make_http_server(service, host: str, port: int) -> ThreadingHTTPServer:
    """Bind (port 0 -> ephemeral, for tests); caller runs serve_forever on
    a thread and shutdown() on stop."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.service = service
    return server
