"""Job records + the on-disk spool that makes the daemon restart-safe.

One JSON manifest per job under the spool directory, written atomically
(write-then-rename, the driver.atomic_save idiom) so a daemon killed
mid-update never leaves a truncated manifest.  A restarted daemon replays
the spool: ``pending`` jobs resume as-is, and ``running`` jobs — whose
dispatch died with the process — are demoted back to ``pending`` and
re-dispatched (masks are deterministic, so a re-run is idempotent up to
overwriting its own output).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field

#: Job lifecycle: pending -> running -> done | error.
STATES = ("pending", "running", "done", "error")
TERMINAL = ("done", "error")


def new_job_id() -> str:
    """Time-sortable unique id: submission order survives a spool replay
    (lexicographic sort of ids == arrival order) without a separate
    sequence file to keep crash-consistent."""
    return f"{int(time.time() * 1000):013d}-{uuid.uuid4().hex[:8]}"


@dataclass
class Job:
    id: str
    path: str                       # archive to clean
    state: str = "pending"
    submitted_s: float = 0.0        # unix time
    finished_s: float = 0.0
    out_path: str | None = None
    loops: int = 0
    rfi_frac: float = 0.0
    converged: bool = False
    error: str | None = None
    attempts: int = 0               # dispatch attempts (retry accounting)
    served_by: str = ""             # "sharded" | "oracle-fallback"
    shape: list[int] = field(default_factory=list)  # cube shape once decoded
    trace_id: str = ""              # telemetry trace context (obs/events.py):
                                    # minted at admission, echoed in every
                                    # HTTP response and event-log line
    termination: str = ""           # forensics: fixed_point | cycle | max_iter
    profile: bool = False           # submitter asked for a jax.profiler
                                    # capture around this job's dispatch
                                    # (obs/profiling.py)
    profile_dir: str = ""           # capture artifact directory, once taken
    audit: bool = False             # submitter asked for a shadow-oracle
                                    # parity audit of this job (obs/audit.py;
                                    # ICT_AUDIT_RATE samples the rest)
    content_key: str = ""           # content address of the cleaning
                                    # problem (ingest/cas.cube_key:
                                    # preprocessed cube bytes + config/
                                    # version salt), stamped at ingest —
                                    # the replica-side result cache's key
    file_digest: str = ""           # plain SHA-256 of the archive file's
                                    # raw bytes (ingest/cas.file_digest) —
                                    # the fleet router's placement-time
                                    # cache key, paired with cache_salt
    cache_salt: str = ""            # the serving replica's config/version
                                    # salt (ingest/cas.cache_salt): a
                                    # cached result only answers
                                    # submissions under the same salt
    idem_key: str = ""              # submitter-supplied idempotency key
                                    # (the fleet router's failover path):
                                    # a re-submission carrying the same key
                                    # dedupes against this job instead of
                                    # running it twice (service/context.py)
    tenant: str = ""                # showback identity (X-ICT-Tenant /
                                    # the router's forwarded "tenant"
                                    # field; "" reads as "default") — the
                                    # cost ledger's aggregation key
                                    # (obs/costs.py)
    synthetic: bool = False         # router-injected canary probe
                                    # (fleet/canary.py): stamped end-to-
                                    # end so every observer can exclude
                                    # it from demand/quota/cost planes
    # Cost accounting (obs/costs.py): device-seconds split by phase,
    # compile seconds, apportioned static bytes/FLOPs, coalesced batch
    # size, cache-hit avoided cost, attainment — stamped by the dispatch
    # worker, persisted on the manifest (ISSUE 15's showback record).
    cost: dict = field(default_factory=dict)
    # Shadow-audit outcome, re-persisted once the background replay
    # finishes: mask_identical, n_mask_diffs, score drift vs the
    # documented bound, and the repro-bundle path on a divergence.
    audit_result: dict = field(default_factory=dict)
    # RFI data-quality summary of the served mask (obs/quality.py): zap
    # fraction, occupancy histograms, fully-zapped channel/subint counts.
    quality: dict = field(default_factory=dict)
    # XLA's static accounting of the executable that served this job's
    # shape bucket (obs/memory.py: bytes accessed, FLOPs, buffer split) —
    # attached when exec analysis is enabled, persisted on the manifest.
    exec_analysis: dict = field(default_factory=dict)
    # Per-iteration forensics records (obs.forensics.iteration_record dicts)
    # — served by GET /jobs/<id>/trace, EXCLUDED from to_dict so the job
    # manifest responses stay lean.
    timeline: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("timeline", None)
        return d

    def trace_dict(self) -> dict:
        """The GET /jobs/<id>/trace payload: identity + convergence
        forensics (per-iteration timeline, termination reason)."""
        return {
            "id": self.id,
            "trace_id": self.trace_id,
            "state": self.state,
            "served_by": self.served_by,
            "loops": self.loops,
            "converged": self.converged,
            "termination": self.termination,
            "timeline": self.timeline,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Job":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class JobSpool:
    """Directory of per-job JSON manifests; the daemon's durable state.

    All mutation goes through :meth:`save` under one lock — manifests are
    tiny, and serialized writes keep the rename-atomic invariant simple
    across the loader/worker/HTTP threads.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        # Written only by start()/stop() on the daemon's lifecycle thread;
        # worker/HTTP threads never touch the flock fd.
        self._flock_fd: int | None = None  # ict: guarded-by(none: lifecycle-thread only)

    def acquire_exclusive(self) -> None:
        """Take the spool's single-daemon flock.  Two daemons on one spool
        would sweep each other's atomic-write temps and re-dispatch each
        other's running jobs, so the daemon takes this before touching any
        manifest.  flock, not a pid file: the kernel releases it when the
        process dies, so there is no stale-lock handling."""
        import fcntl

        fd = os.open(os.path.join(self.root, ".lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as exc:
            os.close(fd)
            raise RuntimeError(
                f"spool {self.root!r} is already served by another daemon "
                "(its .lock is held); use a separate --spool per daemon"
            ) from exc
        self._flock_fd = fd

    def release_exclusive(self) -> None:
        if self._flock_fd is not None:
            os.close(self._flock_fd)   # closing drops the flock
            self._flock_fd = None

    def _manifest(self, job_id: str) -> str | None:
        """Manifest path for an id, or None for anything that is not a
        plain filename — ids come straight off the HTTP path
        (GET /jobs/<id>), so '../'-shaped ids must never resolve outside
        the spool directory."""
        name = f"{job_id}.json"
        if os.path.basename(name) != name or job_id.startswith("."):
            return None
        return os.path.join(self.root, name)

    def create(self, path: str) -> Job:
        job = Job(id=new_job_id(), path=path, submitted_s=time.time())
        self.save(job)
        return job

    def save(self, job: Job) -> None:
        p = self._manifest(job.id)
        if p is None:
            raise ValueError(f"unsaveable job id {job.id!r}")
        tmp = f"{p}.part"
        with self._lock:
            with open(tmp, "w") as fh:
                # The FULL record, timeline included (to_dict trims it for
                # HTTP responses only): the spool is the durable store the
                # trace endpoint reads back after a restart.
                json.dump(dataclasses.asdict(job), fh, indent=1)
                fh.write("\n")
            os.replace(tmp, p)

    def get(self, job_id: str) -> Job | None:
        p = self._manifest(job_id)
        if p is None:
            return None
        try:
            with open(p) as fh:
                d = json.load(fh)
            if not isinstance(d, dict):
                return None
            job = Job.from_dict(d)
            if job.id != job_id:
                # The content id must round-trip to the filename: a foreign
                # manifest with a traversal-shaped or mismatched inner id
                # would otherwise crash recover()'s re-persist (save
                # rejects it) or duplicate the job under a second name.
                return None
            return job
        # TypeError covers foreign/schema-drifted JSON (an operator note
        # dropped into the spool, a manifest missing required fields): one
        # unreadable file must degrade to "not a job", never crash-loop
        # the startup replay that reads every manifest.
        except (OSError, ValueError, TypeError):
            return None

    def all_jobs(self) -> list[Job]:
        jobs = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".json"):
                continue
            job = self.get(name[: -len(".json")])
            if job is not None:
                jobs.append(job)
        return jobs

    def recover(self, jobs: list[Job] | None = None) -> list[Job]:
        """Jobs a previous daemon left unfinished, in submission order.
        ``running`` manifests are demoted to ``pending`` (their dispatch
        died with the process) and re-persisted before being handed back,
        so a crash during the replay itself loses nothing.  ``jobs`` lets
        the startup path share one all_jobs() directory scan with trim()."""
        pending = []
        for job in (self.all_jobs() if jobs is None else jobs):
            if job.state == "running":
                job.state = "pending"
                job.attempts = 0
                self.save(job)
            if job.state == "pending":
                pending.append(job)
        return pending

    def trim(self, keep_terminal: int, jobs: list[Job] | None = None) -> int:
        """Delete the oldest TERMINAL manifests beyond ``keep_terminal``
        (daemon startup, the compile-cache-trim rationale: a long-lived
        daemon is exactly the unbounded-growth workload).  Pending/running
        manifests — accepted, unserved work — are never touched.  Returns
        how many were removed.  ``jobs`` shares the startup directory scan
        with recover()."""
        if keep_terminal < 0:
            return 0
        # Sweep orphaned atomic-write temps first: a daemon killed between
        # the .part write and the rename leaves one behind, and nothing
        # else ever looks at them.  trim() runs under the startup flock,
        # before any writer thread exists, so no live .part can be swept.
        for name in os.listdir(self.root):
            if name.endswith(".json.part"):
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass
        if jobs is None:
            jobs = self.all_jobs()
        terminal = [j for j in jobs if j.state in TERMINAL]
        removed = 0
        for job in terminal[: max(len(terminal) - keep_terminal, 0)]:
            p = self._manifest(job.id)
            try:
                os.remove(p)
                removed += 1
            except OSError:
                continue
        return removed
