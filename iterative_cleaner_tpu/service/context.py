"""ReplicaContext: one serving replica's identity + shared mutable state.

Before the fleet tier, :class:`~.daemon.CleaningService` owned every
piece of per-daemon state directly and the scheduler/worker/pool
reached back through the service object — workable for one daemon per
process, but the fleet tests (and the ``serve-fleet --smoke`` lane)
stand up 3+ replicas in ONE process, so anything per-replica must live
on an explicit context object passed in, never reached through a
process-global (or implicitly-singular service) reference.  The
context carries:

- **identity** — ``replica_id`` (``--replica_id`` or minted), echoed on
  ``/healthz`` and every ``POST /jobs`` 202 so trace logs attribute
  jobs to replicas;
- **the job index** — the in-memory open-job table plus the
  idempotency-key map the fleet router's failover path relies on (a
  re-routed job re-submitted with the same ``idempotency_key`` dedupes
  against the accepted original instead of running twice);
- **the demotion state machine** — backend mode, consecutive dispatch
  failures, confirmed audit divergences (moved verbatim from the
  daemon; the count-then-demote transition stays atomic under one
  lock);
- **the drain flag** — set via ``POST /drain``; a draining replica
  refuses new admissions (503) and reports ``draining: true`` on
  ``/healthz`` so the router stops placing on it while it finishes
  accepted work.

The dispatch worker and warm pool are constructed from a context alone
(``DispatchWorker(ctx)`` / ``WarmPool(ctx, cap)``); the daemon keeps
only lifecycle (threads, HTTP server, scheduler wiring).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid

from iterative_cleaner_tpu.obs import flight, tracing
from iterative_cleaner_tpu.service.jobs import TERMINAL, Job, JobSpool
from iterative_cleaner_tpu.utils import backoff


class ServiceBusy(RuntimeError):
    """Admission refused: the open-job cap is reached, or the replica is
    draining (the API maps this to 503 + Retry-After).  The cap is the
    daemon's backpressure — every open job can hold one decoded f32
    cube on host, so unbounded admission would let a submission burst
    outrun the single dispatch thread and OOM."""


def new_replica_id() -> str:
    """Short stable-enough identity for one replica process; operators
    pin ``--replica_id`` in real fleets, tests and smoke runs mint."""
    return f"r-{uuid.uuid4().hex[:8]}"


class ReplicaContext:
    """Everything per-replica that more than one service component
    touches.  Constructed once per replica, before any thread starts;
    the daemon, worker, pool, and HTTP handlers all hold the same
    instance."""

    def __init__(self, serve_cfg, mesh=None) -> None:
        self.serve_cfg = serve_cfg
        self.clean_cfg = serve_cfg.clean
        self.replica_id = serve_cfg.replica_id or new_replica_id()
        self.spool = JobSpool(serve_cfg.spool_dir)
        self.mesh = mesh
        # Demotion state ("jax" | "numpy") is written by three paths
        # (startup liveness, the dispatch worker's note_dispatch_failure,
        # the shadow auditor's note_audit_divergence) and read everywhere:
        # one lock makes the count-then-demote transition atomic, so two
        # racing failure reports can neither lose an increment nor
        # double-fire the demotion side effects (flight dump, stderr).
        self._mode_lock = threading.Lock()
        self.backend_mode = self.clean_cfg.backend  # ict: guarded-by(self._mode_lock)
        self._consecutive_failures = 0  # ict: guarded-by(self._mode_lock)
        self._audit_divergences = 0  # ict: guarded-by(self._mode_lock)
        self.draining = False  # ict: guarded-by(self._mode_lock)
        # RLock, deliberately: the idempotency-map trim takes it lexically
        # (the ICT007 discipline) while its callers already hold it.
        self._jobs_lock = threading.RLock()
        self._jobs: dict[str, Job] = {}  # ict: guarded-by(self._jobs_lock)
        # idempotency key -> job id; survives retire() (the key must keep
        # deduping after the job turns terminal and leaves _jobs — the
        # spool manifest is the durable record the daemon resolves).
        self._idem: dict[str, str] = {}  # ict: guarded-by(self._jobs_lock)
        # Full-jitter retry schedule for this replica's dispatch ladder
        # (utils/backoff.py; ICT_BACKOFF_SEED makes it deterministic).
        self.backoff_rng = backoff.make_rng()
        # Device-level observability artifacts live under the spool (the
        # single-daemon flock already covers it).
        self.profile_root = os.path.join(serve_cfg.spool_dir, "profiles")
        self.flight_dir = os.path.join(serve_cfg.spool_dir, "flight")
        self.repro_dir = os.path.join(serve_cfg.spool_dir, "repro")
        # Content-addressed result cache (service/results_cache.py; keys
        # from ingest/cas.py): per-replica by construction — fleet tests
        # run several replicas per process, and one replica's cache must
        # not answer for another's config.  Persisted next to the job
        # index so a restart keeps answering yesterday's campaign.
        from iterative_cleaner_tpu.ingest import cas
        from iterative_cleaner_tpu.service.results_cache import ResultCache

        self.result_cache = ResultCache(
            getattr(serve_cfg, "result_cache", 0),
            root=os.path.join(serve_cfg.spool_dir, "results-cache"))
        # The replica's config/version salt, advertised on /healthz and
        # stamped on every manifest: the fleet router's cache only
        # answers a submission when every candidate replica agrees on it.
        self.cache_salt = cas.cache_salt(self.clean_cfg)
        # The cost-accounting ledger (obs/costs.py): per-replica by
        # construction (fleet tests run several replicas per process),
        # spool-persisted next to the job index so a restart resumes the
        # lifetime showback record.
        from iterative_cleaner_tpu.obs.costs import CostLedger

        self.cost_ledger = CostLedger(
            os.path.join(serve_cfg.spool_dir, "costs.json"),
            replica_id=self.replica_id)
        # The shadow auditor; assigned once by the daemon during start(),
        # before any worker thread runs.
        self.auditor = None

    # --- job index ---

    def admit(self, job: Job, idempotency_key: str = "") -> str | None:
        """Cap-check and insert under ONE lock hold (concurrent POST
        handler threads must not all pass the check before any inserts —
        the cap is the OOM backpressure).  Returns None when ``job`` was
        admitted, or the id of the already-admitted job holding the same
        idempotency key (the caller resolves it, possibly via the
        spool)."""
        with self._jobs_lock:
            if idempotency_key:
                known = self._idem.get(idempotency_key)
                if known is not None:
                    return known
            if self.serve_cfg.max_open_jobs:
                # retire() evicts terminal jobs, so this scan is O(open).
                open_n = sum(1 for j in self._jobs.values()
                             if j.state not in TERMINAL)
                if open_n >= self.serve_cfg.max_open_jobs:
                    tracing.count("service_jobs_refused")
                    raise ServiceBusy(
                        f"{open_n} open jobs at the --max_open_jobs cap "
                        f"({self.serve_cfg.max_open_jobs}); retry later")
            self._jobs[job.id] = job
            if idempotency_key:
                self._idem[idempotency_key] = job.id
                self._trim_idem_locked()
        return None

    def rollback(self, job: Job, idempotency_key: str = "") -> None:
        """Undo a failed admission (the spool save threw): a job that was
        never made durable is also never enqueued, so leaving it indexed
        would leak one max_open_jobs slot per failed save."""
        with self._jobs_lock:
            self._jobs.pop(job.id, None)
            if idempotency_key and self._idem.get(idempotency_key) == job.id:
                del self._idem[idempotency_key]

    def index(self, job: Job) -> None:
        """Insert without the cap check — the startup replay path (spool
        recovery runs before the API opens, so the cap can't be raced)."""
        with self._jobs_lock:
            self._jobs[job.id] = job
            if job.idem_key:
                self._idem[job.idem_key] = job.id
                self._trim_idem_locked()

    def remember_idem(self, job: Job) -> None:
        """Replay-time idempotency rebuild: terminal manifests keep their
        keys deduping across a replica restart (a router failover retry
        of a job that in fact finished must get the finished manifest,
        not a second run)."""
        if not job.idem_key:
            return
        with self._jobs_lock:
            self._idem.setdefault(job.idem_key, job.id)
            self._trim_idem_locked()

    def _trim_idem_locked(self) -> None:
        """Bound the idempotency map.  Keys must outlive retire() — but
        NOT the spool manifests they resolve to: beyond ``spool_keep``
        retained manifests a key can only dedupe onto a pruned job (an
        error anyway), so evicting the oldest non-open entries at that
        point keeps a continuous-traffic replica's memory bounded (the
        fleet router mints a key for EVERY submission) without ever
        dropping a key that still dedupes.  Takes the (reentrant) jobs
        lock itself so the eviction stays lexically guarded; every
        caller already holds it."""
        with self._jobs_lock:
            cap = max(int(self.serve_cfg.spool_keep), 0)
            excess = len(self._idem) - cap
            if excess <= 0:
                return
            evictable = sorted(
                (jid, key) for key, jid in self._idem.items()
                if jid not in self._jobs)   # open jobs keep their keys
            for _jid, key in evictable[:excess]:
                del self._idem[key]

    def idem_job_id(self, key: str) -> str | None:
        with self._jobs_lock:
            return self._idem.get(key)

    def lookup(self, job_id: str) -> Job | None:
        with self._jobs_lock:
            return self._jobs.get(job_id)

    def retire(self, job: Job) -> None:
        """Drop a terminal job from the in-memory index — the spool
        manifest is the durable record, so a continuous-traffic replica's
        memory stays bounded by OPEN work.  The idempotency mapping
        deliberately survives (see _idem)."""
        with self._jobs_lock:
            self._jobs.pop(job.id, None)

    def open_count(self) -> int:
        with self._jobs_lock:
            return sum(1 for j in self._jobs.values()
                       if j.state not in TERMINAL)

    def all_terminal(self) -> bool:
        with self._jobs_lock:
            return all(j.state in TERMINAL for j in self._jobs.values())

    # --- drain flag ---

    def set_draining(self, flag: bool) -> None:
        with self._mode_lock:
            self.draining = bool(flag)

    def is_draining(self) -> bool:
        with self._mode_lock:
            return self.draining

    # --- demotion state machine (moved verbatim from the daemon) ---

    def demote_for_liveness(self) -> None:
        """Startup-time demotion: backend liveness indeterminable after a
        hung probe (utils/device_probe.py) — the next jax call may hang
        the daemon."""
        with self._mode_lock:
            self.backend_mode = "numpy"

    def note_dispatch_ok(self) -> None:
        with self._mode_lock:
            self._consecutive_failures = 0

    def note_dispatch_failure(self, exc) -> None:
        # Count-then-demote under the mode lock (the worker and auditor
        # threads both reach the demotion transition); side effects fire
        # outside it, exactly once, on the thread that flipped the mode.
        with self._mode_lock:
            self._consecutive_failures += 1
            n_failures = self._consecutive_failures
            demote = (self.backend_mode == "jax"
                      and n_failures >= self.serve_cfg.demote_after)
            if demote:
                self.backend_mode = "numpy"
        if demote:
            tracing.count("service_backend_demotions")
            # The top rung of the fault ladder: dump the flight ring — the
            # post-mortem of what led to a service-wide demotion is worth
            # a file even when nobody configured telemetry.
            flight.note("service_demoted", error=str(exc),
                        replica_id=self.replica_id)
            flight.dump(f"service_demotion: {exc}", self.flight_dir)
            print(f"ict-serve[{self.replica_id}]: {n_failures} consecutive "
                  f"bucket dispatches failed (last: {exc}); demoting the "
                  "service to the numpy oracle backend", file=sys.stderr)

    def note_audit_divergence(self, record: dict) -> None:
        """The shadow auditor confirmed a served mask differed from the
        oracle.  Repeated confirmed divergences demote the service the
        same way repeated dispatch failures do: a route that keeps
        producing wrong masks is worse than a route that keeps
        crashing."""
        with self._mode_lock:
            self._audit_divergences += 1
            n_div = self._audit_divergences
            demote = (self.backend_mode == "jax"
                      and n_div >= self.serve_cfg.demote_after)
            if demote:
                self.backend_mode = "numpy"
        if demote:
            tracing.count("service_backend_demotions")
            flight.note("service_demoted_audit",
                        n_divergences=n_div,
                        job_id=record.get("job_id", ""),
                        replica_id=self.replica_id)
            flight.dump(f"audit_divergence_demotion: "
                        f"{n_div} confirmed divergences "
                        f"(last: job {record.get('job_id', '?')})",
                        self.flight_dir)
            print(f"ict-serve[{self.replica_id}]: {n_div} confirmed audit "
                  "divergences vs the numpy oracle; demoting the service "
                  "to the oracle backend (repro bundles under "
                  f"{self.repro_dir})", file=sys.stderr)

    # --- policy reads ---

    def audit_rate(self) -> float:
        """The effective shadow-audit sampling fraction: an explicit
        --audit_rate wins; < 0 honors ICT_AUDIT_RATE (default 0)."""
        from iterative_cleaner_tpu.obs import audit as obs_audit

        if self.serve_cfg.audit_rate >= 0:
            return min(self.serve_cfg.audit_rate, 1.0)
        return obs_audit.audit_rate()

    def new_job(self, path: str, profile: bool = False, audit: bool = False,
                idempotency_key: str = "", trace_id: str = "",
                tenant: str = "", synthetic: bool = False) -> Job:
        """Mint one job record.  The trace context is minted HERE unless
        the submitter carried one across the router hop (X-ICT-Trace) —
        either way it rides the job through every layer and is echoed in
        the 202 response."""
        from iterative_cleaner_tpu.obs import events
        from iterative_cleaner_tpu.service.jobs import new_job_id

        return Job(id=new_job_id(), path=path, submitted_s=time.time(),
                   trace_id=trace_id or events.new_trace_id(),
                   profile=bool(profile), audit=bool(audit),
                   idem_key=idempotency_key, tenant=tenant,
                   synthetic=bool(synthetic))
