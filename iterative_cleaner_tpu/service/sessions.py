"""Daemon-side streaming sessions: spool-backed lifecycle over OnlineSession.

Each session owns a directory under ``<spool>/sessions/<id>/``:

- ``meta.json`` — the SessionMeta the session was opened with (+ options);
- ``block_00000.npz`` … — every accepted block's VERBATIM upload bytes, in
  arrival order (the replay log);
- ``final.json`` — written at finish; its presence is the terminal marker.

Durability is replay, the jobs-spool philosophy applied to streams: blocks
are persisted atomically BEFORE they are ingested, so a daemon that dies
mid-stream loses at most the in-memory provisional state — the next daemon
indexes the directory at startup and lazily rebuilds the resident
:class:`OnlineSession` (re-ingesting the spooled blocks through the
identical path, deterministic) the first time the client touches the
session again.  Finalize itself is the canonical offline clean of the
assembled blocks, so a finish after restart produces the same
oracle-identical mask a never-restarted daemon would.

Provisional passes for DIFFERENT sessions are serialized by one pass lock:
concurrent HTTP handler threads must not stack device dispatches (the
dispatch-worker single-ownership rationale), and a bounded pass is short by
design.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.obs import events, tracing
from iterative_cleaner_tpu.online.blocks import decode_block
from iterative_cleaner_tpu.online.session import (
    DEFAULT_ALERT_ITERS,
    OnlineSession,
)
from iterative_cleaner_tpu.online.state import SessionMeta
from iterative_cleaner_tpu.service.jobs import new_job_id

_ID_RE = re.compile(r"^[0-9]{13}-[0-9a-f]{8}$")
_BLOCK_RE = re.compile(r"^block_(\d{5,})\.npz$")


class UnknownSession(KeyError):
    """No such session (API → 404)."""


class SessionClosed(ValueError):
    """Blocks/finish on an already-finished session (API → 409)."""


class SessionManager:
    def __init__(self, root: str, cfg: CleanConfig,
                 alert_iters: int = DEFAULT_ALERT_ITERS,
                 quiet: bool = False, cfg_provider=None) -> None:
        self.root = root
        self.cfg = cfg
        # ``cfg_provider`` re-resolves the config per touched session so a
        # runtime service-wide backend demotion (daemon.note_dispatch_
        # failure) reaches streaming passes too, not only the job routes.
        self._cfg = cfg_provider or (lambda: self.cfg)
        self.alert_iters = int(alert_iters)
        self.quiet = quiet
        os.makedirs(root, exist_ok=True)
        self._live: dict[str, OnlineSession] = {}  # ict: guarded-by(self._lock)
        self._out_paths: dict[str, str] = {}  # ict: guarded-by(self._lock)
        self._trace_ids: dict[str, str] = {}   # telemetry context per session  # ict: guarded-by(self._lock)
        self._lock = threading.Lock()          # the maps
        self._pass_lock = threading.Lock()     # device passes serialize
        self._locks: dict[str, threading.Lock] = {}  # per-session ordering

    # --- paths ---

    def _dir(self, sid: str) -> str:
        if not _ID_RE.match(sid or ""):
            # Ids come straight off the HTTP path (the jobs-spool traversal
            # rule): anything not shaped like our ids resolves to nothing.
            raise UnknownSession(sid)
        return os.path.join(self.root, sid)

    def _session_lock(self, sid: str) -> threading.Lock:
        with self._lock:
            return self._locks.setdefault(sid, threading.Lock())

    @staticmethod
    def _write_json(path: str, payload: dict) -> None:
        tmp = f"{path}.part"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)

    def _block_files(self, d: str) -> list[str]:
        try:
            names = sorted(n for n in os.listdir(d) if _BLOCK_RE.match(n))
        except OSError:
            raise UnknownSession(os.path.basename(d)) from None
        return [os.path.join(d, n) for n in names]

    # --- lifecycle ---

    def create(self, meta_dict: dict, out_path: str | None = None,
               alert_iters: int | None = None) -> dict:
        # Validate EVERYTHING before touching the disk: a refused open must
        # not leak a meta-less session directory that /healthz would count
        # as open forever.
        meta = SessionMeta.from_dict(meta_dict)   # ValueError → API 400
        iters = self.alert_iters if alert_iters is None else int(alert_iters)
        if iters < 1:
            raise ValueError(f"alert_iters must be >= 1, got {iters}")
        sid = new_job_id()
        # Streaming sessions are an entry point: the trace context is
        # minted at open, persisted in meta.json (so a restarted daemon
        # keeps the same trace), and echoed in every manifest response.
        trace_id = events.new_trace_id()
        d = os.path.join(self.root, sid)
        os.makedirs(d, exist_ok=True)
        self._write_json(os.path.join(d, "meta.json"), {
            "meta": meta.to_dict(),
            "out_path": out_path,
            "alert_iters": iters,
            "created_s": time.time(),
            "trace_id": trace_id,
        })
        with self._lock:
            self._live[sid] = OnlineSession(
                meta, self._cfg(), alert_iters=iters)
            if out_path:
                self._out_paths[sid] = out_path
            self._trace_ids[sid] = trace_id
        tracing.count("online_sessions_opened")
        if events.active():
            events.emit("session_opened", trace_id=trace_id, session_id=sid,
                        nchan=meta.nchan, nbin=meta.nbin)
        return self.manifest(sid)

    def _materialize(self, sid: str) -> OnlineSession:
        """The resident session — rebuilt from the spool (block replay)
        when this daemon has never touched it (restart resume)."""
        with self._lock:
            live = self._live.get(sid)
        if live is not None:
            return live
        d = self._dir(sid)
        try:
            with open(os.path.join(d, "meta.json")) as fh:
                saved = json.load(fh)
        except OSError:
            raise UnknownSession(sid) from None
        if os.path.exists(os.path.join(d, "final.json")):
            raise SessionClosed(f"session {sid} already finished")
        session = OnlineSession(
            SessionMeta.from_dict(saved["meta"]), self._cfg(),
            alert_iters=int(saved.get("alert_iters") or self.alert_iters))
        # replay_block appends without per-block provisional passes (the
        # alerts already fired in the previous life), so a long session's
        # restart costs slab copies, not blocks × device dispatches.
        n = 0
        for p in self._block_files(d):
            with open(p, "rb") as fh:
                data, weights = decode_block(fh.read())
            session.replay_block(data, weights)
            n += 1
        if n:
            tracing.count("online_blocks_replayed", n)
        with self._lock:
            # A concurrent materialize of the same sid may have won; keep
            # the first so block counters stay consistent.
            live = self._live.setdefault(sid, session)
            out = saved.get("out_path")
            if out:
                self._out_paths.setdefault(sid, out)
            self._trace_ids.setdefault(sid, saved.get("trace_id", ""))
        return live

    def _trace_id(self, sid: str) -> str:
        with self._lock:
            return self._trace_ids.get(sid, "")

    def add_block(self, sid: str, payload: bytes) -> dict:
        with self._session_lock(sid):
            session = self._materialize(sid)
            if session.finalized:
                raise SessionClosed(f"session {sid} already finished")
            # Re-resolve the config on every touch: a service-wide backend
            # demotion mid-stream must reach this session's next pass.
            session.cfg = self._cfg()
            data, weights = decode_block(payload)   # ValueError → 400
            d = self._dir(sid)
            idx = session.blocks_ingested
            p = os.path.join(d, f"block_{idx:05d}.npz")
            tmp = f"{p}.part"
            with self._pass_lock, events.trace_scope(self._trace_id(sid)):
                # The spooled copy lands only after ingest ACCEPTED the
                # block (ingest rolls its slab append back on any failure),
                # so spool and resident state can never diverge: crash
                # after ingest loses only advisory provisional state.
                # The trace scope threads the session's trace_id into the
                # ingest pass's per-block / per-iteration telemetry events.
                with open(tmp, "wb") as fh:
                    fh.write(payload)
                try:
                    alert = session.ingest(data, weights)
                except Exception:
                    os.remove(tmp)
                    raise
                os.replace(tmp, p)
            return alert.to_dict()

    def finish(self, sid: str) -> dict:
        from iterative_cleaner_tpu.driver import atomic_save
        from iterative_cleaner_tpu.io.npz import NpzIO

        with self._session_lock(sid):
            session = self._materialize(sid)
            if session.finalized:
                raise SessionClosed(f"session {sid} already finished")
            if session.blocks_ingested == 0:
                raise ValueError(f"session {sid} has no blocks to finalize")
            session.cfg = self._cfg()   # demotion reaches finalize too
            d = self._dir(sid)
            with self._pass_lock, events.trace_scope(self._trace_id(sid)), \
                    events.span("session_finalize", session_id=sid), \
                    tracing.phase("online_finalize"):
                fin = session.finalize()
            out_path = self._out_paths.get(sid) or os.path.join(d, "final.npz")
            atomic_save(NpzIO(), fin.output.cleaned, out_path)
            payload = dict(fin.to_dict(), out_path=out_path,
                           finished_s=time.time())
            self._write_json(os.path.join(d, "final.json"), payload)
            with self._lock:
                # The resident slabs are the big memory; drop them — the
                # manifest below is served from disk from here on.
                self._live.pop(sid, None)
            tracing.count("online_sessions_finished")
            return self.manifest(sid)

    # --- inspection ---

    def manifest(self, sid: str) -> dict:
        d = self._dir(sid)
        try:
            with open(os.path.join(d, "meta.json")) as fh:
                saved = json.load(fh)
        except OSError:
            raise UnknownSession(sid) from None
        out = {
            "id": sid,
            "state": "open",
            "blocks": len(self._block_files(d)),
            "alert_iters": saved.get("alert_iters"),
            "nchan": saved["meta"].get("nchan"),
            "nbin": saved["meta"].get("nbin"),
            "trace_id": saved.get("trace_id", ""),
        }
        with self._lock:
            live = self._live.get(sid)
        if live is not None:
            out["nsub"] = live.state.nsub
            out["provisional_rfi_frac"] = (
                float((live.state.prov_w == 0).mean())
                if live.state.prov_w.size else 0.0)
        try:
            with open(os.path.join(d, "final.json")) as fh:
                final = json.load(fh)
            out["state"] = "done"
            out.update(final)
        except OSError:
            pass
        return out

    def open_count(self) -> int:
        """Unfinished sessions on disk (the /healthz view — includes
        not-yet-rematerialized ones from a previous daemon life)."""
        try:
            sids = [n for n in os.listdir(self.root) if _ID_RE.match(n)]
        except OSError:
            return 0
        return sum(
            1 for s in sids
            if not os.path.exists(os.path.join(self.root, s, "final.json")))
