"""Dispatch worker: fault-isolated execution of flushed shape buckets.

One thread owns the device (JAX dispatch is not re-entrant across threads
without care, and the bucket executables serialize on the chip anyway); the
loader threads and the HTTP server stay responsive while it runs.  The
worker is constructed purely from a :class:`~.context.ReplicaContext`, so
fleet tests run several workers in one process without shared state.  The
failure ladder, top to bottom:

1. a job whose archive fails to DECODE never reaches this worker — the
   loader marks it ``error`` alone (the parallel/batch isolation rule);
2. a sharded bucket dispatch that throws is retried with full-jitter
   exponential backoff (``dispatch_retries`` / ``retry_backoff_s``,
   utils/backoff.py — jittered so replicas recovering together don't
   thundering-herd the spool; the dev-tunnel failure mode is a transient
   RPC error on first contact, bench.py learned this in r01);
3. retries exhausted: every still-unfinished job in the bucket degrades to
   the numpy ORACLE backend, individually — slower, but masks are the
   oracle's by definition, and one poisoned cube cannot take its bucket
   siblings down;
4. repeated bucket failures demote the whole replica to oracle mode
   (context.note_dispatch_failure), the serving analog of the CLI's
   wedged-tunnel CPU demotion (utils/device_probe.py).
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time

import numpy as np

from iterative_cleaner_tpu.obs import (
    audit as obs_audit,
    costs as obs_costs,
    events,
    flight,
    forensics,
    memory as obs_memory,
    profiling,
    quality as obs_quality,
    tracing,
)
from iterative_cleaner_tpu.service.jobs import TERMINAL, Job
from iterative_cleaner_tpu.service.scheduler import Entry, bucket_label
from iterative_cleaner_tpu.utils import backoff

_STOP = object()


class DispatchWorker(threading.Thread):
    """Consumes entry groups (same-shape buckets) from the scheduler."""

    def __init__(self, ctx) -> None:
        super().__init__(daemon=True,
                         name=f"ict-serve-dispatch-{ctx.replica_id}")
        self.ctx = ctx
        self._q: queue.Queue = queue.Queue()

    def submit(self, entries: list[Entry]) -> None:
        self._q.put(entries)

    def queue_depth(self) -> int:
        """Flushed-but-undispatched bucket count (the /healthz drain view)."""
        return self._q.qsize()

    def stop(self) -> None:
        self._q.put(_STOP)

    def run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            try:
                self._dispatch(item)
            except Exception as exc:  # noqa: BLE001 — the thread must live
                for e in item:
                    if e.job.state not in TERMINAL:
                        self._fail(e.job, f"dispatch worker error: {exc}")

    # --- the failure ladder ---

    def _dispatch(self, entries: list[Entry]) -> None:
        ctx = self.ctx
        # The content-cache rung runs FIRST: a cube whose bytes + config
        # hash to a known key is served from the cached mask — the
        # sibling misses still share one coalesced dispatch below.
        entries = self._serve_cached(entries)
        if not entries:
            return
        for e in entries:
            e.job.state = "running"
            ctx.spool.save(e.job)
            if events.active():
                events.emit("dispatch", trace_id=e.job.trace_id,
                            job_id=e.job.id, bucket_size=len(entries),
                            backend=ctx.backend_mode)
        # Per-job profiler capture (obs/profiling): requested at submit
        # time, taken around this bucket's whole dispatch (device work is
        # bucket-granular — the capture necessarily covers the siblings
        # too, which the artifact dir's job tag makes plain).  Skipped
        # silently when the profiler is busy with an operator capture.
        want_profile = [e for e in entries if e.job.profile]
        with profiling.maybe_capture(
                ctx.profile_root,
                tag=want_profile[0].job.id if want_profile else "",
                want=bool(want_profile)) as profile_dir:
            if profile_dir:
                for e in want_profile:
                    e.job.profile_dir = profile_dir
            self._dispatch_routed(entries)

    def _serve_cached(self, entries: list[Entry]) -> list[Entry]:
        """Content-addressed reuse (service/results_cache.py, keys from
        ingest/cas.py): serve every entry whose cube key has a cached
        mask — byte-identical to a fresh clean by construction (the key
        covers cube bytes + config + code version) with zero device
        work — and return the misses for the coalesced dispatch.  A hit
        is only shadow-audited on explicit request (``{"audit": true}``
        replays the oracle against the cached mask); sampled audits stay
        on the freshly-cleaned routes."""
        ctx = self.ctx
        if not ctx.result_cache.enabled:
            return entries
        misses: list[Entry] = []
        for e in entries:
            if e.job.state in TERMINAL:
                continue
            bucket = bucket_label(e.D.shape)
            rec = (ctx.result_cache.get(e.job.content_key)
                   if e.job.content_key else None)
            if rec is None:
                tracing.count("service_result_cache_misses")
                tracing.count_labeled("result_cache_total",
                                      {"outcome": "miss",
                                       "shape_bucket": bucket})
                misses.append(e)
                continue
            e.job.state = "running"
            ctx.spool.save(e.job)
            tracing.count("service_result_cache_hits")
            tracing.count_labeled("result_cache_total",
                                  {"outcome": "hit",
                                   "shape_bucket": bucket})
            # Bytes that never crossed to (or through) a device because
            # of this hit — the campaign-dedupe savings figure.
            tracing.count("service_result_cache_bytes_saved",
                          float(e.D.nbytes))
            if events.active():
                events.emit("dispatch", trace_id=e.job.trace_id,
                            job_id=e.job.id, bucket_size=1,
                            backend="cache",
                            origin_job_id=rec.get("origin_job_id", ""))
            # Cost accounting (obs/costs): a hit consumes no device time;
            # the avoided cost is the ORIGIN job's recorded figures (its
            # manifest outlives retire() in the spool; a pruned origin
            # just reads as zero avoided cost, never a guess).  The one
            # manifest read is noise next to the archive decode this hit
            # already paid in the loader.
            origin_id = str(rec.get("origin_job_id", "") or "")
            origin = ctx.spool.get(origin_id) if origin_id else None
            obs_costs.add_cache_hit(
                e.job, origin.cost if origin is not None else None)
            t0c = time.perf_counter()
            try:
                with tracing.phase("service_cache_emit"):
                    self._emit(e, rec["weights"], rec["loops"],
                               rec["converged"], rec["rfi_frac"], "cache",
                               termination=rec.get("termination") or "")
            except Exception as exc:  # noqa: BLE001 — isolate the one job
                self._fail(e.job, f"cache-hit emission failed: {exc}")
            finally:
                self._record_cost(e.job, phases={
                    "cache_emit": time.perf_counter() - t0c})
        return misses

    def _record_cost(self, job, phases: dict | None = None) -> None:
        """Finalize one TERMINAL job's CostRecord exactly once: stamp the
        trailing phase seconds, fold it into the replica ledger (which
        renders the ``ict_cost_*`` counters the fleet federates), and
        re-persist the manifest so the record rides it (the exec_analysis
        re-persist pattern — the terminal save already happened).  A job
        that is still open (mid-retry) is skipped; its accumulators keep
        growing until the attempt that finishes it."""
        if job.state not in TERMINAL or getattr(job, "_cost_recorded",
                                                False):
            return
        for phase, dt in (phases or {}).items():
            if dt:
                obs_costs.add_phase(job, phase, dt)
        obs_costs.finalize(job)
        job._cost_recorded = True
        try:
            self.ctx.cost_ledger.record(job.cost)
            self.ctx.spool.save(job)
        except Exception:  # noqa: BLE001 — accounting must not fail a
            pass           # job that already served its result

    def _dispatch_routed(self, entries: list[Entry]) -> None:
        ctx = self.ctx
        if ctx.backend_mode == "jax":
            err = self._try_sharded(entries)
            if err is None:
                return
            tracing.count("service_oracle_fallbacks")
            # A fault-ladder trip is exactly the moment the flight ring
            # exists for: persist what the daemon was doing (dispatches,
            # phase timings, retries) next to the spool.
            flight.dump(f"oracle_fallback: {err}", ctx.flight_dir)
            print(f"ict-serve: sharded dispatch failed after retries ({err}); "
                  f"serving {len(entries)} job(s) via the numpy oracle",
                  file=sys.stderr)
        # "oracle" = the configured numpy route; "oracle-fallback" = the
        # degraded one — an intentionally-numpy deployment must not raise
        # permanent fallback alarms.
        label = ("oracle" if ctx.clean_cfg.backend == "numpy"
                 else "oracle-fallback")
        for e in entries:
            if e.job.state not in TERMINAL:
                self._clean_oracle(e, label)

    def _try_sharded(self, entries: list[Entry]):
        """Bounded retry around one bucket dispatch; returns the final
        exception, or None on success.  Retry delays draw full jitter
        from the replica's private RNG (utils/backoff.py) so N replicas
        recovering from the same incident spread their re-contacts
        instead of herding — deterministic under ICT_BACKOFF_SEED."""
        ctx = self.ctx
        last = None
        for attempt in range(1 + ctx.serve_cfg.dispatch_retries):
            live = [e for e in entries if e.job.state not in TERMINAL]
            if not live:
                return None
            if attempt:
                tracing.count("service_dispatch_retries")
                time.sleep(backoff.full_jitter(
                    ctx.serve_cfg.retry_backoff_s, attempt - 1,
                    rng=ctx.backoff_rng))
            for e in live:
                e.job.attempts += 1
            try:
                self._dispatch_sharded(live)
                ctx.note_dispatch_ok()
                return None
            except Exception as exc:  # noqa: BLE001 — retried, then degraded
                last = exc
        ctx.note_dispatch_failure(last)
        return last

    def _dispatch_sharded(self, entries: list[Entry]) -> None:
        """One stacked bucket on the mesh — literally the directory-batch
        dispatcher (_finish_bucket: note_compiled_shape bounding, bad-parts
        sweep, per-item emission), fed from the admission queue instead of
        a directory listing."""
        from iterative_cleaner_tpu.parallel.batch import (
            BatchItem,
            _finish_bucket,
        )

        ctx = self.ctx
        items = [BatchItem(path=e.job.path, archive=e.archive)
                 for e in entries]
        Db = np.stack([e.D for e in entries])
        w0b = np.stack([e.w0 for e in entries])
        # Coalescing accounting (the throughput-tier rung): the realized
        # batch size per shape bucket, as a low-cardinality labeled
        # counter (k is pow2-bounded by the scheduler, O(log cap) values
        # per shape) — federated into /fleet/metrics, rendered as a
        # per-bucket batch-size p50 by tools/fleet_top.py.
        tracing.count_labeled("coalesce_batch_size_total",
                              {"shape_bucket": bucket_label(Db.shape[1:]),
                               "k": str(len(entries))})
        if len(entries) > 1:
            tracing.count("service_coalesced_dispatches")
            tracing.count("service_coalesced_jobs", float(len(entries)))

        emit_s = [0.0]

        def on_item(i, item) -> None:
            # Emission failures are per-job: they must neither abort the
            # bucket loop for the sibling jobs nor read as a (retryable)
            # dispatch failure.
            t0 = time.perf_counter()
            try:
                self._emit(entries[i], item.weights, item.loops,
                           item.converged, item.rfi_frac, "sharded",
                           iterations=item.iterations,
                           termination=item.termination,
                           emit_iteration_events=True,
                           scores=item.test_results)
            except Exception as exc:  # noqa: BLE001 — isolate the one job
                self._fail(entries[i].job, f"output emission failed: {exc}")
            finally:
                dt = time.perf_counter() - t0
                emit_s[0] += dt
                tracing.observe_phase("service_emit", dt)
                obs_costs.add_phase(entries[i].job, "emit", dt)

        # Compile-accounting baseline for this dispatch's cost
        # attribution: any backend compile the window pays (the jit
        # compiles run synchronously on this thread) is apportioned
        # across the bucket's member jobs.  Best-effort in multi-replica
        # single-process tests (the listener's counters are
        # process-global); exact in the one-replica-per-process
        # production layout.
        compile_before = tracing.counters_snapshot().get(
            "jax_compile_s", 0.0)
        t0 = time.perf_counter()
        ok = False
        try:
            _finish_bucket(items, list(range(len(items))), Db, w0b,
                           ctx.clean_cfg, ctx.mesh, on_item=on_item,
                           # The per-job iteration timeline (GET /jobs/<id>/
                           # trace) costs a history fetch per bucket; pay it
                           # only when the operator turned forensics on.
                           want_history=forensics.timeline_enabled())
            ok = True
        finally:
            # _finish_bucket calls on_item inline, so subtract the emission
            # seconds: the per-stage means (_s/_n) must not double-count
            # I/O time as device-dispatch time.  try/finally so FAILED
            # dispatches count too (tracing.phase's rule) — a backend
            # incident must not make the mean dispatch latency look healthy,
            # and error=True makes the failure RATE visible on /metrics
            # (service_dispatch_err_n — the fallback-ladder alarm).
            dispatch_s = time.perf_counter() - t0 - emit_s[0]
            tracing.observe_phase("service_dispatch", dispatch_s,
                                  error=not ok)
            # Cost attribution (obs/costs): the EXACT seconds the line
            # above recorded, split equally across the bucket's member
            # jobs — failed attempts included, so the per-replica
            # conservation invariant (Σ attributed device-seconds ==
            # Δict_service_dispatch_s) holds by construction.
            compile_s = max(tracing.counters_snapshot().get(
                "jax_compile_s", 0.0) - compile_before, 0.0)
            obs_costs.add_dispatch_share([e.job for e in entries],
                                         dispatch_s, compile_s)
            if not ok:
                # A raised dispatch can still have emitted some items
                # terminal (a partial-emission edge): record those NOW —
                # the retry drops them from `live`, so the success path
                # below would never see them again.
                for e in entries:
                    self._record_cost(e.job)
            # Peak HBM attributable to the service's batched route, read
            # while this dispatch is the freshest thing in the stats.
            obs_memory.observe_route("sharded_batch")
        # XLA's static cost/memory accounting of this bucket's executable,
        # memoized per shape bucket (obs/memory; ICT_EXEC_ANALYSIS=0 opts
        # out), AFTER the device work: the analysis AOT compile must delay
        # telemetry, never the jobs.  Manifests were already written
        # terminal by on_item, so the analysis — and the finalized
        # CostRecord, bytes/FLOPs apportioned across the K members with
        # the batch's attainment ratio — is re-persisted onto them
        # (GET /jobs/<id> falls back to the spool after retire()).
        analysis = obs_memory.analyze_batch_route(Db.shape, ctx.clean_cfg)
        if analysis:
            obs_costs.add_exec_share([e.job for e in entries], analysis,
                                     dispatch_s)
            for e in entries:
                e.job.exec_analysis = analysis
        for e in entries:
            self._record_cost(e.job)
            if analysis and not getattr(e.job, "_cost_recorded", False):
                # Open jobs (mid-retry emission failure edge) still get
                # the analysis persisted, the historical behavior.
                try:
                    ctx.spool.save(e.job)
                except Exception:  # noqa: BLE001 — telemetry must not fail
                    pass           # a job that already served its result

    def _clean_oracle(self, e: Entry, served_by: str = "oracle-fallback") -> None:
        """The numpy-oracle route, one job at a time (isolated).  Runs
        inside the job's trace scope, so the core loop's per-iteration
        telemetry events carry the job's trace_id."""
        from iterative_cleaner_tpu.core.cleaner import clean_cube
        from iterative_cleaner_tpu.parallel.batch import finalize_weights

        ctx = self.ctx
        t0 = time.perf_counter()
        try:
            with events.trace_scope(e.job.trace_id), \
                    tracing.phase("service_oracle"):
                cfg = ctx.clean_cfg.replace(backend="numpy")
                res = clean_cube(e.D, e.w0, cfg)
                final_w, rfi = finalize_weights(res.weights, cfg)
                self._emit(e, final_w, res.loops, res.converged, rfi,
                           served_by, iterations=res.iterations,
                           termination=res.termination,
                           scores=res.test_results)
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            self._fail(e.job, str(exc))
        finally:
            # Oracle wall seconds are HOST cost, recorded as their own
            # phase — never device_s (the conservation invariant is
            # against ict_service_dispatch_s alone; a degraded job keeps
            # whatever failed-attempt dispatch share it accumulated).
            self._record_cost(e.job, phases={
                "oracle": time.perf_counter() - t0})

    # --- terminal transitions ---

    def _emit(self, e: Entry, weights, loops, converged, rfi_frac,
              served_by: str, iterations=None, termination: str = "",
              emit_iteration_events: bool = False, scores=None) -> None:
        """``iterations``/``termination`` land on the job manifest as the
        forensics timeline; ``emit_iteration_events`` additionally writes
        them to the event log (the batched route's post-hoc equivalent of
        the core loop's inline per-iteration events — the oracle route
        already emitted inline under its trace scope, so it passes False).
        ``scores`` is the route's last-iteration test scores, handed to the
        shadow auditor for the ulp-drift check."""
        from iterative_cleaner_tpu.driver import atomic_save, output_name
        from iterative_cleaner_tpu.io.base import get_io
        from iterative_cleaner_tpu.models.surgical import apply_output_policy

        ctx = self.ctx
        job = e.job
        cleaned = apply_output_policy(e.archive, np.asarray(weights), ctx.clean_cfg)
        o_name = output_name(ctx.clean_cfg, e.archive, job.path)
        atomic_save(get_io(job.path), cleaned, o_name)
        job.out_path = o_name
        job.loops = int(loops)
        job.converged = bool(converged)
        job.rfi_frac = float(rfi_frac)
        job.served_by = served_by
        job.termination = termination
        if iterations:
            job.timeline = [forensics.iteration_record(i) for i in iterations]
            if emit_iteration_events and events.active():
                for rec in job.timeline:
                    events.emit("iteration", trace_id=job.trace_id,
                                job_id=job.id, **rec)
        # RFI data-quality telemetry (obs/quality.py): the served mask's
        # zap fraction, occupancy histograms, and termination/attribution
        # mix, on the manifest and as /metrics counters — a drifting
        # receiver shows up as a metric anomaly, not a mystery.
        job.quality = obs_quality.quality_summary(
            np.asarray(weights), termination=termination)
        obs_quality.record_job_quality(job.quality, timeline=job.timeline)
        # Store-through into the content cache: every freshly-cleaned
        # result (sharded or oracle — masks are identical by the parity
        # invariant) becomes the answer for the next byte-identical
        # submission.  Cache-served jobs are not re-stored.
        if served_by != "cache" and job.content_key:
            ctx.result_cache.put(
                job.content_key, np.asarray(weights), loops=job.loops,
                converged=job.converged, rfi_frac=job.rfi_frac,
                termination=termination, origin_job_id=job.id)
        # Shadow-oracle audit (obs/audit.py): sampled (ICT_AUDIT_RATE) or
        # per-job requested jobs are offered to the background auditor
        # BEFORE the terminal transition below, so "every job is terminal"
        # (drain) implies "every due audit is at least queued" — the drain
        # + auditor.drain sequence the smoke check and tests rely on.  The
        # queue keeps the cube arrays alive past the release below; a full
        # queue skips, never blocks.  Jobs the oracle itself served are
        # only audited on explicit request — a sampled replay of the
        # oracle against the oracle proves nothing.
        auditor = ctx.auditor
        if (auditor is not None
                and (job.audit or served_by == "sharded")
                and obs_audit.should_audit(job.audit, ctx.audit_rate())):
            auditor.submit(job, e.D, e.w0, np.asarray(weights), scores,
                           served_by, ctx.clean_cfg)
        job.finished_s = time.time()
        # Persist the done-stamped manifest BEFORE the in-memory state
        # flips: drain() keys off ``job.state``, so flipping first opens a
        # window where "every job is terminal" is true while the spool
        # still says "running" — a reader (or a crash) in that window sees
        # a served job without its quality/profile fields (observed as a
        # test flake).  A copy carries the stamp; the shared field refs
        # are only read for serialization.
        ctx.spool.save(dataclasses.replace(job, state="done"))
        job.state = "done"
        ctx.retire(job)
        tracing.count("service_jobs_done")
        tracing.count_labeled("jobs_served_total", {"route": served_by})
        if events.active():
            events.emit("job_done", trace_id=job.trace_id, job_id=job.id,
                        served_by=served_by, loops=job.loops,
                        termination=termination,
                        rfi_frac=round(job.rfi_frac, 6))
        # Release the decoded cube — steady-state host residency stays
        # bounded by the admission queue, not the job history.
        e.archive = e.D = e.w0 = None

    def _fail(self, job: Job, msg: str) -> None:
        """Terminal error transition.  Must NEVER raise: it is the last
        resort of the dispatch and loader threads, and a spool write that
        fails (disk full, spool dir removed) would otherwise kill the only
        dispatch thread while /healthz keeps reporting ok."""
        job.state = "error"
        job.error = msg
        job.finished_s = time.time()
        if events.active():
            events.emit("job_error", trace_id=job.trace_id, job_id=job.id,
                        error=msg)
        try:
            self.ctx.spool.save(job)
            self.ctx.retire(job)
        except Exception as exc:  # noqa: BLE001 — keep the job in memory:
            # with the manifest unwritten, the in-memory record is the only
            # true view of its state (GET /jobs/<id> reads it first).
            tracing.count("service_spool_save_errors")
            print(f"ict-serve: spool save failed for job {job.id}: {exc}",
                  file=sys.stderr)
        tracing.count("service_jobs_error")
        trace = f" trace={job.trace_id}" if job.trace_id else ""
        print(f"ict-serve: job {job.id} ({job.path}){trace} failed: {msg}",
              file=sys.stderr)
