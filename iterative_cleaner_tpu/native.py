"""ctypes bindings for the native host runtime (native/ict_native.cc).

Builds on demand with ``make -C native`` (g++ + OpenMP); everything degrades
to the pure-numpy path when the toolchain or library is unavailable, so the
framework never hard-depends on the native layer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, "_native", "libict_native.so")
_NATIVE_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "native")

_lock = threading.Lock()
_lib = None
_tried = False

STATE_TO_ENUM = {"Intensity": 0, "Stokes": 1, "Coherence": 2}
ENUM_TO_STATE = {v: k for k, v in STATE_TO_ENUM.items()}


class IctbHeader(ctypes.Structure):
    _fields_ = [
        ("magic", ctypes.c_uint32),
        ("version", ctypes.c_uint32),
        ("nsub", ctypes.c_uint32),
        ("npol", ctypes.c_uint32),
        ("nchan", ctypes.c_uint32),
        ("nbin", ctypes.c_uint32),
        ("centre_frequency", ctypes.c_double),
        ("dm", ctypes.c_double),
        ("period", ctypes.c_double),
        ("mjd_start", ctypes.c_double),
        ("mjd_end", ctypes.c_double),
        ("state", ctypes.c_uint32),
        ("dedispersed", ctypes.c_uint32),
        ("source", ctypes.c_char * 64),
    ]


def _build() -> bool:
    if not os.path.isdir(_NATIVE_SRC_DIR):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_SRC_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except Exception:  # noqa: BLE001 — missing toolchain: fall back to numpy
        return False


def get_lib():
    """The loaded library, building it first if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        lib = ctypes.CDLL(_SO_PATH)
        u32, f32p = ctypes.c_uint32, ctypes.POINTER(ctypes.c_float)
        f64p, i32p = ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32)
        hp = ctypes.POINTER(IctbHeader)
        lib.ictb_save.argtypes = [ctypes.c_char_p, hp, f64p, f32p, f32p]
        lib.ictb_save.restype = ctypes.c_int
        lib.ictb_load_header.argtypes = [ctypes.c_char_p, hp]
        lib.ictb_load_header.restype = ctypes.c_int
        lib.ictb_load.argtypes = [ctypes.c_char_p, hp, f64p, f32p, f32p]
        lib.ictb_load.restype = ctypes.c_int
        lib.ict_preprocess.argtypes = [
            f32p, f32p, i32p, u32, u32, u32, u32, u32, u32, f32p]
        lib.ict_preprocess.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def save_ictb(path: str, archive) -> None:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++ toolchain?)")
    h = IctbHeader(
        nsub=archive.nsub, npol=archive.npol, nchan=archive.nchan,
        nbin=archive.nbin, centre_frequency=archive.centre_frequency,
        dm=archive.dm, period=archive.period, mjd_start=archive.mjd_start,
        mjd_end=archive.mjd_end, state=STATE_TO_ENUM[archive.state],
        dedispersed=int(archive.dedispersed),
        source=archive.source.encode()[:63],
    )
    data = np.ascontiguousarray(archive.data, np.float32)
    weights = np.ascontiguousarray(archive.weights, np.float32)
    freqs = np.ascontiguousarray(archive.freqs, np.float64)
    rc = lib.ictb_save(
        path.encode(), ctypes.byref(h),
        freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _fptr(weights), _fptr(data))
    if rc != 0:
        raise OSError(f"ictb_save({path}) failed with rc={rc}")


def load_ictb(path: str):
    from iterative_cleaner_tpu.io.base import Archive

    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++ toolchain?)")
    h = IctbHeader()
    rc = lib.ictb_load_header(path.encode(), ctypes.byref(h))
    if rc != 0:
        raise OSError(f"ictb_load_header({path}) failed with rc={rc}")
    freqs = np.empty(h.nchan, np.float64)
    weights = np.empty((h.nsub, h.nchan), np.float32)
    data = np.empty((h.nsub, h.npol, h.nchan, h.nbin), np.float32)
    rc = lib.ictb_load(
        path.encode(), ctypes.byref(h),
        freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _fptr(weights), _fptr(data))
    if rc != 0:
        raise OSError(f"ictb_load({path}) failed with rc={rc}")
    return Archive(
        data=data, weights=weights, freqs=freqs,
        centre_frequency=h.centre_frequency, dm=h.dm, period=h.period,
        source=h.source.decode(errors="replace"),
        mjd_start=h.mjd_start, mjd_end=h.mjd_end,
        state=ENUM_TO_STATE[h.state], dedispersed=bool(h.dedispersed),
        filename=path,
    )


def preprocess_native(archive) -> tuple[np.ndarray, np.ndarray] | None:
    """Native pscrunch+dedisperse+baseline; None if the library is missing.
    Bit-matches ops.preprocess.preprocess (both accumulate baselines in f64)."""
    from iterative_cleaner_tpu.ops.preprocess import (
        BASELINE_FRAC,
        dispersion_shifts,
    )

    lib = get_lib()
    if lib is None:
        return None
    nsub, npol, nchan, nbin = archive.data.shape
    # load_ictb fills a header first; ictb_load revalidates dims against it,
    # so the buffers allocated here can never be overflowed by a file that
    # changed on disk in between.
    shifts = (
        dispersion_shifts(
            archive.freqs, archive.dm, archive.period, nbin, archive.centre_frequency
        )
        if not archive.dedispersed
        else np.zeros(nchan, np.int64)
    ).astype(np.int32)
    width = max(1, int(round(BASELINE_FRAC * nbin)))
    data = np.ascontiguousarray(archive.data, np.float32)
    # Always a fresh copy: w0 is the frozen original weights (§8.L11) and
    # must not alias archive.weights (the numpy path's astype also copies).
    w0 = np.array(archive.weights, dtype=np.float32, copy=True)
    out = np.empty((nsub, nchan, nbin), np.float32)
    rc = lib.ict_preprocess(
        _fptr(data), _fptr(w0),
        shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nsub, npol, nchan, nbin, STATE_TO_ENUM[archive.state], width, _fptr(out))
    if rc != 0:
        raise RuntimeError(f"ict_preprocess failed with rc={rc}")
    return out, w0
