from iterative_cleaner_tpu.io.base import Archive, ArchiveIO, get_io

__all__ = ["Archive", "ArchiveIO", "get_io"]
