"""Seeded synthetic pulsar archives with injected RFI.

The reference has no test fixtures (SURVEY.md §4); this module is the
framework's replacement: reproducible Gaussian-noise cubes with a folded pulse
plus the RFI morphologies the surgical cleaner targets — per-profile spikes,
DC offsets, broadband (whole-subint) bursts, narrowband (whole-channel)
contamination — and optional pre-zapped weights.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from iterative_cleaner_tpu.io.base import (
    Archive,
    STATE_COHERENCE,
    STATE_INTENSITY,
    STATE_STOKES,
)


@dataclass(frozen=True)
class RFISpec:
    """Which RFI morphologies to inject and how hard."""

    n_profile_spikes: int = 4       # isolated (subint, chan) impulse RFI
    n_dc_profiles: int = 3          # isolated profiles with a DC offset
    n_bad_channels: int = 1         # persistent narrowband channels
    n_bad_subints: int = 1          # broadband bursts across a whole subint
    n_prezapped: int = 2            # profiles with weight already 0 on load
    amplitude: float = 40.0         # RFI strength in units of noise sigma


#: make_archive's default injection mix (a frozen spec, so one shared
#: instance is safe — and keeps the call out of the argument default,
#: where a later mutable refactor would silently share state: ruff B008).
_DEFAULT_RFI = RFISpec()


def pulse_profile(nbin: int, width_frac: float = 0.03, phase: float = 0.30) -> np.ndarray:
    """A Gaussian pulse template in phase bins."""
    x = np.arange(nbin, dtype=np.float64) / nbin
    w = max(width_frac, 1.5 / nbin)
    d = x - phase
    d -= np.round(d)  # circular distance
    return np.exp(-0.5 * (d / w) ** 2)


def make_archive(
    nsub: int = 8,
    nchan: int = 64,
    nbin: int = 256,
    npol: int = 1,
    seed: int = 0,
    snr: float = 25.0,
    rfi: RFISpec | None = _DEFAULT_RFI,
    dm: float = 12.455,
    period: float = 0.714,
    centre_frequency: float = 149.0,
    bandwidth: float = 78.125,
    dispersed: bool = True,
    noise_sigma: float = 1.0,
    state: str | None = None,
) -> Archive:
    """Build a seeded synthetic archive.

    The pulse is injected per channel at its dispersed phase (when
    ``dispersed``), so the dedispersion op has something real to undo; channel
    gains vary smoothly to exercise the per-channel scalers.

    ``state`` defaults by npol the way real archives come: 1 → Intensity,
    2 → Coherence (pscrunch sums AA+BB), 4 → Stokes (total intensity is
    pol 0) — so multi-pol end-to-end tests exercise the real pscrunch
    arithmetic, not the Intensity passthrough.
    """
    if state is None:
        state = {1: STATE_INTENSITY, 2: STATE_COHERENCE}.get(npol, STATE_STOKES)
    rng = np.random.default_rng(seed)
    freqs = centre_frequency + bandwidth * (np.arange(nchan) / nchan - 0.5)

    prof = pulse_profile(nbin)
    gains = 1.0 + 0.3 * np.sin(np.linspace(0, 3.1, nchan))  # smooth bandpass
    amp = snr * noise_sigma / max(np.sqrt(prof.sum()), 1e-9)

    cube = rng.normal(0.0, noise_sigma, size=(nsub, npol, nchan, nbin))
    from iterative_cleaner_tpu.ops.preprocess import dispersion_shifts

    shifts = dispersion_shifts(freqs, dm, period, nbin, centre_frequency) if dispersed else np.zeros(nchan, int)
    for c in range(nchan):
        # Disperse = inverse of the dedispersion roll (roll_cube(x, s) is
        # np.roll(x, -s), so the dispersed profile is np.roll(prof, +s)).
        chan_prof = np.roll(prof, int(shifts[c])) * amp * gains[c]
        cube[:, :, c, :] += chan_prof

    weights = np.ones((nsub, nchan), dtype=np.float32)
    # Mild weight variation: the reference multiplies data by raw (not
    # boolean) weights (iterative_cleaner.py:290-296), so tests must see
    # non-unit weights.
    weights *= (0.8 + 0.4 * rng.random((nsub, nchan))).astype(np.float32)

    if rfi is not None:
        a = rfi.amplitude * noise_sigma
        for _ in range(rfi.n_profile_spikes):
            s, c, b = rng.integers(nsub), rng.integers(nchan), rng.integers(nbin)
            cube[s, :, c, b] += a * (2.0 + rng.random())
        for _ in range(rfi.n_dc_profiles):
            s, c = rng.integers(nsub), rng.integers(nchan)
            cube[s, :, c, :] += a * 0.4
        for _ in range(rfi.n_bad_channels):
            c = rng.integers(nchan)
            cube[:, :, c, :] += rng.normal(0, a * 0.3, size=(nsub, npol, 1, nbin))[:, :, 0, :]
        for _ in range(rfi.n_bad_subints):
            s = rng.integers(nsub)
            cube[s, :, :, :] += rng.normal(0, a * 0.3, size=(npol, nchan, nbin))
        for _ in range(rfi.n_prezapped):
            s, c = rng.integers(nsub), rng.integers(nchan)
            weights[s, c] = 0.0

    return Archive(
        data=cube.astype(np.float32),
        weights=weights,
        freqs=freqs,
        centre_frequency=float(centre_frequency),
        dm=float(dm) if dispersed else 0.0,
        period=float(period),
        source="J0000+0000",
        mjd_start=60500.0,
        mjd_end=60500.0 + nsub * 10.0 / 86400.0,
        state=state,
        dedispersed=not dispersed,
        filename=f"synthetic_seed{seed}",
    )
