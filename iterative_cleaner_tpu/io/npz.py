"""NPZ archive backend — the canonical, hermetic file format.

Stores exactly the fields of :class:`..io.base.Archive`.  This is the format
all unit tests and benchmarks run against (SURVEY.md §4.3: "a fake archive-I/O
backend (NPZ: cube + weights + metadata) so the full CLI runs hermetically").
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.io.base import Archive


class NpzIO:
    def load(self, path: str) -> Archive:
        with np.load(path, allow_pickle=False) as z:
            return Archive(
                data=np.asarray(z["data"], dtype=np.float32),
                weights=np.asarray(z["weights"], dtype=np.float32),
                freqs=np.asarray(z["freqs"], dtype=np.float64),
                centre_frequency=float(z["centre_frequency"]),
                dm=float(z["dm"]),
                period=float(z["period"]),
                source=str(z["source"]),
                mjd_start=float(z["mjd_start"]),
                mjd_end=float(z["mjd_end"]),
                state=str(z["state"]),
                dedispersed=bool(z["dedispersed"]),
                filename=path,
            )

    def save(self, archive: Archive, path: str) -> None:
        # Write through a file object: np.savez with a *str* path appends
        # .npz to unfamiliar suffixes, which breaks the driver's
        # write-to-temp-then-rename (driver.atomic_save) for -o names.
        with open(path, "wb") as fh:
            self._savez(fh, archive)

    def _savez(self, fh, archive: Archive) -> None:
        np.savez_compressed(
            fh,
            data=archive.data.astype(np.float32),
            weights=archive.weights.astype(np.float32),
            freqs=np.asarray(archive.freqs, dtype=np.float64),
            centre_frequency=np.float64(archive.centre_frequency),
            dm=np.float64(archive.dm),
            period=np.float64(archive.period),
            source=np.str_(archive.source),
            mjd_start=np.float64(archive.mjd_start),
            mjd_end=np.float64(archive.mjd_end),
            state=np.str_(archive.state),
            dedispersed=np.bool_(archive.dedispersed),
        )
