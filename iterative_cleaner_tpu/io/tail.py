"""Tail a growing archive file: yield new subint blocks as they land.

Archive containers (.npz/.ictb) are not appendable, so an observatory-side
writer "grows" an archive by atomically REWRITING it with more subints (the
same write-then-rename idiom driver.atomic_save uses).  The tail reader
polls the file's (mtime, size) signature, reloads when it changes, and
yields only the subints beyond what it already delivered; end-of-stream is
either an explicit sentinel file (``<path>.eos`` — the writer's "observation
over" marker) or ``idle_timeout_s`` with no growth.

A reload that fails or shrinks is treated as a torn mid-rewrite read (a
non-atomic writer) and retried on the next poll rather than raised — only
the EOS-deadline load is allowed to fail loudly.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Iterator

from iterative_cleaner_tpu.io.base import Archive, get_io


def eos_sentinel(path: str) -> str:
    return f"{path}.eos"


def _signature(path: str) -> tuple | None:
    try:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)
    except OSError:
        return None


def tail_blocks(
    path: str,
    poll_s: float = 1.0,
    idle_timeout_s: float = 30.0,
    sleep: Callable[[float], None] | None = None,
) -> Iterator[tuple[Archive, int, int]]:
    """Yield ``(archive, lo, hi)`` for each newly-appeared subint range; the
    archive is the CURRENT full on-disk content (the last yield's archive is
    therefore the completed cube).  ``sleep`` is injectable so tests drive
    the loop deterministically.  Raises TimeoutError if the file never
    yields a single readable archive before the idle timeout."""
    if sleep is None:
        sleep = time.sleep
    io = get_io(path)
    known = 0
    last_sig: tuple | None = None
    last_growth = time.monotonic()
    while True:
        eos = os.path.exists(eos_sentinel(path))
        sig = _signature(path)
        if sig is not None and sig != last_sig:
            try:
                archive = io.load(path)
            except Exception:  # noqa: BLE001 — torn mid-rewrite read
                archive = None
                if eos:
                    raise  # the writer said done; a broken file is final
            if archive is not None:
                last_sig = sig
                if archive.nsub > known:
                    yield archive, known, archive.nsub
                    known = archive.nsub
                    last_growth = time.monotonic()
        if eos:
            return
        if time.monotonic() - last_growth >= idle_timeout_s:
            if known == 0:
                raise TimeoutError(
                    f"no readable archive at {path!r} within "
                    f"{idle_timeout_s:.1f}s")
            return
        sleep(poll_s)
