"""Archive data model + I/O protocol.

The reference talks to PSRCHIVE (C++ via SWIG) through 22 API methods
(SURVEY.md §2.3).  This module defines the host-side contract those methods
imply — an in-memory :class:`Archive` value plus an :class:`ArchiveIO`
load/save protocol — so the rest of the framework never touches a file format
directly.  Backends: NPZ (canonical, hermetic; :mod:`..io.npz`) and psrchive
(optional, real telescope data; :mod:`..io.psrchive_io`).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Protocol

import numpy as np

# PSRCHIVE polarization states we distinguish for pscrunch semantics.
STATE_INTENSITY = "Intensity"   # npol == 1, already total intensity
STATE_STOKES = "Stokes"         # I,Q,U,V — total intensity is pol 0
STATE_COHERENCE = "Coherence"   # AA,BB(,CR,CI) — total intensity is AA+BB


@dataclass
class Archive:
    """In-memory pulsar archive: the 4-D cube + weights + fold metadata.

    Equivalent of the PSRCHIVE Archive object surface the reference uses
    (``get_data``/``get_weights``/dims/metadata — SURVEY.md §2.3), as a plain
    value type.
    """

    data: np.ndarray            # (nsub, npol, nchan, nbin) float32
    weights: np.ndarray         # (nsub, nchan) float32
    freqs: np.ndarray           # (nchan,) channel centre frequencies, MHz
    centre_frequency: float     # MHz
    dm: float                   # pc cm^-3
    period: float               # folding period, seconds
    source: str = "SYNTH"
    mjd_start: float = 60000.0
    mjd_end: float = 60000.0
    state: str = STATE_INTENSITY
    dedispersed: bool = False   # True once inter-channel delays are removed
    filename: str = "archive"

    def __post_init__(self) -> None:
        if self.data.ndim != 4:
            raise ValueError(f"data must be 4-D (nsub,npol,nchan,nbin), got {self.data.shape}")
        nsub, _npol, nchan, _nbin = self.data.shape
        if self.weights.shape != (nsub, nchan):
            raise ValueError(
                f"weights shape {self.weights.shape} != (nsub, nchan) = {(nsub, nchan)}")
        if self.freqs.shape != (nchan,):
            raise ValueError(f"freqs shape {self.freqs.shape} != ({nchan},)")

    # --- dims (reference get_nsubint/get_nchan/get_nbin) ---
    @property
    def nsub(self) -> int:
        return self.data.shape[0]

    @property
    def npol(self) -> int:
        return self.data.shape[1]

    @property
    def nchan(self) -> int:
        return self.data.shape[2]

    @property
    def nbin(self) -> int:
        return self.data.shape[3]

    @property
    def mjd_mid(self) -> float:
        # Reference 'std' naming uses the mid-MJD (iterative_cleaner.py:52).
        return 0.5 * (self.mjd_start + self.mjd_end)

    def copy(self) -> "Archive":
        return replace(
            self,
            data=self.data.copy(),
            weights=self.weights.copy(),
            freqs=self.freqs.copy(),
        )


class ArchiveIO(Protocol):
    """Load/save protocol — the host I/O layer the driver dispatches through."""

    def load(self, path: str) -> Archive: ...

    def save(self, archive: Archive, path: str) -> None: ...


def _npz_io():
    from iterative_cleaner_tpu.io.npz import NpzIO

    return NpzIO()


def _ictb_io():
    from iterative_cleaner_tpu.io.ictb import IctbIO

    return IctbIO()


def _psrchive_io():
    from iterative_cleaner_tpu.io.psrchive_io import PsrchiveIO

    return PsrchiveIO()


# Single source of truth for extension routing — the driver derives output
# extensions from the same table (anything unlisted is a PSRCHIVE .ar path).
EXTENSION_IO = {
    ".npz": _npz_io,
    ".ictb": _ictb_io,
}
DEFAULT_EXT = ".ar"


def known_extension(path: str) -> str:
    for ext in EXTENSION_IO:
        if path.endswith(ext):
            return ext
    return DEFAULT_EXT


def get_io(path: str) -> "ArchiveIO":
    """Pick an I/O backend from the file extension."""
    ext = known_extension(path)
    if ext in EXTENSION_IO:
        return EXTENSION_IO[ext]()
    return _psrchive_io()
