"""Native binary archive backend (.ictb) — the fast data-loader path.

Flat binary layout written/read by the C++ runtime (native/ict_native.cc):
no compression, one sequential read, threaded batch loading.  Orders of
magnitude faster to decode than .npz for the GB-scale cubes the TPU pipeline
streams.
"""

from __future__ import annotations

from iterative_cleaner_tpu import native
from iterative_cleaner_tpu.io.base import Archive


class IctbIO:
    def __init__(self) -> None:
        if not native.available():
            raise ImportError(
                "native library unavailable; build it with `make -C native` "
                "(needs g++) or use the .npz backend")

    def load(self, path: str) -> Archive:
        return native.load_ictb(path)

    def save(self, archive: Archive, path: str) -> None:
        native.save_ictb(path, archive)
