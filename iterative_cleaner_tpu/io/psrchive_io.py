"""Optional PSRCHIVE archive backend.

Bridges real telescope archives into the framework when the (Python-2-era,
often unavailable) ``psrchive`` SWIG bindings are importable.  Covers the
reference's PSRCHIVE API surface (SURVEY.md §2.3): load/unload, data + weight
extraction, metadata for output naming, and weight write-back on save.

This module is import-safe without psrchive; constructing :class:`PsrchiveIO`
raises a clear error instead.  The backend logic itself is exercised
hermetically by ``tests/test_psrchive_io.py`` against
``tests/fake_psrchive.py``, which implements exactly this object surface.
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.io.base import (
    Archive,
    STATE_COHERENCE,
    STATE_INTENSITY,
    STATE_STOKES,
)

try:  # pragma: no cover - psrchive unavailable in the hermetic environment
    import psrchive as _psr
except Exception:  # noqa: BLE001
    _psr = None


def psrchive_available() -> bool:
    return _psr is not None


class PsrchiveIO:
    def __init__(self) -> None:
        if _psr is None:
            raise ImportError(
                "psrchive python bindings are not available; use the .npz "
                "backend (iterative_cleaner_tpu.io.npz) instead")

    def load(self, path: str) -> Archive:
        ar = _psr.Archive_load(path)
        data = np.asarray(ar.get_data(), dtype=np.float32)
        weights = np.asarray(ar.get_weights(), dtype=np.float32)
        freqs = np.array(
            [ar.get_Integration(0).get_centre_frequency(c) for c in range(ar.get_nchan())],
            dtype=np.float64,
        )
        state = str(ar.get_state())
        if state not in (STATE_INTENSITY, STATE_STOKES, STATE_COHERENCE):
            state = STATE_STOKES if ar.get_npol() > 1 else STATE_INTENSITY
        return Archive(
            data=data,
            weights=weights,
            freqs=freqs,
            centre_frequency=float(ar.get_centre_frequency()),
            dm=float(ar.get_dispersion_measure()),
            period=float(ar.get_Integration(0).get_folding_period()),
            source=str(ar.get_source()),
            mjd_start=float(ar.start_time().strtempo()),
            mjd_end=float(ar.end_time().strtempo()),
            state=state,
            dedispersed=bool(ar.get_dedispersed()),
            filename=path,
        )

    def save(self, archive: Archive, path: str) -> None:
        # Re-open the source file and write the (possibly updated) weights and
        # amplitudes back through the PSRCHIVE object model, mirroring the
        # reference's set_weights_archive + unload flow
        # (iterative_cleaner.py:299-304, 59).
        #
        # The classic SWIG bindings expose bulk READS (get_data,
        # get_weights) but no bulk setters — the only write paths are
        # per-profile get_amps() view assignment (what the reference itself
        # does, iterative_cleaner.py:271) and per-cell
        # Integration.set_weight.  So instead of nsub*nchan*npol
        # unconditional round-trips (4.2 M at north-star scale — the exact
        # interpreter-call pathology this project removes), diff against
        # the freshly-loaded source and touch only cells that changed:
        # the common weights-only output costs one bulk read + ~zapped-count
        # set_weight calls, and the residual path (every profile rewritten)
        # is the only case that pays the full per-profile write.
        ar = _psr.Archive_load(archive.filename)
        nsub, npol, nchan, _ = archive.data.shape
        if ar.get_npol() != npol:
            if npol != 1:
                raise ValueError(
                    f"cannot write {npol}-pol data into a "
                    f"{ar.get_npol()}-pol source archive")
            ar.pscrunch()

        src_w = np.asarray(ar.get_weights(), dtype=np.float32)
        new_w = np.asarray(archive.weights, dtype=np.float32)
        integ = None
        last_isub = -1
        for isub, ichan in np.argwhere(src_w != new_w):
            if isub != last_isub:  # argwhere is row-major: one fetch per subint
                integ = ar.get_Integration(int(isub))
                last_isub = isub
            integ.set_weight(int(ichan), float(new_w[isub, ichan]))

        src_data = np.asarray(ar.get_data(), dtype=np.float32)
        new_data = np.asarray(archive.data, dtype=np.float32)
        # One comparison pass decides both "anything to do?" and "which
        # profiles" (NaN compares unequal to itself, so NaN-bearing profiles
        # are conservatively rewritten — harmless).
        changed = np.any(src_data != new_data, axis=3)
        for isub, ipol, ichan in np.argwhere(changed):
            prof = ar.get_Profile(int(isub), int(ipol), int(ichan))
            prof.get_amps()[:] = new_data[isub, ipol, ichan]
        ar.unload(path)
