"""Invariant-aware static analysis for the iterative_cleaner_tpu tree.

Three layers, one finding vocabulary (docs/ANALYSIS.md):

- :mod:`.rules` + :mod:`.bench_cfg` — AST source lint over the project's
  load-bearing conventions (guarded backend init, mask-path determinism
  and dtype discipline, the bench.py JSON-on-every-exit contract, the
  Prometheus metric grammar, no numpy inside jit traces);
- :mod:`.races` — a static race detector for the threaded ``service/`` and
  ``obs/`` packages: module-global and lock-owning-class shared state must
  carry ``# ict: guarded-by(<lock>)`` annotations, annotated writes must
  happen under their lock, and the lock-acquisition graph must be
  cycle-free (lock-order inversions);
- :mod:`.contracts` — a jaxpr/HLO contract checker that traces each
  registered clean route (stepwise, fused, chunked, sharded) on a tiny
  cube and asserts no host callbacks, the expected dtype lattice (the jax
  side of the oracle's f64-promotion split stays uniformly 32-bit), and
  that the declared buffer-donation count survived lowering.

``tools/ict_lint.py`` is the CLI; findings are suppressible only through
the checked-in ``tools/ict_lint_baseline.json``.
"""

from iterative_cleaner_tpu.analysis.engine import (  # noqa: F401
    Finding,
    collect_project_files,
    load_baseline,
    parse_annotations,
)
