"""AST source rules: the project's load-bearing conventions, mechanised.

Rule catalog (docs/ANALYSIS.md has the full rationale + examples):

- **ICT001/device-init** — ``jax.devices()``-class calls (anything that can
  trigger first backend init: a wedged dev tunnel hangs it process-wide,
  the CLAUDE.md quirk) are allowed only in ``utils/device_probe.py``,
  lexically inside a ``with init_watchdog(...)`` block, or annotated
  ``# ict: backend-init-ok(<how it is guarded>)``.
- **ICT002/mask-f64** — no 64-bit float/complex dtypes in mask-affecting
  modules (``ops/``, ``core/``, ``parallel/``, ``online/finalize.py``)
  without ``# ict: f64-ok(<reason>)``: the oracle's numpy.ma f64 promotion
  is *its* defined behavior; the jax route must stay uniformly 32-bit or
  the masks drift (SURVEY §8.L9).
- **ICT003/mask-nondet** — no wall-clock (``time.time``) or RNG
  (``random``/``np.random``/``uuid``/``secrets``/``os.urandom``) calls in
  mask-affecting modules without ``# ict: nondet-ok(<reason>)``: replay
  determinism (spool resume, repro bundles, fuzz seeds) depends on the
  mask path being a pure function of (cube, weights, config).
- **ICT005/metric-name** — literal metric/phase names handed to the
  :mod:`..obs.tracing` registries must fit the Prometheus grammar once
  the ``ict_`` prefix lands (``[a-z][a-z0-9_]*``), and label keys
  likewise.
- **ICT005/metric-registration** — one family, one kind: a name used as
  both counter and gauge (or both flat and labeled) would render twice
  under the same ``ict_`` family on ``/metrics``; label-key sets per
  family must be consistent across call sites.
- **ICT006/numpy-in-jit** — no ``np.*`` *calls* inside jit-traced bodies
  (they run at trace time on tracers, forcing host transfers or silent
  constant-folding); dtype-object accesses (``np.float32`` & co.) are
  trace-time constants and stay allowed.

``ICT004/bench-exit`` (the bench.py CFG walk) lives in
:mod:`.bench_cfg`; the race rules (ICT007/ICT008) in :mod:`.races`.
"""

from __future__ import annotations

import ast
import re

from iterative_cleaner_tpu.analysis.engine import Finding, SourceFile

#: Modules whose code can affect a flag mask: every dtype / determinism
#: rule applies here (docs/PARITY.md's behavior matrix is the map).
MASK_MODULE_PREFIXES = (
    "iterative_cleaner_tpu/ops/",
    "iterative_cleaner_tpu/core/",
    "iterative_cleaner_tpu/parallel/",
)
MASK_MODULES_EXACT = (
    "iterative_cleaner_tpu/online/finalize.py",
    "iterative_cleaner_tpu/backends/jax_backend.py",
    "iterative_cleaner_tpu/backends/numpy_backend.py",
)

#: The one module allowed to touch backend init unguarded — it IS the guard.
DEVICE_INIT_ALLOWED = ("iterative_cleaner_tpu/utils/device_probe.py",)

#: Call attributes that can trigger first backend init.
BACKEND_INIT_ATTRS = {
    "devices", "local_devices", "device_count", "local_device_count",
    "default_backend", "process_index", "process_count",
}

METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
LABEL_KEY_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

#: tracing-registry entry points -> metric kind ("counter" / "gauge") and
#: whether the family takes labels.
REGISTRY_FNS = {
    "count": ("counter", False),
    "count_labeled": ("counter", True),
    "observe_phase": ("counter", False),
    "phase": ("counter", False),
    "set_gauge": ("gauge", False),
    "set_gauge_labeled": ("gauge", True),
    "max_gauge_labeled": ("gauge", True),
}

#: np.<attr> calls that are trace-time constants, fine inside jit.
NUMPY_TRACE_SAFE = {
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
    "complex128", "dtype", "finfo", "iinfo",
}

NONDET_EXACT = {"time.time", "time.time_ns", "os.urandom"}
NONDET_PREFIXES = ("random.", "numpy.random.", "uuid.", "secrets.")


def _import_canonical_map(tree: ast.AST) -> dict[str, str]:
    """alias -> canonical dotted prefix, so import style cannot evade a
    name-based rule: ``from time import time`` -> {'time': 'time.time'},
    ``import numpy.random as npr`` -> {'npr': 'numpy.random'},
    ``import numpy as np`` -> {'np': 'numpy'}."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}")
    return out


def _canonical_call_name(node: ast.Call, aliases: dict[str, str]) -> str:
    """The call target's dotted name with its leading alias resolved to
    the canonical module path ('' when unresolvable)."""
    name = dotted_name(node.func)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_mask_module(path: str) -> bool:
    return (path.startswith(MASK_MODULE_PREFIXES)
            or path in MASK_MODULES_EXACT)


# --- ICT001: guarded backend init ---


def _watchdog_guarded_lines(tree: ast.AST) -> set[int]:
    """Line numbers lexically inside a ``with init_watchdog(...)`` block."""
    guarded: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            call = item.context_expr
            if (isinstance(call, ast.Call)
                    and (dotted_name(call.func) or "").endswith(
                        "init_watchdog")):
                guarded.update(range(node.lineno, node.end_lineno + 1))
    return guarded


def rule_device_init(sf: SourceFile) -> list[Finding]:
    if sf.path in DEVICE_INIT_ALLOWED or sf.tree is None:
        return []
    guarded = _watchdog_guarded_lines(sf.tree)
    # Bare aliases too: `from jax import devices [as d]` must not evade
    # the rule by import style.
    bare_aliases: set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and (node.module or "").split(
                ".")[0] == "jax":
            for alias in node.names:
                if alias.name in BACKEND_INIT_ATTRS:
                    bare_aliases.add(alias.asname or alias.name)
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] not in bare_aliases:
                continue
        elif parts[-1] not in BACKEND_INIT_ATTRS or parts[0] not in (
                "jax", "xla_bridge", "_xb"):
            continue
        if node.lineno in guarded:
            continue
        if sf.annotation(node.lineno, "backend-init-ok") is not None:
            continue
        out.append(sf.finding(
            "ICT001/device-init", node.lineno,
            f"'{name}()' can trigger first backend init, which a wedged "
            f"device tunnel hangs process-wide (CLAUDE.md); guard it via "
            f"utils/device_probe.py (probe / init_watchdog / liveness "
            f"gate) and annotate '# ict: backend-init-ok(<guard>)'"))
    return out


# --- ICT002: no 64-bit floats on the mask path ---


_F64_NAMES = ("float64", "complex128")


def _string_dtype_64(node: ast.Call) -> str | None:
    """A 64-bit dtype smuggled in as a string: ``.astype("float64")``,
    ``dtype="float64"`` keywords, ``np.dtype("complex128")``."""
    def is64(n):
        return (isinstance(n, ast.Constant) and isinstance(n.value, str)
                and n.value in _F64_NAMES)

    callee = (node.func.attr if isinstance(node.func, ast.Attribute)
              else getattr(node.func, "id", ""))
    if callee in ("astype", "dtype", "view") and node.args and is64(node.args[0]):
        return node.args[0].value
    for kw in node.keywords:
        if kw.arg == "dtype" and is64(kw.value):
            return kw.value.value
    return None


def rule_mask_f64(sf: SourceFile) -> list[Finding]:
    if not is_mask_module(sf.path) or sf.tree is None:
        return []
    out = []
    for node in ast.walk(sf.tree):
        name = None
        if isinstance(node, ast.Attribute) and node.attr in _F64_NAMES:
            name = dotted_name(node) or node.attr
        elif isinstance(node, ast.Name) and node.id in _F64_NAMES:
            name = node.id
        elif isinstance(node, ast.Call):
            smuggled = _string_dtype_64(node)
            if smuggled is not None:
                name = f'"{smuggled}"'
        if name is None:
            continue
        if sf.annotation(node.lineno, "f64-ok") is not None:
            continue
        out.append(sf.finding(
            "ICT002/mask-f64", node.lineno,
            f"64-bit dtype '{name}' in a mask-affecting module: the jax "
            f"route must stay uniformly 32-bit for mask parity (SURVEY "
            f"§8.L9); if deliberate (oracle-side promotion, x64-gated), "
            f"annotate '# ict: f64-ok(<reason>)'"))
    return out


# --- ICT003: determinism of the mask path ---


def rule_mask_nondet(sf: SourceFile) -> list[Finding]:
    if not is_mask_module(sf.path) or sf.tree is None:
        return []
    aliases = _import_canonical_map(sf.tree)
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        # Canonicalized through the import table, so `from time import
        # time` / `import numpy.random as npr` cannot evade the rule.
        name = _canonical_call_name(node, aliases)
        if not (name in NONDET_EXACT
                or name.startswith(NONDET_PREFIXES)):
            continue
        if sf.annotation(node.lineno, "nondet-ok") is not None:
            continue
        out.append(sf.finding(
            "ICT003/mask-nondet", node.lineno,
            f"nondeterministic call '{name}()' in a mask-affecting "
            f"module: masks must be a pure function of (cube, weights, "
            f"config) for replay/resume/audit determinism; if it cannot "
            f"reach a mask, annotate '# ict: nondet-ok(<reason>)'"))
    return out


# --- ICT005: Prometheus metric grammar + single registration ---


def _registry_calls(sf: SourceFile):
    """Yield (node, fn_name, kind, labeled) for tracing-registry calls."""
    if sf.tree is None:
        return
    in_tracing = sf.path.endswith("obs/tracing.py")
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = None
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if (isinstance(base, ast.Name) and "tracing" in base.id
                    and node.func.attr in REGISTRY_FNS):
                fn = node.func.attr
        elif (isinstance(node.func, ast.Name) and in_tracing
                and node.func.id in REGISTRY_FNS):
            fn = node.func.id
        if fn is not None:
            kind, labeled = REGISTRY_FNS[fn]
            yield node, fn, kind, labeled


def rule_metric_grammar(sf: SourceFile) -> list[Finding]:
    out = []
    for node, fn, _kind, labeled in _registry_calls(sf):
        if not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if not METRIC_NAME_RE.match(first.value):
                out.append(sf.finding(
                    "ICT005/metric-name", node.lineno,
                    f"metric/phase name {first.value!r} (via {fn}) breaks "
                    f"the Prometheus grammar once prefixed 'ict_' — want "
                    f"[a-z][a-z0-9_]*"))
        if labeled and len(node.args) > 1 and isinstance(node.args[1], ast.Dict):
            for key in node.args[1].keys:
                if (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                        and not LABEL_KEY_RE.match(key.value)):
                    out.append(sf.finding(
                        "ICT005/metric-name", node.lineno,
                        f"label key {key.value!r} (via {fn}) breaks the "
                        f"Prometheus label grammar [a-z_][a-z0-9_]*"))
    return out


def rule_metric_registration(files: list[SourceFile]) -> list[Finding]:
    """Cross-file: one family name, one (kind, labeledness, label-key set).

    ``observe_phase``/``phase`` families are checked against each other
    and against flat counters (they share the ``ict_<name>_s/_n``
    namespace); a family seen as both counter and gauge, or both flat and
    labeled, would collide in the rendered exposition."""
    seen: dict[str, tuple[str, bool, tuple, SourceFile, int]] = {}
    out: list[Finding] = []
    for sf in files:
        for node, fn, kind, labeled in _registry_calls(sf):
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            keys: tuple = ()
            if labeled and len(node.args) > 1 and isinstance(
                    node.args[1], ast.Dict):
                keys = tuple(sorted(
                    k.value for k in node.args[1].keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)))
            prior = seen.get(name)
            if prior is None:
                seen[name] = (kind, labeled, keys, sf, node.lineno)
                continue
            pkind, plabeled, pkeys, psf, pline = prior
            if (kind, labeled) != (pkind, plabeled):
                out.append(sf.finding(
                    "ICT005/metric-registration", node.lineno,
                    f"metric family {name!r} registered as "
                    f"{'labeled ' if labeled else ''}{kind} here but as "
                    f"{'labeled ' if plabeled else ''}{pkind} at "
                    f"{psf.path}:{pline} — one family, one kind"))
            elif labeled and keys and pkeys and keys != pkeys:
                out.append(sf.finding(
                    "ICT005/metric-registration", node.lineno,
                    f"metric family {name!r} uses label keys "
                    f"{list(keys)} here but {list(pkeys)} at "
                    f"{psf.path}:{pline} — label sets must match"))
    return out


# --- ICT006: no numpy calls inside jit-traced bodies ---


def _jitted_functions(tree: ast.Module) -> list[ast.FunctionDef]:
    """Function defs that are jit entry points: decorated with jax.jit /
    partial(jax.jit, ...), or wrapped by a module-level
    ``x = jax.jit(f)`` / ``x = partial(jax.jit, ...)(f)`` assignment."""

    def mentions_jit(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "jit":
                return True
            if isinstance(sub, ast.Name) and sub.id == "jit":
                return True
        return False

    by_name: dict[str, ast.FunctionDef] = {}
    jitted: list[ast.FunctionDef] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
            if any(mentions_jit(d) for d in node.decorator_list):
                jitted.append(node)
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and mentions_jit(node.value.func)):
            for arg in node.value.args:
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    fn = by_name[arg.id]
                    if fn not in jitted:
                        jitted.append(fn)
    return jitted


def rule_numpy_in_jit(sf: SourceFile) -> list[Finding]:
    if sf.tree is None:
        return []
    out = []
    for fn in _jitted_functions(sf.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in ("np", "numpy")):
                continue
            if func.attr in NUMPY_TRACE_SAFE:
                continue
            out.append(sf.finding(
                "ICT006/numpy-in-jit", node.lineno,
                f"'np.{func.attr}()' inside jit-traced '{fn.name}': numpy "
                f"calls run at trace time (host transfer / silent "
                f"constant-folding on tracers) — use jnp, or hoist the "
                f"value out of the traced body"))
    return out


def run_source_rules(files: list[SourceFile]) -> list[Finding]:
    """Every per-file rule plus the cross-file registration check (the
    bench CFG rule rides along for bench.py — see :mod:`.bench_cfg`)."""
    from iterative_cleaner_tpu.analysis.bench_cfg import rule_bench_exit
    from iterative_cleaner_tpu.analysis.engine import malformed_annotations

    out: list[Finding] = []
    for sf in files:
        if sf.parse_error:
            out.append(sf.finding("ICT000/annotation-grammar", 1,
                                  f"file does not parse: {sf.parse_error}"))
            continue
        out.extend(malformed_annotations(sf))
        out.extend(rule_device_init(sf))
        out.extend(rule_mask_f64(sf))
        out.extend(rule_mask_nondet(sf))
        out.extend(rule_metric_grammar(sf))
        out.extend(rule_numpy_in_jit(sf))
        out.extend(rule_bench_exit(sf))
    out.extend(rule_metric_registration(
        [sf for sf in files if not sf.parse_error]))
    return out
