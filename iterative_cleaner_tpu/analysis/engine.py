"""Shared machinery of the analysis suite: findings, annotations, baseline.

A :class:`Finding` is one rule violation at one source location.  Its
``fingerprint`` is deliberately line-number-free (rule + path + the
stripped source line + the occurrence index of that line-text in the
file), so an unrelated edit above a baselined finding does not resurrect
it — the same stability trick ruff/mypy baselines use.

Annotations are trailing (or immediately-preceding) comments of the form
``# ict: <kind>(<argument>)``; the argument is mandatory — an annotation
without a reason or lock name documents nothing and is itself a finding.
Grammar (docs/ANALYSIS.md):

- ``# ict: guarded-by(<lock>)`` — this state is protected by ``<lock>``
  (``self._lock`` / module ``_lock`` / ``none: <reason>`` for
  deliberately lock-free state, e.g. GIL-atomic idempotent caches);
- ``# ict: backend-init-ok(<reason>)`` — this ``jax.devices()``-class
  call is guarded against the wedged-tunnel first-init hang;
- ``# ict: f64-ok(<reason>)`` — deliberate 64-bit float in a
  mask-affecting module (oracle-parity promotion, x64-gated);
- ``# ict: nondet-ok(<reason>)`` — deliberate wall-clock/RNG use in a
  mask-affecting module (telemetry only, never mask-affecting).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field

ANNOTATION_RE = re.compile(r"#\s*ict:\s*([a-z0-9-]+)\(([^)]*)\)")

#: Baseline suppressions live here (tools/ict_lint.py --baseline overrides).
DEFAULT_BASELINE = os.path.join("tools", "ict_lint_baseline.json")


@dataclass
class Finding:
    rule: str          # e.g. "ICT001/device-init"
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    message: str
    snippet: str = ""  # stripped source line (fingerprint input)
    occurrence: int = 0  # nth identical snippet in the file
    # Mechanical remedy (--fix): text appended to the flagged line.
    fix_append: str | None = None

    @property
    def fingerprint(self) -> str:
        basis = f"{self.rule}|{self.path}|{self.snippet}|{self.occurrence}"
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    """One parsed file handed to every rule: path, text, per-line
    annotations, and the AST (parsed once)."""

    path: str                       # repo-relative
    abspath: str
    text: str
    lines: list[str] = field(default_factory=list)
    annotations: dict[int, list[tuple[str, str]]] = field(default_factory=dict)
    tree: object | None = None      # ast.Module (None on syntax error)
    parse_error: str = ""

    def annotation(self, lineno: int, kind: str) -> str | None:
        """The argument of a ``kind`` annotation on ``lineno``, or on a
        comment-ONLY line directly above it (the two placements the
        grammar allows — a trailing comment on the *previous statement*
        must not leak onto this one); None when absent."""
        candidates = [lineno]
        above = lineno - 1
        if (1 <= above <= len(self.lines)
                and self.lines[above - 1].strip().startswith("#")):
            candidates.append(above)
        for ln in candidates:
            for k, arg in self.annotations.get(ln, ()):
                if k == kind:
                    return arg
        return None

    def snippet_at(self, lineno: int) -> tuple[str, int]:
        """(stripped line text, occurrence index) — the fingerprint basis."""
        if not (1 <= lineno <= len(self.lines)):
            return "", 0
        text = self.lines[lineno - 1].strip()
        occurrence = sum(
            1 for prior in self.lines[: lineno - 1] if prior.strip() == text)
        return text, occurrence

    def finding(self, rule: str, lineno: int, message: str,
                fix_append: str | None = None) -> Finding:
        snippet, occurrence = self.snippet_at(lineno)
        return Finding(rule=rule, path=self.path, line=lineno,
                       message=message, snippet=snippet,
                       occurrence=occurrence, fix_append=fix_append)


def parse_annotations(text: str) -> dict[int, list[tuple[str, str]]]:
    """Line -> [(kind, argument), ...] for every ``# ict:`` annotation.

    Parsed from raw source rather than the AST so annotations survive on
    lines the compiler drops (comment-only lines above an assignment)."""
    out: dict[int, list[tuple[str, str]]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        for match in ANNOTATION_RE.finditer(line):
            out.setdefault(i, []).append(
                (match.group(1), match.group(2).strip()))
    return out


def load_source_file(root: str, relpath: str) -> SourceFile:
    import ast

    abspath = os.path.join(root, relpath)
    with open(abspath, encoding="utf-8") as fh:
        text = fh.read()
    sf = SourceFile(path=relpath.replace(os.sep, "/"), abspath=abspath,
                    text=text, lines=text.splitlines(),
                    annotations=parse_annotations(text))
    try:
        sf.tree = ast.parse(text, filename=relpath)
    except SyntaxError as exc:  # surfaced as a finding by the runner
        sf.parse_error = str(exc)
    return sf


def collect_project_files(root: str, subset: list[str] | None = None) -> list[str]:
    """Repo-relative paths of every Python file the source layer lints:
    the package, bench.py, the driver entry, and tools/ (tests and
    fixtures lint themselves via pytest, not here)."""
    if subset:
        out = []
        for p in subset:
            rel = os.path.relpath(os.path.abspath(p), root)
            out.append(rel.replace(os.sep, "/"))
        return out
    found: list[str] = []
    for top in ("iterative_cleaner_tpu", "tools"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, top)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    found.append(rel.replace(os.sep, "/"))
    for name in ("bench.py", "__graft_entry__.py"):
        if os.path.exists(os.path.join(root, name)):
            found.append(name)
    return sorted(found)


def malformed_annotations(sf: SourceFile) -> list[Finding]:
    """An ``# ict:`` annotation with an empty argument documents nothing —
    the grammar makes the reason/lock mandatory."""
    out = []
    for lineno, anns in sorted(sf.annotations.items()):
        for kind, arg in anns:
            if not arg:
                out.append(sf.finding(
                    "ICT000/annotation-grammar", lineno,
                    f"annotation 'ict: {kind}(...)' needs a non-empty "
                    f"argument (a lock name or a reason)"))
            elif kind not in ("guarded-by", "backend-init-ok", "f64-ok",
                              "nondet-ok"):
                out.append(sf.finding(
                    "ICT000/annotation-grammar", lineno,
                    f"unknown annotation kind 'ict: {kind}(...)' "
                    f"(known: guarded-by, backend-init-ok, f64-ok, "
                    f"nondet-ok)"))
    return out


# --- baseline suppressions ---


def load_baseline(path: str) -> dict[str, dict]:
    """fingerprint -> entry.  A missing file is an empty baseline."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return {}
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: list[Finding]) -> None:
    entries = [
        {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
         "snippet": f.snippet,
         "note": "baselined by --write-baseline; justify or fix"}
        for f in findings
    ]
    payload = {
        "comment": "Baseline suppressions for tools/ict_lint.py.  Every "
                   "entry must carry a per-finding justification in its "
                   "'note'; prefer fixing or annotating over baselining "
                   "(docs/ANALYSIS.md).",
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")


def split_baselined(findings: list[Finding],
                    baseline: dict[str, dict]) -> tuple[list[Finding], list[Finding]]:
    """(fresh, suppressed) under the baseline."""
    fresh, suppressed = [], []
    for f in findings:
        (suppressed if f.fingerprint in baseline else fresh).append(f)
    return fresh, suppressed


def apply_fixes(root: str, findings: list[Finding]) -> int:
    """Apply mechanical remedies (``fix_append``): append the suggested
    annotation to each flagged line.  Returns how many lines changed."""
    by_file: dict[str, list[Finding]] = {}
    for f in findings:
        if f.fix_append:
            by_file.setdefault(f.path, []).append(f)
    changed = 0
    for relpath, group in by_file.items():
        abspath = os.path.join(root, relpath)
        with open(abspath, encoding="utf-8") as fh:
            lines = fh.read().splitlines(keepends=True)
        for f in sorted(group, key=lambda f: -f.line):
            idx = f.line - 1
            if idx >= len(lines) or f.fix_append in lines[idx]:
                continue
            stripped = lines[idx].rstrip("\n")
            lines[idx] = f"{stripped}  {f.fix_append}\n"
            changed += 1
        with open(abspath, "w", encoding="utf-8") as fh:
            fh.writelines(lines)
    return changed
